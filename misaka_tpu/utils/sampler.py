"""Always-on continuous profiler: a wall-clock stack sampler for the
whole process, served as flamegraph data at GET /debug/flamegraph.

The jax.profiler surface (utils/profiling.py) answers "what did the
DEVICE do during this capture window" and must be started by an
operator.  Production debugging usually starts from the other end:
"what is this process doing RIGHT NOW, and what was it doing for the
last few minutes" — with nobody having pressed record.  This module is
that: a daemon thread samples every Python thread's stack ~67 times a
second (stdlib ``sys._current_frames`` — one dict snapshot, no tracing
hooks, no per-call overhead on the code being profiled) and aggregates
the samples as FOLDED stacks (the Brendan Gregg flamegraph collapse
format: ``root;child;leaf count``), keyed by thread name so the serving
tiers (device loop, batcher workers, HTTP handlers, plane connections)
read as separate roots.

Sampling cost is engineered down to what an always-on profiler must be:
labels are cached per code object (no per-frame formatting), a PARKED
thread's fold is reused via leaf-frame identity (two attribute reads
instead of a stack walk — most threads on a serving box are parked at
any instant), and a duty-cycle governor measures each sample's wall cost
and stretches the period so the sampler itself stays under
``MISAKA_SAMPLER_BUDGET`` (default 2%) of one core no matter how many
threads the process runs — the nominal rate holds on normal boxes, a
pathological one samples slower instead of harder (the payload reports
``effective_hz`` next to ``rate_hz``).

Memory is bounded twice over: at most ``MISAKA_SAMPLER_MAX_STACKS``
distinct folded stacks are kept (new shapes beyond the cap aggregate
into ``(other)``), and every ``MISAKA_SAMPLER_DECAY_S`` seconds all
counts HALVE (dropping below 1 prunes the entry) — the aggregate is an
exponentially-decayed window over recent behavior, not an unbounded
since-boot integral, so "what is it doing now" stays answerable after a
week of uptime.

The C++ serving pool runs OUTSIDE the interpreter: while a pool call is
in flight the sampled Python stack parks at the ctypes call site
(cinterp._call), which tells you Python is waiting but not how busy the
C++ side actually is.  The payload therefore carries the pool's
MEASURED per-thread busy/idle nanosecond counters
(native/interpreter.cpp via core/native_serve.pool_counters) next to
the CPython aggregate — "time in the C++ pool" vs "time in CPython" is
one view, which is exactly the question a saturated box asks.

``GET /debug/flamegraph`` serves JSON ({folded, stacks, native_pool,
...}); ``?html=1`` serves a self-contained viewer (no external assets —
an air-gapped ops box renders it).  Kill switches: ``MISAKA_SAMPLER=0``
never starts the thread; stop()/start() toggle it live (the bench A/B
measures both sides).  Stdlib-only like the rest of the plane.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

DEFAULT_HZ = 67.0  # ~15ms period; prime-ish vs common 10/100ms loops
# The duty-cycle budget: the fraction of one core the sampler may spend
# on itself.  A sample's cost is O(threads x stack depth) and a serving
# box can run hundreds of threads; an always-on profiler must never
# become the workload, so the loop measures its own per-sample cost and
# stretches the period to stay under budget (the nominal rate holds on
# normal thread counts; a pathological box samples slower, not harder).
DEFAULT_BUDGET = 0.02


class StackSampler:
    """The sampling thread + folded-stack aggregate."""

    def __init__(self, hz: float = DEFAULT_HZ, max_stacks: int = 4096,
                 decay_s: float = 120.0, budget: float = DEFAULT_BUDGET):
        self.hz = max(1.0, min(250.0, float(hz)))
        self.max_stacks = max(16, int(max_stacks))
        self.decay_s = max(1.0, float(decay_s))
        self.budget = min(0.5, max(0.001, float(budget)))
        self._cost_ema = 0.0  # EMA of one sample's wall seconds
        self._lock = threading.Lock()
        self._stacks: dict[str, float] = {}
        # code object -> "name (file.py)" label cache: the walk must be
        # allocation-free per frame — formatting per frame per sample was
        # measured as a double-digit-% GIL tax on a 100+-thread serving
        # box (the A/B gate caught it).  Keyed by the code object itself
        # (stable, hashable); labels carry no line number so one function
        # is one cache entry.
        self._labels: dict = {}
        # thread ident -> name, refreshed only when an unknown ident
        # appears (threading.enumerate is O(threads) per call)
        self._names: dict[int, str] = {}
        # thread ident -> (leaf frame, f_lasti, folded str): a PARKED
        # thread (socket recv, lock wait, queue get — most of a serving
        # box at any instant) keeps the same leaf frame object at the
        # same instruction between samples, so its fold is reusable with
        # two attribute reads instead of a full stack walk.  A running
        # thread advances f_lasti and misses the cache, which is exactly
        # the set worth walking.  Holding the leaf frame pins one popped
        # chain per thread for at most one period — replaced on miss,
        # pruned when the ident disappears.
        self._fold_cache: dict[int, tuple] = {}
        self._samples = 0
        self._started_mono: float | None = None
        self._last_decay = time.monotonic()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # --- lifecycle ----------------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> None:
        if self.running:
            return
        self._stop.clear()
        self._started_mono = time.monotonic()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="misaka-sampler"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2)
        self._thread = None

    # --- the sampling loop --------------------------------------------------

    def _current_period(self) -> float:
        """The governed period: nominal 1/hz, stretched whenever one
        sample's measured cost would blow the duty-cycle budget."""
        return max(1.0 / self.hz, self._cost_ema / self.budget)

    def _loop(self) -> None:
        me = threading.get_ident()
        while not self._stop.wait(self._current_period()):
            t0 = time.perf_counter()
            try:
                self._sample_once(me)
            except Exception:  # pragma: no cover — a sampler crash must
                pass           # never take serving down with it
            dt = time.perf_counter() - t0
            self._cost_ema = (
                dt if self._cost_ema == 0.0
                else 0.8 * self._cost_ema + 0.2 * dt
            )

    def _sample_once(self, skip_ident: int) -> None:
        frames = sys._current_frames()
        labels = self._labels
        names = self._names
        cache = self._fold_cache
        folded: list[str] = []
        for ident, leaf in frames.items():
            if ident == skip_ident:
                continue  # the sampler must not profile itself
            lasti = leaf.f_lasti
            hit = cache.get(ident)
            if hit is not None and hit[0] is leaf and hit[1] == lasti:
                folded.append(hit[2])  # parked since last sample
                continue
            parts: list[str] = []
            frame = leaf
            depth = 0
            while frame is not None and depth < 64:
                code = frame.f_code
                label = labels.get(code)
                if label is None:
                    if len(labels) >= 32768:  # pathological code churn
                        labels.clear()
                    label = labels[code] = (
                        f"{code.co_name} "
                        f"({os.path.basename(code.co_filename)})"
                    )
                parts.append(label)
                frame = frame.f_back
                depth += 1
            name = names.get(ident)
            if name is None:
                self._names = names = {
                    t.ident: t.name for t in threading.enumerate()
                    if t.ident is not None
                }
                if ident not in names:
                    # cache the fallback too: a C-created thread running
                    # Python never registers with threading, and an
                    # uncached miss would rebuild the whole names dict
                    # on EVERY sample it is on-CPU
                    names[ident] = f"thread-{ident}"
                name = names[ident]
            parts.append(name)
            stack = ";".join(reversed(parts))
            cache[ident] = (leaf, lasti, stack)
            folded.append(stack)
        if len(cache) >= len(frames):
            # prune dead idents EVERY sample a dead entry exists (>=:
            # steady state is cache == frames - 1, the sampler's own
            # thread is sampled but never cached): a cached leaf frame
            # pins its whole chain (and every local in it) — an exited
            # worker's multi-MB locals must not live as long as the
            # always-on sampler does
            for ident in list(cache):
                if ident not in frames:
                    del cache[ident]
        now = time.monotonic()
        with self._lock:
            self._samples += 1
            for stack in folded:
                if stack in self._stacks:
                    self._stacks[stack] += 1
                elif len(self._stacks) < self.max_stacks:
                    self._stacks[stack] = 1
                else:
                    # cap reached: new stack shapes fold into one bucket
                    # (bounded memory beats completeness for an always-on
                    # profiler; decay frees slots over time)
                    self._stacks["(other)"] = \
                        self._stacks.get("(other)", 0) + 1
            if now - self._last_decay >= self.decay_s:
                self._last_decay = now
                for k in list(self._stacks):
                    half = self._stacks[k] / 2.0
                    if half < 1.0:
                        del self._stacks[k]
                    else:
                        self._stacks[k] = half

    # --- the read side ------------------------------------------------------

    def snapshot(self) -> tuple[dict[str, float], int]:
        with self._lock:
            return dict(self._stacks), self._samples

    @staticmethod
    def _fold(stacks: dict) -> str:
        return "\n".join(
            f"{stack} {int(round(count))}"
            for stack, count in sorted(
                stacks.items(), key=lambda kv: -kv[1]
            )
        )

    def folded(self) -> str:
        """The collapse-format text (``stack count`` per line) every
        flamegraph tool ingests (flamegraph.pl, speedscope, inferno)."""
        stacks, _ = self.snapshot()
        return self._fold(stacks)

    def payload(self) -> dict:
        stacks, samples = self.snapshot()
        out = {
            "enabled": True,
            "running": self.running,
            "rate_hz": self.hz,
            "effective_hz": round(1.0 / self._current_period(), 2),
            "budget": self.budget,
            "sample_cost_us": round(self._cost_ema * 1e6, 1),
            "samples": samples,
            "distinct_stacks": len(stacks),
            "max_stacks": self.max_stacks,
            "decay_s": self.decay_s,
            "uptime_s": round(
                time.monotonic() - self._started_mono, 3
            ) if self._started_mono is not None else 0.0,
            "stacks": {
                k: round(v, 2) for k, v in sorted(
                    stacks.items(), key=lambda kv: -kv[1]
                )
            },
            # folded from the SAME snapshot as "stacks" — a second
            # snapshot here could disagree with it mid-sample
            "folded": self._fold(stacks),
        }
        try:
            # the measured C++ split (None when no pool serves): "time in
            # the native pool" next to "time in CPython", one view
            from misaka_tpu.core import native_serve

            pool = native_serve.pool_counters()
            if pool is not None:
                out["native_pool"] = pool
        except Exception:  # pragma: no cover — payload must always answer
            pass
        return out


_lock = threading.Lock()
_sampler: StackSampler | None = None


def enabled(environ=os.environ) -> bool:
    return environ.get("MISAKA_SAMPLER", "1") != "0"


def get() -> StackSampler | None:
    return _sampler


def ensure_started(environ=os.environ) -> StackSampler | None:
    """Start (or return) the process-global sampler — called by
    make_http_server, so every serving process profiles itself from
    boot; library/test use never pays for a thread it didn't ask for.
    None when MISAKA_SAMPLER=0."""
    global _sampler
    if not enabled(environ):
        return None
    with _lock:
        if _sampler is None:
            try:
                hz = float(environ.get("MISAKA_SAMPLER_HZ", "") or DEFAULT_HZ)
            except ValueError:
                hz = DEFAULT_HZ
            try:
                max_stacks = int(
                    environ.get("MISAKA_SAMPLER_MAX_STACKS", "") or 4096
                )
            except ValueError:
                max_stacks = 4096
            try:
                decay_s = float(
                    environ.get("MISAKA_SAMPLER_DECAY_S", "") or 120.0
                )
            except ValueError:
                decay_s = 120.0
            try:
                budget = float(
                    environ.get("MISAKA_SAMPLER_BUDGET", "") or DEFAULT_BUDGET
                )
            except ValueError:
                budget = DEFAULT_BUDGET
            _sampler = StackSampler(
                hz=hz, max_stacks=max_stacks, decay_s=decay_s, budget=budget
            )
        if not _sampler.running:
            _sampler.start()
    return _sampler


def shutdown() -> None:
    """Stop the global sampler (tests; the A/B's off side)."""
    global _sampler
    with _lock:
        if _sampler is not None:
            _sampler.stop()
            _sampler = None


def debug_payload() -> dict:
    s = _sampler
    if s is None:
        return {
            "enabled": enabled(),
            "running": False,
            "stacks": {},
            "folded": "",
            "hint": "sampler not started (MISAKA_SAMPLER=0, or no HTTP "
                    "server in this process)",
        }
    return s.payload()


# --- the self-contained HTML viewer -----------------------------------------

_VIEWER = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>misaka flamegraph</title>
<style>
 body { font: 13px system-ui, sans-serif; margin: 16px; background: #fff; }
 h1 { font-size: 16px; } .meta { color: #555; margin-bottom: 8px; }
 .bar { height: 18px; margin-bottom: 10px; background: #eee; border-radius: 3px;
        overflow: hidden; max-width: 720px; }
 .bar > div { height: 100%%; background: #c0504d; float: left; }
 .frame { position: absolute; height: 17px; overflow: hidden;
          white-space: nowrap; font-size: 11px; line-height: 17px;
          border: 1px solid #fff; border-radius: 2px; cursor: default;
          text-overflow: ellipsis; padding: 0 2px; box-sizing: border-box; }
 #graph { position: relative; width: 100%%; }
</style></head><body>
<h1>misaka continuous profiler</h1>
<div class="meta" id="meta"></div>
<div class="meta" id="native"></div>
<div class="bar" id="nativebar" title="native pool busy fraction"></div>
<div id="graph"></div>
<script>
const DATA = %s;
const meta = document.getElementById('meta');
meta.textContent = `rate ${DATA.rate_hz} Hz | samples ${DATA.samples} | ` +
  `distinct stacks ${DATA.distinct_stacks} | decay ${DATA.decay_s}s`;
const np = DATA.native_pool;
if (np) {
  const frac = np.busy_fraction;
  const inline = np.caller_inline_ns || 0;
  document.getElementById('native').textContent =
    `native C++ pool: ${np.threads} threads, worker busy ` +
    `${(np.busy_ns/1e9).toFixed(2)}s + caller-inline ` +
    `${(inline/1e9).toFixed(2)}s vs idle ${(np.idle_ns/1e9).toFixed(2)}s ` +
    `(${(frac*100).toFixed(1)}%% busy)`;
  const fill = document.createElement('div');
  fill.style.width = (frac*100).toFixed(2) + '%%';
  document.getElementById('nativebar').appendChild(fill);
} else {
  document.getElementById('native').textContent =
    'native C++ pool: not serving';
  document.getElementById('nativebar').remove();
}
// Build a frame tree from the folded stacks and render it as nested
// proportional boxes (the flamegraph shape), depth growing downward.
const root = {name: 'all', value: 0, children: {}};
for (const [stack, count] of Object.entries(DATA.stacks)) {
  let node = root; root.value += count;
  for (const part of stack.split(';')) {
    if (!node.children[part])
      node.children[part] = {name: part, value: 0, children: {}};
    node = node.children[part];
    node.value += count;
  }
}
const ROW = 18, graph = document.getElementById('graph');
const palette = x => `hsl(${20 + 40 * x}, 70%%, 60%%)`;
let maxDepth = 0;
function render(node, x0, x1, depth) {
  maxDepth = Math.max(maxDepth, depth);
  let x = x0;
  const kids = Object.values(node.children)
    .sort((a, b) => b.value - a.value);
  for (const kid of kids) {
    const w = (x1 - x0) * kid.value / node.value;
    if (w > 0.0008) {
      const div = document.createElement('div');
      div.className = 'frame';
      div.style.left = (x * 100) + '%%';
      div.style.width = (w * 100) + '%%';
      div.style.top = (depth * ROW) + 'px';
      div.style.background = palette(Math.abs(
        kid.name.split('').reduce((h, c) => (h * 31 + c.charCodeAt(0)) %% 97, 7)
      ) / 97);
      div.textContent = kid.name;
      div.title = `${kid.name} — ${kid.value.toFixed(0)} samples ` +
        `(${(100 * kid.value / root.value).toFixed(1)}%% of all)`;
      graph.appendChild(div);
      render(kid, x, x + w, depth + 1);
    }
    x += w;
  }
}
if (root.value > 0) render(root, 0, 1, 0);
graph.style.height = ((maxDepth + 1) * ROW) + 'px';
</script></body></html>
"""


def render_html() -> str:
    """The GET /debug/flamegraph?html=1 body: the current payload baked
    into the self-contained viewer (no external assets)."""
    return _VIEWER % json.dumps(debug_payload())
