"""Bounded exponential backoff with jitter — the ONE retry-delay policy.

Three r9 retry loops need the same curve: node execute loops retrying a
failed RPC (transport/rpc.py), the frontend supervisor respawning dead
workers (runtime/frontends.py), and the HTTP client riding out a
server-restart window (client.py).  One implementation here (stdlib
only — two of those callers must never import grpc or jax) instead of
three hand-inlined copies drifting apart.

The policy: delay doubles from `base` up to `cap`, and every sleep is
jittered uniformly over [delay/2, delay] so a fleet of retriers
decorrelates instead of waking in lockstep.  The CAP is what "bounded"
means: retrying itself may be infinite (a node must outlive any peer
outage), but no single sleep exceeds `cap` seconds, so recovery latency
after the peer returns is bounded too.
"""

from __future__ import annotations

import random


class Backoff:
    """Stateful attempt counter over the shared delay curve; `delay_for`
    is the stateless form for callers that track their own streaks (the
    frontend supervisor's per-slot fast-crash counts)."""

    def __init__(self, base: float = 0.05, cap: float = 5.0,
                 factor: float = 2.0):
        if not (0 < base <= cap):
            raise ValueError(f"need 0 < base <= cap, got ({base}, {cap})")
        self.base = float(base)
        self.cap = float(cap)
        self.factor = float(factor)
        self.attempts = 0

    def delay_for(self, attempt: int) -> float:
        """The jittered sleep for a given zero-based attempt number."""
        delay = min(self.cap, self.base * self.factor ** max(0, attempt))
        return delay * (0.5 + 0.5 * random.random())

    def next_delay(self) -> float:
        """The next sleep in seconds (advances the attempt counter)."""
        delay = self.delay_for(self.attempts)
        self.attempts += 1
        return delay

    def reset(self) -> None:
        self.attempts = 0
