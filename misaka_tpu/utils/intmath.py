"""Integer math helpers — host-side parity with the reference's utils/math.go.

The reference exposes IntMax/IntMin/IntClamp (math.go:4-22), with clamp used
only by JRO (program.go:354,:362).  In the kernel that clamp is a dense
`jnp.clip` (core/step.py pc_jro); these host-side twins exist for tooling and
tests that need the exact same scalar semantics without importing jax.
"""

from __future__ import annotations


def int_max(a: int, b: int) -> int:
    """math.go:4-9."""
    return a if a > b else b


def int_min(a: int, b: int) -> int:
    """math.go:11-16."""
    return a if a < b else b


def int_clamp(v: int, lo: int, hi: int) -> int:
    """math.go:18-22 — clamp v into [lo, hi] (the JRO bound, program.go:354)."""
    return int_max(lo, int_min(v, hi))
