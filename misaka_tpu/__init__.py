"""misaka_tpu — a TPU-native rebuild of the Misaka Net distributed TIS-100 system.

The reference (jasmaa/misaka-net, mounted at /root/reference) is a MIMD actor
network: one OS process per node, gRPC+TLS unary RPC per transferred integer.
This package re-designs the same system TPU-first: the entire node graph is
compiled into ONE jitted SPMD superstep kernel in which

  * every program node  = a lane of a vmapped register file (ACC, BAK, PC, ports)
  * every stack node    = an HBM-resident (array, top) pair updated by scatter/gather
  * every inter-node MOV= dense one-hot routing (all arbitration is data-parallel)
  * master IN/OUT queues= device-resident ring buffers synced with the host in chunks
  * a batch axis vmaps N independent network instances for throughput
  * multi-chip scaling  = jax.sharding Mesh + shard_map with XLA collectives

Component map vs. the reference (SURVEY.md §2):
  C1 process entrypoint -> misaka_tpu.runtime.app (+ the `python -m misaka_tpu` CLI)
  C2 MasterNode         -> misaka_tpu.runtime.master
  C3 ProgramNode        -> lanes of misaka_tpu.core.step
  C4 StackNode          -> stack arrays in misaka_tpu.core.step
  C5 tokenizer          -> misaka_tpu.tis.parser (+ .lower/.disasm/.native, new)
  C6 IntStack           -> misaka_tpu.core.state stack arrays
  C7 gRPC transport     -> in-kernel routing + XLA collectives (misaka_tpu.parallel;
                           wire-compatible gRPC kept in .transport for per-process mode)
  C8 math utils         -> misaka_tpu.utils.intmath
  C9 build system       -> Makefile (native / grpc / cert / test / bench)
  C10 deployment        -> deploy/ (Dockerfile + fused & per-process compose)
  C11 docs              -> README.md, docs/NOTES.md

Beyond-parity subsystems (SURVEY.md §5 — the reference has none of these):
  tracing/profiling     -> misaka_tpu.utils.profiling (jax.profiler surface)
  instruction trace     -> misaka_tpu.core.trace (HBM ring + host decoder)
  debugger              -> misaka_tpu.debug (breakpoints, lane inspection)
  checkpoint/resume     -> runtime.master save/load_checkpoint + HTTP routes
  multi-host (DCN)      -> misaka_tpu.parallel.multihost (jax.distributed)
  compose migration     -> misaka_tpu.runtime.compose (run reference deploy files)
  native interpreter    -> misaka_tpu.core.cinterp (C++ superstep engine,
                           third differential implementation)
"""

__version__ = "0.1.0"

# Parent pid captured at the earliest importable moment — before any jax
# import gets a chance to spend seconds booting a backend.  If the launching
# shell dies during that boot, runtime/lifecycle.py compares getppid()
# against this to catch the orphaning (VERDICT r3 weak #1: leaked servers
# wedged the single-client TPU relay).
import os as _os

PPID_AT_IMPORT = _os.getppid()
