"""Interactive debugger: single-step a fused network, inspect every lane.

The reference's only debugging story is tailing per-instruction stdout logs
across N containers (program.go:222-223).  Because the TPU build keeps the
whole network's state in one pytree, a debugger is small: step the superstep
kernel one tick at a time, read registers/ports/stacks directly, break when a
lane reaches a program line.

Host-driven and deliberately unjitted across ticks (one traced_step per
tick), so breakpoints can be data-dependent without recompilation.  This is
the bring-up tool; production throughput lives in engine.run / fused_runner.

    dbg = Debugger(networks.add2())
    dbg.feed([5])
    dbg.add_breakpoint("misaka2", 2)       # PUSH ACC, misaka3
    hits = dbg.run(max_ticks=100)          # -> [("misaka2", 2)]
    dbg.inspect("misaka2")["acc"]          # -> 7
    print(dbg.listing("misaka2"))          # disasm with pc/breakpoint marks
"""

from __future__ import annotations

import numpy as np

from misaka_tpu.core.trace import decode_trace, format_trace, traced_step
from misaka_tpu.runtime.topology import Topology
from misaka_tpu.tis.disasm import disassemble_program


class Debugger:
    """Single-instance stepper over a compiled topology."""

    def __init__(self, topology: Topology, trace_cap: int = 256):
        self._top = topology
        self._net = topology.compile()
        self._lane_ids = topology.lane_ids()
        self._lane_names = list(self._lane_ids)
        self._stack_names = list(topology.stack_ids())
        self._state = self._net.init_state()
        self._trace = self._net.init_trace(trace_cap)
        # breakpoints: lane index -> set of program lines
        self._breaks: dict[int, set[int]] = {}
        # One compiled tick, reused every step (breakpoint checks stay on host).
        import jax

        self._step1 = jax.jit(traced_step)

    # --- control -----------------------------------------------------------

    def feed(self, values) -> int:
        """Queue client inputs; returns how many were accepted."""
        self._state, took = self._net.feed(self._state, list(values))
        return took

    def outputs(self) -> list[int]:
        """Drain anything the network has emitted."""
        self._state, outs = self._net.drain(self._state)
        return outs

    def reset(self) -> None:
        self._state = self._net.init_state()
        self._trace = self._net.init_trace(self._trace.buf.shape[1])

    def add_breakpoint(self, lane: str, line: int) -> None:
        idx = self._lane_index(lane)
        length = int(self._net.prog_len[idx])
        if not 0 <= line < length:
            raise ValueError(f"line {line} out of range for {lane} (len {length})")
        self._breaks.setdefault(idx, set()).add(line)

    def clear_breakpoints(self) -> None:
        self._breaks.clear()

    def step(self, ticks: int = 1) -> list[tuple[str, int]]:
        """Advance up to `ticks` supersteps; stops early on a breakpoint hit.

        Returns the breakpoint hits ([(lane_name, line)]) of the stopping
        tick, empty if the full count ran without a hit.
        """
        code, prog_len = self._net._tables
        for _ in range(ticks):
            self._state, self._trace = self._step1(
                code, prog_len, self._state, self._trace
            )
            hits = self._hits()
            if hits:
                return hits
        return []

    def run(self, max_ticks: int = 10_000) -> list[tuple[str, int]]:
        """Run until a breakpoint hit (or the tick budget); returns the hits."""
        return self.step(max_ticks)

    # --- inspection --------------------------------------------------------

    @property
    def tick(self) -> int:
        return int(self._state.tick)

    def inspect(self, lane: str) -> dict:
        """One lane's full architectural state."""
        i = self._lane_index(lane)
        s = self._state
        def full64(hi, lo):  # the true 64-bit register (core/regs64.py)
            return (int(hi) << 32) | (int(lo) & 0xFFFFFFFF)

        return {
            "acc": full64(s.acc_hi[i], s.acc[i]),
            "bak": full64(s.bak_hi[i], s.bak[i]),
            "pc": int(s.pc[i]),
            "ports": {
                f"R{k}": (int(s.port_val[i, k]) if bool(s.port_full[i, k]) else None)
                for k in range(s.port_val.shape[1])
            },
            "holding": bool(s.holding[i]),
            "hold_val": int(s.hold_val[i]),
            "retired": int(s.retired[i]),
        }

    def stacks(self) -> dict[str, list[int]]:
        """Every stack node's live contents, bottom first."""
        mem = np.asarray(self._state.stack_mem)
        tops = np.asarray(self._state.stack_top)
        return {
            name: mem[i, : tops[i]].tolist()
            for i, name in enumerate(self._stack_names)
        }

    def listing(self, lane: str) -> str:
        """Disassembly with `->` at the current pc and `B` on breakpoints."""
        i = self._lane_index(lane)
        length = int(self._net.prog_len[i])
        text = disassemble_program(
            self._net.code[i], length, self._lane_names, self._stack_names
        )
        pc = int(self._state.pc[i])
        rows = []
        for line_no, line in enumerate(text.split("\n")):
            cursor = "->" if line_no == pc else "  "
            bp = "B" if line_no in self._breaks.get(i, ()) else " "
            rows.append(f"{cursor}{bp} {line_no:>3}  {line}")
        return "\n".join(rows)

    def history(self, last: int | None = None) -> str:
        """Formatted trace listing of the most recent ticks."""
        entries = decode_trace(
            self._trace,
            self._net.code,
            self._net.prog_len,
            lane_names=self._lane_names,
            stack_names=self._stack_names,
            last=last,
        )
        return format_trace(entries)

    # --- internals ---------------------------------------------------------

    def _lane_index(self, lane: str) -> int:
        if lane not in self._lane_ids:
            raise KeyError(f"'{lane}' is not a program node (have {self._lane_names})")
        return self._lane_ids[lane]

    def _hits(self) -> list[tuple[str, int]]:
        if not self._breaks:
            return []
        pc = np.asarray(self._state.pc)
        return [
            (self._lane_names[i], int(pc[i]))
            for i, lines in self._breaks.items()
            if int(pc[i]) in lines
        ]
