"""TIS assembly frontend: parser (reference-parity) and dense-table lowering."""

from misaka_tpu.tis.parser import TISParseError, generate_label_map, tokenize, parse
from misaka_tpu.tis import isa
from misaka_tpu.tis.lower import lower_program, LoweredProgram

__all__ = [
    "TISParseError",
    "generate_label_map",
    "tokenize",
    "parse",
    "isa",
    "lower_program",
    "LoweredProgram",
]
