r"""TIS-100 dialect parser with grammar parity to the reference tokenizer.

Reproduces the exact two-pass compile of /root/reference/internal/tis/tokenizer.go:
pass 1 builds the label->line map (GenerateLabelMap, tokenizer.go:11-26), pass 2
regex-dispatches every line to a token row (Tokenize, tokenizer.go:29-106).

Parity notes (each deliberate):
  * Labels are uppercased (tokenizer.go:18,:70); duplicates rejected with the
    reference's message (tokenizer.go:19-21).
  * Every source line — blank, comment, label-only — becomes one NOP slot, so
    label indices equal raw line numbers (tokenizer.go:41-46) and the PC wrap
    `(ptr+1) % len(asm)` (program.go:429) sees the same program length.
  * The grammar requires a comma followed by whitespace: `MOV 1,ACC` is a
    syntax error exactly as in the reference (`\s*,\s+` at tokenizer.go:50).
  * `\w` is matched ASCII-only (Go's regexp \w is ASCII; Python's defaults to
    Unicode, hence re.ASCII below).
  * Jump labels are validated at compile time (tokenizer.go:71-75).
"""

from __future__ import annotations

import re


class TISParseError(ValueError):
    """Raised on any parse failure; messages mirror the reference's errors."""


_LABEL_RE = re.compile(r"^\s*(\w+):", re.ASCII)
_PREFIX_RE = re.compile(r"^(\s*\w+:)?\s*", re.ASCII)

# Ordered regex cascade — one entry per branch of tokenizer.go:41-101, in the
# same priority order.  Each maps match groups -> token row.
_RULES = [
    (re.compile(r"^#.*$", re.ASCII), lambda m: ["NOP"]),
    (re.compile(r"^(NOP|SWP|SAV|NEG)\s*$", re.ASCII), lambda m: [m.group(1)]),
    (re.compile(r"^MOV\s+(-?\d+)\s*,\s+(ACC|NIL)\s*$", re.ASCII),
     lambda m: ["MOV_VAL_LOCAL", m.group(1), m.group(2)]),
    (re.compile(r"^MOV\s+(-?\d+)\s*,\s+(\w+:R[0123])\s*$", re.ASCII),
     lambda m: ["MOV_VAL_NETWORK", m.group(1), m.group(2)]),
    (re.compile(r"^MOV\s+(ACC|NIL|R[0123])\s*,\s+(ACC|NIL)\s*$", re.ASCII),
     lambda m: ["MOV_SRC_LOCAL", m.group(1), m.group(2)]),
    (re.compile(r"^MOV\s+(ACC|NIL|R[0123])\s*,\s+(\w+:R[0123])\s*$", re.ASCII),
     lambda m: ["MOV_SRC_NETWORK", m.group(1), m.group(2)]),
    (re.compile(r"^(ADD|SUB)\s+(-?\d+)\s*$", re.ASCII),
     lambda m: [f"{m.group(1)}_VAL", m.group(2)]),
    (re.compile(r"^(ADD|SUB)\s+(ACC|NIL|R[0123])\s*$", re.ASCII),
     lambda m: [f"{m.group(1)}_SRC", m.group(2)]),
    # JMP/JEZ/JNZ/JGZ/JLZ handled separately (needs label validation).
    (re.compile(r"^JRO\s+(-?\d+)\s*$", re.ASCII), lambda m: ["JRO_VAL", m.group(1)]),
    (re.compile(r"^JRO\s+(ACC|NIL|R[0123])\s*$", re.ASCII),
     lambda m: ["JRO_SRC", m.group(1)]),
    (re.compile(r"^PUSH\s+(-?\d+)\s*,\s+(\w+)\s*$", re.ASCII),
     lambda m: ["PUSH_VAL", m.group(1), m.group(2)]),
    (re.compile(r"^PUSH\s+(ACC|NIL|R[0123])\s*,\s+(\w+)\s*$", re.ASCII),
     lambda m: ["PUSH_SRC", m.group(1), m.group(2)]),
    (re.compile(r"^POP\s+(\w+)\s*,\s+(ACC|NIL)\s*$", re.ASCII),
     lambda m: ["POP", m.group(1), m.group(2)]),
    (re.compile(r"^IN\s+(ACC|NIL)\s*$", re.ASCII), lambda m: ["IN", m.group(1)]),
    (re.compile(r"^OUT\s+(-?\d+)\s*$", re.ASCII), lambda m: ["OUT_VAL", m.group(1)]),
    (re.compile(r"^OUT\s+(ACC|NIL|R[0123])\s*$", re.ASCII),
     lambda m: ["OUT_SRC", m.group(1)]),
]

_JUMP_RE = re.compile(r"^(JMP|JEZ|JNZ|JGZ|JLZ)\s+(\w+)\s*$", re.ASCII)


def generate_label_map(lines: list[str]) -> dict[str, int]:
    """Pass 1: map uppercased labels to their raw line index."""
    label_map: dict[str, int] = {}
    for i, line in enumerate(lines):
        m = _LABEL_RE.match(line)
        if m:
            label = m.group(1).upper()
            if label in label_map:
                raise TISParseError("Cannot repeat label")
            label_map[label] = i
    return label_map


def tokenize(lines: list[str], label_map: dict[str, int]) -> list[list[str]]:
    """Pass 2: convert each line into a token row, validating jump labels."""
    asm: list[list[str]] = []
    for i, line in enumerate(lines):
        m = _PREFIX_RE.match(line)
        instr = line[m.end():] if m else line

        if len(instr) == 0:
            asm.append(["NOP"])
            continue

        jm = _JUMP_RE.match(instr)
        if jm:
            label = jm.group(2).upper()
            if label not in label_map:
                raise TISParseError(f"line {i}, label '{label}' was not declared")
            asm.append([jm.group(1), label])
            continue

        for pattern, build in _RULES:
            rm = pattern.match(instr)
            if rm:
                asm.append(build(rm))
                break
        else:
            raise TISParseError(f"line {i}, '{instr}' not a valid instruction")

    return asm


def parse(program: str) -> tuple[list[list[str]], dict[str, int]]:
    """Full compile of a program string (the LoadProgram path, program.go:178-193)."""
    lines = program.split("\n")
    label_map = generate_label_map(lines)
    return tokenize(lines, label_map), label_map
