"""Dense ISA encoding for the TIS superstep kernel.

The reference interprets token rows (strings) with a 24-case switch per step
(/root/reference/internal/nodes/program.go:219-432).  On TPU we cannot branch
per lane, so the frontend lowers every instruction to a fixed-width row of
int32 fields and the kernel evaluates all semantic classes as dense masked
vector ops.  The 24 surface forms collapse to 18 semantic opcodes because
"VAL vs SRC" variants differ only in the source selector field.

Instruction word layout (one int32[NFIELDS] row per program line; every source
line, including comments/labels, occupies one slot so that label indices equal
raw line numbers — parity with program.go:429 wrap semantics):

  F_OP    semantic opcode (OP_*)
  F_SRC   source selector (SRC_*): immediate / ACC / NIL / inbound port R0-R3
  F_IMM   immediate operand (int32; reference locals are 64-bit Go ints but the
          wire is sint32, messenger.proto:34-41 — we use int32 end to end)
  F_DST   local destination selector (DST_*): ACC or NIL
  F_TGT   target index: program-lane id for OP_MOV_NET, stack id for PUSH/POP
  F_PORT  target port 0-3 for OP_MOV_NET
  F_JMP   absolute jump target line for OP_JMP..OP_JLZ
"""

# --- semantic opcodes -------------------------------------------------------
OP_NOP = 0        # no-op (also blank/comment/label-only lines, tokenizer.go:41-46)
OP_SWP = 1        # acc <-> bak                      (program.go:276-280)
OP_SAV = 2        # bak <- acc                       (program.go:281-283)
OP_NEG = 3        # acc <- -acc                      (program.go:312-314)
OP_MOV_LOCAL = 4  # read src, write ACC/NIL          (program.go:228-241, :252-265)
OP_MOV_NET = 5    # read src, send to lane:port      (program.go:242-251, :266-275)
OP_ADD = 6        # acc += src                       (program.go:284-290, :298-304)
OP_SUB = 7        # acc -= src                       (program.go:291-297, :305-311)
OP_JMP = 8        # pc <- target                     (program.go:315-319)
OP_JEZ = 9        # if acc == 0                      (program.go:320-326)
OP_JNZ = 10       # if acc != 0                      (program.go:327-333)
OP_JGZ = 11       # if acc > 0                       (program.go:334-340)
OP_JLZ = 12       # if acc < 0                       (program.go:341-347)
OP_JRO = 13       # pc <- clamp(pc+src, 0, len-1)    (program.go:348-363)
OP_PUSH = 14      # push src onto stack tgt          (program.go:364-383)
OP_POP = 15       # pop stack tgt into ACC/NIL       (program.go:384-394)
OP_IN = 16        # read master input into ACC/NIL   (program.go:395-405)
OP_OUT = 17       # send src to master output        (program.go:406-423)

NUM_OPS = 18

# --- source selectors -------------------------------------------------------
SRC_IMM = 0
SRC_ACC = 1
SRC_NIL = 2   # reads as 0 (program.go:439-440)
SRC_R0 = 3    # SRC_R0 + k selects inbound port Rk; reading a port stalls the
SRC_R1 = 4    # lane until a peer's send lands (getFromSrc, program.go:441-468)
SRC_R2 = 5
SRC_R3 = 6

# --- local destination selectors -------------------------------------------
DST_ACC = 0
DST_NIL = 1   # writes discard (program.go:237-239)

# --- field indices ----------------------------------------------------------
F_OP = 0
F_SRC = 1
F_IMM = 2
F_DST = 3
F_TGT = 4
F_PORT = 5
F_JMP = 6
NFIELDS = 7

# Opcodes whose semantics read the source operand (and therefore stall when the
# source is an empty inbound port).  OP_POP / OP_IN write ACC but their "source"
# is the stack / master queue, handled by dedicated feasibility logic.
READS_SRC = (OP_MOV_LOCAL, OP_MOV_NET, OP_ADD, OP_SUB, OP_JRO, OP_PUSH, OP_OUT)

# Number of inbound ports per program node (r0..r3, program.go:29-32).
NUM_PORTS = 4

OP_NAMES = {
    OP_NOP: "NOP", OP_SWP: "SWP", OP_SAV: "SAV", OP_NEG: "NEG",
    OP_MOV_LOCAL: "MOV_LOCAL", OP_MOV_NET: "MOV_NET",
    OP_ADD: "ADD", OP_SUB: "SUB",
    OP_JMP: "JMP", OP_JEZ: "JEZ", OP_JNZ: "JNZ", OP_JGZ: "JGZ", OP_JLZ: "JLZ",
    OP_JRO: "JRO", OP_PUSH: "PUSH", OP_POP: "POP", OP_IN: "IN", OP_OUT: "OUT",
}
