"""Lowering: token rows -> dense int32 instruction tables for the kernel.

This stage has no counterpart in the reference (which interprets token strings
directly, program.go:219-432); it is the TPU-native step that turns a parsed
program plus the network's name->index maps into the fixed-shape arrays the
superstep kernel consumes.

Symbol resolution happens here, at compile time:
  * `name:Rk` network targets (parsed per-send at program.go:476 in the
    reference) become (lane id, port) pairs.  Sending to a non-program node is
    a compile error here; the reference would dial it and fatally error at
    runtime (program.go:494) — documented divergence, strictly better.
  * PUSH/POP stack targets become stack ids.  Same divergence note.
  * Jump labels were validated by the parser; here they become absolute line
    indices (the reference looks them up per-execution, program.go:318).

Register ARITHMETIC is 64-bit everywhere — acc/bak are carried as int32
(hi, lo) planes on device (core/regs64.py) and int64 on hosts, with
truncation to sint32 exactly at wire transfers (messenger.proto:34-41,
program.go:498), matching the reference's Go-int locals.  IMMEDIATES,
however, are wrapped to int32 in the tables (one field per instruction);
the reference's Atoi yields a 64-bit int, so a source literal outside
int32 (e.g. `ADD 4000000000`) diverges — kernel tables sign-extend the
wrapped int32.  Documented corner: TIS-dialect programs use small
literals (the original language clamps at ±999), and 64-bit magnitudes
remain reachable the same way the tests build them, by accumulation.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from misaka_tpu.tis import isa
from misaka_tpu.tis.parser import TISParseError, parse

_SRC_SEL = {
    "ACC": isa.SRC_ACC,
    "NIL": isa.SRC_NIL,
    "R0": isa.SRC_R0,
    "R1": isa.SRC_R1,
    "R2": isa.SRC_R2,
    "R3": isa.SRC_R3,
}

_DST_SEL = {"ACC": isa.DST_ACC, "NIL": isa.DST_NIL}

_JUMP_OPS = {
    "JMP": isa.OP_JMP,
    "JEZ": isa.OP_JEZ,
    "JNZ": isa.OP_JNZ,
    "JGZ": isa.OP_JGZ,
    "JLZ": isa.OP_JLZ,
}


class TISLowerError(ValueError):
    """Raised when a parsed program references unknown nodes/stacks."""


@dataclass(frozen=True)
class LoweredProgram:
    """One node's program as a dense [prog_len, NFIELDS] int32 table."""

    code: np.ndarray  # [L, NFIELDS] int32
    length: int       # true program length before padding
    source: str       # original program text (for /load round-trips & debug)


def _i32(text: str) -> int:
    """Parse a decimal immediate and wrap to int32."""
    v = int(text) & 0xFFFFFFFF
    return v - (1 << 32) if v >= (1 << 31) else v


def _src_of(tok: str, row: list[str]) -> tuple[int, int]:
    """Return (src_sel, imm) for a VAL-or-SRC operand token."""
    if tok in _SRC_SEL:
        return _SRC_SEL[tok], 0
    return isa.SRC_IMM, _i32(tok)


def lower_tokens(
    tokens: list[list[str]],
    label_map: dict[str, int],
    lane_ids: dict[str, int],
    stack_ids: dict[str, int],
) -> np.ndarray:
    """Lower token rows to a [len(tokens), NFIELDS] int32 table."""
    code = np.zeros((len(tokens), isa.NFIELDS), dtype=np.int32)
    for i, row in enumerate(tokens):
        kind = row[0]
        f = code[i]
        if kind == "NOP":
            f[isa.F_OP] = isa.OP_NOP
        elif kind == "SWP":
            f[isa.F_OP] = isa.OP_SWP
        elif kind == "SAV":
            f[isa.F_OP] = isa.OP_SAV
        elif kind == "NEG":
            f[isa.F_OP] = isa.OP_NEG
        elif kind in ("MOV_VAL_LOCAL", "MOV_SRC_LOCAL"):
            f[isa.F_OP] = isa.OP_MOV_LOCAL
            f[isa.F_SRC], f[isa.F_IMM] = _src_of(row[1], row)
            f[isa.F_DST] = _DST_SEL[row[2]]
        elif kind in ("MOV_VAL_NETWORK", "MOV_SRC_NETWORK"):
            f[isa.F_OP] = isa.OP_MOV_NET
            f[isa.F_SRC], f[isa.F_IMM] = _src_of(row[1], row)
            name, port = row[2].split(":")
            if name not in lane_ids:
                raise TISLowerError(
                    f"line {i}, '{name}' is not a program node on this network"
                )
            f[isa.F_TGT] = lane_ids[name]
            f[isa.F_PORT] = int(port[1])
        elif kind in ("ADD_VAL", "ADD_SRC"):
            f[isa.F_OP] = isa.OP_ADD
            f[isa.F_SRC], f[isa.F_IMM] = _src_of(row[1], row)
        elif kind in ("SUB_VAL", "SUB_SRC"):
            f[isa.F_OP] = isa.OP_SUB
            f[isa.F_SRC], f[isa.F_IMM] = _src_of(row[1], row)
        elif kind in _JUMP_OPS:
            f[isa.F_OP] = _JUMP_OPS[kind]
            f[isa.F_JMP] = label_map[row[1]]
        elif kind in ("JRO_VAL", "JRO_SRC"):
            f[isa.F_OP] = isa.OP_JRO
            f[isa.F_SRC], f[isa.F_IMM] = _src_of(row[1], row)
        elif kind in ("PUSH_VAL", "PUSH_SRC"):
            f[isa.F_OP] = isa.OP_PUSH
            f[isa.F_SRC], f[isa.F_IMM] = _src_of(row[1], row)
            if row[2] not in stack_ids:
                raise TISLowerError(
                    f"line {i}, '{row[2]}' is not a stack node on this network"
                )
            f[isa.F_TGT] = stack_ids[row[2]]
        elif kind == "POP":
            f[isa.F_OP] = isa.OP_POP
            if row[1] not in stack_ids:
                raise TISLowerError(
                    f"line {i}, '{row[1]}' is not a stack node on this network"
                )
            f[isa.F_TGT] = stack_ids[row[1]]
            f[isa.F_DST] = _DST_SEL[row[2]]
        elif kind == "IN":
            f[isa.F_OP] = isa.OP_IN
            f[isa.F_DST] = _DST_SEL[row[1]]
        elif kind in ("OUT_VAL", "OUT_SRC"):
            f[isa.F_OP] = isa.OP_OUT
            f[isa.F_SRC], f[isa.F_IMM] = _src_of(row[1], row)
        else:  # pragma: no cover — parser emits only the kinds above
            raise TISLowerError(f"line {i}, unknown token kind '{kind}'")
    return code


def lower_program(
    program: str,
    lane_ids: dict[str, int],
    stack_ids: dict[str, int],
) -> LoweredProgram:
    """Parse + lower one node's program text."""
    tokens, label_map = parse(program)
    code = lower_tokens(tokens, label_map, lane_ids, stack_ids)
    return LoweredProgram(code=code, length=len(tokens), source=program)


def pad_programs(programs: list[LoweredProgram]) -> tuple[np.ndarray, np.ndarray]:
    """Stack per-lane tables into [N, L, NFIELDS] plus prog_len [N].

    Padding rows are NOP, but they are unreachable: the PC wraps modulo the
    true per-lane length (program.go:429), never the padded length.
    """
    max_len = max(p.length for p in programs)
    n = len(programs)
    code = np.zeros((n, max_len, isa.NFIELDS), dtype=np.int32)
    lengths = np.zeros((n,), dtype=np.int32)
    for i, p in enumerate(programs):
        code[i, : p.length] = p.code
        lengths[i] = p.length
    return code, lengths


DEFAULT_PROGRAM = "NOP"  # a fresh node's program (program.go:64)
