"""ctypes bridge to the native C++ assembler (native/assembler.cpp).

The native assembler is a functional twin of parser.py + lower.py, used for
fast `/load` on large programs.  Build with `make native` (repo root) or let
this module build it on first use (g++, ~1s).  Everything degrades to the
pure-Python frontend when no compiler is available — `assemble()` is the
drop-in entry point that picks the best backend.

Known divergence: immediates beyond int64 range saturate in C++ but wrap in
Python; both are far outside the reference's int domain.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from misaka_tpu.tis import isa
from misaka_tpu.tis.lower import LoweredProgram, TISLowerError, lower_program
from misaka_tpu.tis.parser import TISParseError
from misaka_tpu.utils.nativelib import NativeLib

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_MAX_LINES = 65536


def _configure(lib: ctypes.CDLL) -> None:
    lib.misaka_assemble.restype = ctypes.c_int
    lib.misaka_assemble.argtypes = [
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_int,
    ]


_NATIVE = NativeLib(
    os.path.join(_REPO_ROOT, "native", "assembler.cpp"),
    os.path.join(_REPO_ROOT, "native", "libmisaka_assembler.so"),
    _configure,
)


def _load() -> ctypes.CDLL | None:
    return _NATIVE.load()


def native_available() -> bool:
    return _NATIVE.available()


def _ordered_names(ids: dict[str, int]) -> str:
    return "\n".join(name for name, _ in sorted(ids.items(), key=lambda kv: kv[1]))


def assemble_native(
    program: str, lane_ids: dict[str, int], stack_ids: dict[str, int]
) -> LoweredProgram:
    """Assemble via the C++ backend; raises like the Python frontend."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native assembler unavailable (no g++?)")
    n_lines = program.count("\n") + 1
    if n_lines > _MAX_LINES:
        raise TISLowerError(f"program too long ({n_lines} lines)")
    out = np.zeros((n_lines, isa.NFIELDS), dtype=np.int32)
    err = ctypes.create_string_buffer(512)
    rc = lib.misaka_assemble(
        program.encode(),
        _ordered_names(lane_ids).encode(),
        _ordered_names(stack_ids).encode(),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n_lines,
        err,
        len(err),
    )
    if rc < 0:
        msg = err.value.decode()
        # mirror the Python frontend's exception taxonomy
        if "not a program node" in msg or "not a stack node" in msg:
            raise TISLowerError(msg)
        raise TISParseError(msg)
    return LoweredProgram(code=out[:rc], length=rc, source=program)


def assemble(
    program: str, lane_ids: dict[str, int], stack_ids: dict[str, int]
) -> LoweredProgram:
    """Best-backend assemble: native when available, Python otherwise."""
    if native_available():
        return assemble_native(program, lane_ids, stack_ids)
    return lower_program(program, lane_ids, stack_ids)
