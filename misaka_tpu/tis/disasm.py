"""Disassembler: dense ISA tables -> TIS source text (the inverse of lower.py).

The reference has no disassembler — it keeps programs as token-string rows and
logs them raw (program.go:222-223).  Here lowered programs are opaque int32
tables, so observability tooling (trace decoding, the debugger, /status
listings) needs a way back to readable assembly.

Round-trip guarantee (tested in tests/test_disasm.py): for any lowered
program, `lower(parse(disassemble(code)))` reproduces the exact same table.
Achieved by exploiting two grammar-parity properties of the frontend:

  * every source line is one instruction slot (label indices == line numbers,
    tokenizer.go:41-46), so the disassembly emits exactly one line per row;
  * an inline label prefix (`L3: ADD 1`) occupies no extra slot (the optional
    `\\w+:` prefix strip, tokenizer.go:66-70), so jump targets get synthetic
    labels `L<line>` without shifting any line number.

Lost in the round trip (necessarily): original label names, comments, and
blank-line placement — all of which lower to the same table.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from misaka_tpu.tis import isa


class TISDisasmError(ValueError):
    """Raised on malformed tables (unknown opcode / selector)."""


def _src_text(src: int, imm: int) -> str:
    if src == isa.SRC_IMM:
        return str(imm)
    if src == isa.SRC_ACC:
        return "ACC"
    if src == isa.SRC_NIL:
        return "NIL"
    if isa.SRC_R0 <= src <= isa.SRC_R3:
        return f"R{src - isa.SRC_R0}"
    raise TISDisasmError(f"unknown source selector {src}")


def _dst_text(dst: int) -> str:
    if dst == isa.DST_ACC:
        return "ACC"
    if dst == isa.DST_NIL:
        return "NIL"
    raise TISDisasmError(f"unknown destination selector {dst}")


_JUMP_NAMES = {
    isa.OP_JMP: "JMP",
    isa.OP_JEZ: "JEZ",
    isa.OP_JNZ: "JNZ",
    isa.OP_JGZ: "JGZ",
    isa.OP_JLZ: "JLZ",
}


def disassemble_line(
    fields: Sequence[int],
    lane_names: Sequence[str],
    stack_names: Sequence[str],
) -> str:
    """Render one instruction word (without any label prefix)."""
    op = int(fields[isa.F_OP])
    src = int(fields[isa.F_SRC])
    imm = int(fields[isa.F_IMM])
    dst = int(fields[isa.F_DST])
    tgt = int(fields[isa.F_TGT])
    port = int(fields[isa.F_PORT])
    jmp = int(fields[isa.F_JMP])

    if op == isa.OP_NOP:
        return "NOP"
    if op == isa.OP_SWP:
        return "SWP"
    if op == isa.OP_SAV:
        return "SAV"
    if op == isa.OP_NEG:
        return "NEG"
    if op == isa.OP_MOV_LOCAL:
        return f"MOV {_src_text(src, imm)}, {_dst_text(dst)}"
    if op == isa.OP_MOV_NET:
        return f"MOV {_src_text(src, imm)}, {lane_names[tgt]}:R{port}"
    if op == isa.OP_ADD:
        return f"ADD {_src_text(src, imm)}"
    if op == isa.OP_SUB:
        return f"SUB {_src_text(src, imm)}"
    if op in _JUMP_NAMES:
        return f"{_JUMP_NAMES[op]} L{jmp}"
    if op == isa.OP_JRO:
        return f"JRO {_src_text(src, imm)}"
    if op == isa.OP_PUSH:
        return f"PUSH {_src_text(src, imm)}, {stack_names[tgt]}"
    if op == isa.OP_POP:
        return f"POP {stack_names[tgt]}, {_dst_text(dst)}"
    if op == isa.OP_IN:
        return f"IN {_dst_text(dst)}"
    if op == isa.OP_OUT:
        return f"OUT {_src_text(src, imm)}"
    raise TISDisasmError(f"unknown opcode {op}")


def disassemble_program(
    code: np.ndarray,
    length: int | None = None,
    lane_names: Sequence[str] | None = None,
    stack_names: Sequence[str] | None = None,
) -> str:
    """Disassemble one lane's [L, NFIELDS] table to TIS source.

    `length` trims padding rows (pad_programs pads with unreachable NOPs);
    name sequences default to positional `node<i>` / `stack<i>`.
    """
    code = np.asarray(code)
    n = code.shape[0] if length is None else int(length)
    if lane_names is None or stack_names is None:
        max_tgt = int(code[:n, isa.F_TGT].max(initial=0)) if n else 0
        lane_names = lane_names or [f"node{i}" for i in range(max_tgt + 1)]
        stack_names = stack_names or [f"stack{i}" for i in range(max_tgt + 1)]

    targets = {
        int(code[i, isa.F_JMP])
        for i in range(n)
        if int(code[i, isa.F_OP]) in _JUMP_NAMES
    }
    lines = []
    for i in range(n):
        text = disassemble_line(code[i], lane_names, stack_names)
        if i in targets:
            text = f"L{i}: {text}"
        lines.append(text)
    return "\n".join(lines)


def disassemble_network(
    code: np.ndarray,
    prog_len: np.ndarray,
    lane_names: Sequence[str],
    stack_names: Sequence[str],
) -> dict[str, str]:
    """Disassemble a whole network's [N, L, NFIELDS] tables, keyed by lane name."""
    return {
        name: disassemble_program(code[i], int(prog_len[i]), lane_names, stack_names)
        for i, name in enumerate(lane_names)
    }
