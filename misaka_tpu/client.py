"""Typed Python client for the master's HTTP surface.

The reference ships no client at all — its README drives the five routes
with curl (README.md "Usage"; master.go:90-224).  This wraps those five
byte-compatible routes plus every additive route this build serves, with
the two bulk lanes a throughput client actually wants:

  compute(v)          POST /compute        one value, int -> int
  compute_batch(vals) POST /compute_batch  decimal text, vectorized codec
  compute_raw(vals)   POST /compute_raw    raw little-endian int32 bodies
                                           (the fleet-client wire format)
  run/pause/reset     POST /run /pause /reset
  load(target, prog)  POST /load
  status()/trace()    GET  /status /trace
  healthz()/metrics() GET  /healthz /metrics  (liveness + Prometheus text)
  checkpoint/restore  POST /checkpoint /restore  (server-side .npz)
  profile_start/stop  POST /profile/start /profile/stop

The module imports stdlib only (numpy lazily, inside the two bulk
methods) and none of the jax-backed misaka_tpu packages — the scalar and
lifecycle surface is importable on any ops box.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request


class MisakaClientError(RuntimeError):
    """Non-2xx response from the master (carries status + body text)."""

    def __init__(self, status: int, body: str):
        super().__init__(f"HTTP {status}: {body}")
        self.status = status
        self.body = body


class MisakaClient:
    """A client session against one master (`base_url`, default port 8000).

    Methods raise MisakaClientError on any non-2xx response (e.g. 400
    "network is not running", 500 compute timeout) and propagate socket
    errors (urllib.error.URLError) unchanged.
    """

    def __init__(self, base_url: str = "http://localhost:8000", timeout: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    # --- plumbing ----------------------------------------------------------

    def _request(self, path: str, data: bytes | None, method: str) -> bytes:
        req = urllib.request.Request(
            self.base_url + path, data=data, method=method
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            raise MisakaClientError(
                e.code, e.read().decode(errors="replace").strip()
            ) from None

    def _post_form(self, path: str, **fields) -> bytes:
        return self._request(
            path, urllib.parse.urlencode(fields).encode(), "POST"
        )

    # --- the reference's five routes (master.go:90-224) --------------------

    def run(self) -> None:
        self._post_form("/run")

    def pause(self) -> None:
        self._post_form("/pause")

    def reset(self) -> None:
        self._post_form("/reset")

    def load(self, target: str, program: str) -> None:
        """Reprogram one node (resets the network, like the reference)."""
        self._post_form("/load", targetURI=target, program=program)

    def compute(self, value: int) -> int:
        raw = self._post_form("/compute", value=str(int(value)))
        return int(json.loads(raw)["value"])

    # --- bulk compute lanes -------------------------------------------------

    def compute_batch(self, values, spread: bool = True):
        """A value stream in ONE round trip (decimal text wire format).
        Returns an int32 numpy array (numpy imported here, not at module
        scope — the scalar/lifecycle surface stays stdlib-only)."""
        import numpy as np

        vals = np.ascontiguousarray(values, dtype=np.int32)
        body = b"values=" + b"+".join(b"%d" % v for v in vals.tolist())
        if spread:
            body += b"&spread=1"
        raw = self._request("/compute_batch", body, "POST")
        return np.asarray(json.loads(raw)["values"], dtype=np.int32)

    def compute_raw(self, values, spread: bool = True):
        """The wire-efficient lane: raw little-endian int32 both ways.
        Returns an int32 numpy array."""
        import numpy as np

        vals = np.ascontiguousarray(values, dtype="<i4")
        path = "/compute_raw?spread=" + ("1" if spread else "0")
        raw = self._request(path, vals.tobytes(), "POST")
        return np.frombuffer(raw, dtype="<i4").copy()

    # --- observability ------------------------------------------------------

    def status(self) -> dict:
        return json.loads(self._request("/status", None, "GET"))

    def healthz(self) -> dict:
        """Cheap liveness (no server-side state lock): engine + uptime."""
        return json.loads(self._request("/healthz", None, "GET"))

    def metrics(self) -> str:
        """Raw Prometheus text exposition from GET /metrics (parse with
        misaka_tpu.utils.metrics.parse_text where numpy/jax are absent —
        the parser is stdlib-only like this client)."""
        return self._request("/metrics", None, "GET").decode()

    def trace(self, last: int | None = None) -> list[dict]:
        path = "/trace" if last is None else f"/trace?last={int(last)}"
        return json.loads(self._request(path, None, "GET"))["entries"]

    # --- checkpoint / profiling (additive; server must have dirs enabled) --

    def checkpoint(self, name: str) -> None:
        self._post_form("/checkpoint", name=name)

    def restore(self, name: str) -> None:
        self._post_form("/restore", name=name)

    def profile_start(self, name: str = "profile") -> None:
        self._post_form("/profile/start", name=name)

    def profile_stop(self) -> str:
        return self._request("/profile/stop", b"", "POST").decode()
