"""Typed Python client for the master's HTTP surface.

The reference ships no client at all — its README drives the five routes
with curl (README.md "Usage"; master.go:90-224).  This wraps those five
byte-compatible routes plus every additive route this build serves, with
the two bulk lanes a throughput client actually wants:

  compute(v)          POST /compute        one value, int -> int
  compute_batch(vals) POST /compute_batch  decimal text, vectorized codec
  compute_raw(vals)   POST /compute_raw    raw little-endian int32 bodies
                                           (the fleet-client wire format)
  run/pause/reset     POST /run /pause /reset
  load(target, prog)  POST /load
  status()/trace()    GET  /status /trace
  healthz()/metrics() GET  /healthz /metrics  (liveness + Prometheus text)
  usage()/alerts()    GET  /debug/usage /debug/alerts  (per-program cost
                      ledger + SLO burn-rate states); flamegraph() GET
                      /debug/flamegraph (continuous profiler)
  checkpoint/restore  POST /checkpoint /restore  (server-side .npz)
  profile_start/stop  POST /profile/start /profile/stop
  upload_program/list_programs/program_info  POST/GET /programs*
                      (the registry surface; Client(program=...) pins a
                      session to one registry program)

The module imports stdlib only (numpy lazily, inside the two bulk
methods) and none of the jax-backed misaka_tpu packages — the scalar and
lifecycle surface is importable on any ops box.

Transport: every request rides a POOLED persistent HTTP/1.1 connection
(the server keeps keep-alive since r8) — the reference pays TCP setup +
teardown per transferred value; a fleet client must not.  A connection
dropped by the server (restart, idle timeout) reconnects cleanly: the
retry happens only when the failure hit a REUSED pooled socket before a
response arrived, so a request is never silently replayed against a
connection that might have executed it.
"""

from __future__ import annotations

import http.client
import json
import threading
import urllib.error
import urllib.parse


class MisakaClientError(RuntimeError):
    """Non-2xx response from the master (carries status + body text, and
    the server's trace ID when the response had one — a 503/timeout then
    names the exact request to grep for in `/debug/requests/<id>` and
    the server's JSON logs)."""

    def __init__(self, status: int, body: str, trace_id: str | None = None,
                 retry_after: float | None = None):
        msg = f"HTTP {status}: {body}"
        if trace_id:
            msg += f" [trace {trace_id}]"
        if retry_after is not None:
            msg += f" (retry after {retry_after:g}s)"
        super().__init__(msg)
        self.status = status
        self.body = body
        self.trace_id = trace_id
        #: structured per-request divergence records when the server
        #: refused a ?verify=replay publish (HTTP 409 JSON body
        #: {"error", "diffs"}) — each names the captured request's
        #: trace/offset and the expected-vs-actual value heads.  Empty
        #: for every other error shape.
        self.diffs: list = []
        if status == 409 and body.lstrip().startswith("{"):
            try:
                obj = json.loads(body)
                if isinstance(obj, dict) and isinstance(
                    obj.get("diffs"), list
                ):
                    self.diffs = obj["diffs"]
            except ValueError:
                pass
        #: seconds from the response's Retry-After header (None when the
        #: server sent none).  A 429 carries it always — back off for
        #: this long instead of retrying hot (the edge's token bucket
        #: will just burn your next request too).
        self.retry_after = retry_after


class TracedInt(int):
    """An int carrying the response's tracing context: ``timings`` (the
    parsed ``Server-Timing`` phases, ms) and ``trace_id``."""

    timings: dict | None = None
    trace_id: str | None = None


def _parse_server_timing(value: str) -> dict:
    """"queue;dur=1.2, total;dur=3.4" -> {"queue": 1.2, "total": 3.4}.
    One parser for both halves of the wire (lazy import: tracespan is
    stdlib-only like this client, but the scalar/lifecycle surface
    shouldn't pay any misaka import until a response carries timings)."""
    from misaka_tpu.utils.tracespan import parse_server_timing

    return parse_server_timing(value)


_traced_array_cls = None


def _traced_array(arr, headers):
    """`arr` as a numpy view carrying ``.timings`` + ``.trace_id`` (the
    subclass is built lazily so the scalar/lifecycle client surface stays
    numpy-free)."""
    global _traced_array_cls
    import numpy as np

    if _traced_array_cls is None:
        class TracedArray(np.ndarray):
            """An int32 result array + the response's tracing context."""

            timings = None
            trace_id = None

        _traced_array_cls = TracedArray
    out = arr.view(_traced_array_cls)
    st = headers.get("Server-Timing")
    out.timings = _parse_server_timing(st) if st else {}
    out.trace_id = headers.get("X-Misaka-Trace")
    return out


class MisakaClient:
    """A client session against one master (`base_url`, default port 8000).

    Methods raise MisakaClientError on any non-2xx response (e.g. 400
    "network is not running", 500 compute timeout) and wrap connection
    failures in urllib.error.URLError (the documented socket-error shape
    since r1; the transport is http.client underneath).

    Thread-safe: concurrent callers draw idle connections from a shared
    pool (LIFO — the hottest socket stays warm) and return them after
    each response; `pool_size` caps how many idle sockets are retained.
    """

    def __init__(self, base_url: str = "http://localhost:8000",
                 timeout: float = 30.0, pool_size: int = 4,
                 retry_stale: bool = True, connect_retries: int = 3,
                 program: str | None = None, api_key: str | None = None,
                 ca: str | None = None, tls_insecure: bool = False,
                 wire: str | None = None):
        """`retry_stale` (default True) replays a request ONCE when a
        POOLED connection proves dead at send time or before any
        response byte arrives — the stale-keep-alive case.  This is
        at-least-once: in the rare window where the server executed the
        request and died before writing a byte, the replay executes it
        twice.  Pass False for strict at-most-once (stale pooled sockets
        then surface as URLError and the caller decides).

        `connect_retries` (default 3) retries a request whose FRESH
        connection was refused outright — the server-restarting window
        (a supervisor respawning a frontend worker, a rolling deploy) —
        with exponential backoff (0.1s doubling, jittered).  Distinct
        from `retry_stale` and always safe: connection refused means the
        kernel rejected the dial, so nothing was ever sent to execute.
        Pass 0 to surface the first refusal as URLError immediately.

        `program` pins this session to one registry program: compute /
        compute_batch / compute_raw then ride the program-addressed
        routes (POST /programs/<name>/compute*).  Accepts "name",
        "name@latest", or "name@<version>"; requires the server to run
        with MISAKA_PROGRAMS_DIR (unknown programs answer 404).  None
        (default) keeps the legacy routes, which serve the seeded
        default program.

        `api_key` authenticates this session against a server with the
        edge armed (MISAKA_API_KEYS): sent as X-Misaka-Key on every
        request.  Defaults to the MISAKA_API_KEY env var, so ops scripts
        need no code change to authenticate.  A 401/403/429 surfaces as
        MisakaClientError with `.status` and (for 429) `.retry_after`.

        An `https://` base_url speaks TLS (server-side MISAKA_TLS_CERT/
        KEY): `ca` pins a CA bundle path (the `make cert` ca.cert, or
        the self-signed service cert itself); `tls_insecure=True` skips
        verification (lab use).  Default with neither: the system trust
        store.

        `wire` selects the bulk-lane encoding: "auto" (default) speaks
        the headered binary protocol (utils/wire.py — raw little-endian
        int32 + a 12-byte header, negotiated via Content-Type/Accept)
        when the server advertises `wire_binary` on /healthz, decimal
        text otherwise; "binary" forces it (no probe); "text" keeps the
        legacy decimal lanes.  MISAKA_CLIENT_WIRE overrides the
        default."""
        import os as _os

        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.retry_stale = bool(retry_stale)
        self.connect_retries = max(0, int(connect_retries))
        self.api_key = (
            api_key if api_key is not None
            else _os.environ.get("MISAKA_API_KEY") or None
        )
        split = urllib.parse.urlsplit(self.base_url)
        if split.scheme not in ("http", "https", ""):
            raise ValueError(
                f"unsupported scheme {split.scheme!r} (use http:// or "
                f"https://)"
            )
        self._tls = split.scheme == "https"
        self._ssl_ctx = None
        if self._tls:
            import ssl

            if tls_insecure:
                ctx = ssl._create_unverified_context()
            else:
                ctx = ssl.create_default_context(cafile=ca)
            self._ssl_ctx = ctx
        self._host = split.hostname or "localhost"
        # urllib's defaults, kept exactly
        self._port = split.port or (443 if self._tls else 80)
        self._prefix = split.path.rstrip("/")
        self._pool: list[http.client.HTTPConnection] = []
        self._pool_lock = threading.Lock()
        self._pool_size = max(0, int(pool_size))
        self.program = program
        wire_mode = wire or _os.environ.get("MISAKA_CLIENT_WIRE") or "auto"
        if wire_mode not in ("auto", "binary", "text"):
            raise ValueError(
                f"wire must be auto|binary|text, got {wire_mode!r}"
            )
        # None = auto (probe /healthz wire_binary once, lazily): the
        # headered binary form must never reach a server that would
        # compute on the header bytes as payload
        self._wire_binary: bool | None = (
            True if wire_mode == "binary"
            else False if wire_mode == "text" else None
        )

    def _compute_path(self, suffix: str) -> str:
        """`/compute*` or the program-addressed `/programs/<name>/compute*`
        twin when this session is pinned to a registry program."""
        if not self.program:
            return suffix
        return f"/programs/{urllib.parse.quote(self.program, safe='@')}" \
               f"{suffix}"

    def close(self) -> None:
        """Drop every pooled connection (sessions are reusable after)."""
        with self._pool_lock:
            pool, self._pool = self._pool, []
        for conn in pool:
            conn.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # --- plumbing ----------------------------------------------------------

    def _connection(self) -> http.client.HTTPConnection:
        if self._tls:
            return http.client.HTTPSConnection(
                self._host, self._port, timeout=self.timeout,
                context=self._ssl_ctx,
            )
        return http.client.HTTPConnection(
            self._host, self._port, timeout=self.timeout
        )

    def _checkout(self) -> tuple[http.client.HTTPConnection, bool]:
        """An idle pooled connection (reused=True) or a fresh one."""
        with self._pool_lock:
            if self._pool:
                return self._pool.pop(), True
        return self._connection(), False

    def _checkin(self, conn: http.client.HTTPConnection) -> None:
        with self._pool_lock:
            if len(self._pool) < self._pool_size:
                self._pool.append(conn)
                return
        conn.close()

    def _request(self, path: str, data: bytes | None, method: str) -> bytes:
        return self._request_full(path, data, method)[0]

    def _request_full(
        self, path: str, data: bytes | None, method: str,
        extra_headers: dict[str, str] | None = None,
    ) -> tuple[bytes, dict[str, str]]:
        """Like _request, but also returns the response headers the
        tracing surface rides (X-Misaka-Trace, Server-Timing)."""
        headers = dict(extra_headers) if extra_headers else {}
        if data is not None:
            # the server's bulk lanes answer 411 without a length;
            # http.client sets it for bytes bodies, but be explicit
            headers["Content-Length"] = str(len(data))
        if self.api_key is not None:
            headers["X-Misaka-Key"] = self.api_key
        refused = 0
        replays = 0
        fresh_replays = 0
        while True:
            conn, reused = self._checkout()
            try:
                conn.request(method, self._prefix + path, data, headers)
                resp = conn.getresponse()
            except (http.client.HTTPException, ConnectionError, OSError) as e:
                conn.close()
                if self.retry_stale and isinstance(
                    e, (http.client.RemoteDisconnected, ConnectionError,
                        BrokenPipeError)
                ) and (reused or (replays and fresh_replays < 1)):
                    # A pooled socket the server dropped between requests:
                    # the send failed or ZERO response bytes arrived —
                    # replay on a fresh connection (see __init__'s
                    # retry_stale for the at-least-once caveat).  Any
                    # other failure shape (e.g. a garbled partial status
                    # line) may mean a response was in flight — never
                    # replay those.  Reused-socket replays stay UNCAPPED:
                    # after a server restart the whole idle pool is stale
                    # and must drain, however many connections deep.
                    #
                    # `fresh_replays` additionally allows ONE replay of a
                    # failed FRESH dial, but only once a stale replay has
                    # begun: a kill -9'd SO_REUSEPORT worker keeps its
                    # listening socket for a beat after its threads are
                    # gone, so the replay's connect can land in the dying
                    # worker's backlog and be reset before any byte of
                    # response.  The at-least-once semantics are
                    # unchanged; a request's FIRST attempt on a fresh
                    # dial is still never replayed.
                    if not reused:
                        fresh_replays += 1
                    replays += 1
                    continue
                if (
                    not reused
                    and isinstance(e, ConnectionRefusedError)
                    and refused < self.connect_retries
                ):
                    # fresh dial refused: the server-restarting window.
                    # Nothing was sent, so retrying is exactly-once safe;
                    # back off exponentially to ride out the respawn (see
                    # __init__'s connect_retries).  Lazy import: the
                    # shared policy module is stdlib-only, but the happy
                    # path shouldn't even pay the import.
                    import time

                    from misaka_tpu.utils.backoff import Backoff

                    time.sleep(Backoff(base=0.1, cap=2.0).delay_for(refused))
                    refused += 1
                    continue
                raise urllib.error.URLError(e) from e
            try:
                body = resp.read()
            except (http.client.HTTPException, ConnectionError, OSError) as e:
                # response headers arrived: the request executed — a
                # mid-body failure must surface, never retry
                conn.close()
                raise urllib.error.URLError(e) from e
            if resp.will_close:
                conn.close()
            else:
                self._checkin(conn)
            resp_headers = {
                "X-Misaka-Trace": resp.getheader("X-Misaka-Trace"),
                "Server-Timing": resp.getheader("Server-Timing"),
            }
            if resp.status >= 400:
                retry_after = None
                ra = resp.getheader("Retry-After")
                if ra:
                    try:
                        retry_after = float(ra)
                    except ValueError:
                        pass  # HTTP-date form: surface the header's
                        # presence through the body text instead
                raise MisakaClientError(
                    resp.status, body.decode(errors="replace").strip(),
                    trace_id=resp_headers["X-Misaka-Trace"],
                    retry_after=retry_after,
                )
            return body, resp_headers

    def _post_form(self, path: str, **fields) -> bytes:
        return self._request(
            path, urllib.parse.urlencode(fields).encode(), "POST"
        )

    # --- the reference's five routes (master.go:90-224) --------------------

    def run(self) -> None:
        self._post_form("/run")

    def pause(self) -> None:
        self._post_form("/pause")

    def reset(self) -> None:
        self._post_form("/reset")

    def load(self, target: str, program: str) -> None:
        """Reprogram one node (resets the network, like the reference)."""
        self._post_form("/load", targetURI=target, program=program)

    def compute(self, value: int) -> int:
        """One value through POST /compute.  The returned int carries the
        response's tracing context: ``result.timings`` (parsed
        Server-Timing phases, ms) and ``result.trace_id``."""
        raw, headers = self._request_full(
            self._compute_path("/compute"),
            urllib.parse.urlencode({"value": str(int(value))}).encode(),
            "POST",
        )
        out = TracedInt(json.loads(raw)["value"])
        st = headers.get("Server-Timing")
        out.timings = _parse_server_timing(st) if st else {}
        out.trace_id = headers.get("X-Misaka-Trace")
        return out

    # --- bulk compute lanes -------------------------------------------------

    def _use_binary_wire(self) -> bool:
        """Lazy capability probe for wire="auto": one GET /healthz per
        client session decides whether the server speaks the headered
        binary protocol.  Fail-safe: any probe failure (old server, no
        route, network hiccup) latches text — the headered form must
        never reach a server that would compute on the header bytes."""
        cached = self._wire_binary
        if cached is None:
            try:
                cached = bool(self.healthz().get("wire_binary"))
            except Exception:
                cached = False
            self._wire_binary = cached
        return cached

    def compute_batch(self, values, spread: bool = True):
        """A value stream in ONE round trip.  Speaks the binary wire by
        default (the headered /compute_raw protocol — utils/wire.py) when
        the server supports it; the decimal text /compute_batch form is
        the fallback (and forced by wire="text" / MISAKA_CLIENT_WIRE).
        Returns an int32 numpy array (numpy imported here, not at module
        scope — the scalar/lifecycle surface stays stdlib-only)."""
        import numpy as np

        if self._use_binary_wire():
            return self.compute_raw(values, spread=spread)
        vals = np.ascontiguousarray(values, dtype=np.int32)
        body = b"values=" + b"+".join(b"%d" % v for v in vals.tolist())
        if spread:
            body += b"&spread=1"
        raw, headers = self._request_full(
            self._compute_path("/compute_batch"), body, "POST"
        )
        return _traced_array(
            np.asarray(json.loads(raw)["values"], dtype=np.int32), headers
        )

    def compute_raw(self, values, spread: bool = True):
        """The wire-efficient lane: raw little-endian int32 both ways —
        headered binary protocol (framing-validated, utils/wire.py) when
        negotiated, the legacy headerless raw form otherwise.  Returns an
        int32 numpy array."""
        import numpy as np

        vals = np.ascontiguousarray(values, dtype="<i4")
        path = self._compute_path("/compute_raw") \
            + "?spread=" + ("1" if spread else "0")
        if self._use_binary_wire():
            from misaka_tpu.utils import wire as _wire

            raw, headers = self._request_full(
                path, _wire.pack(vals.tobytes()), "POST",
                extra_headers={
                    "Content-Type": _wire.CONTENT_TYPE,
                    "Accept": _wire.CONTENT_TYPE,
                },
            )
            payload = _wire.unpack(raw)
            return _traced_array(
                np.frombuffer(payload, dtype="<i4").copy(), headers
            )
        raw, headers = self._request_full(path, vals.tobytes(), "POST")
        return _traced_array(np.frombuffer(raw, dtype="<i4").copy(), headers)

    # --- observability ------------------------------------------------------

    def status(self) -> dict:
        return json.loads(self._request("/status", None, "GET"))

    def healthz(self) -> dict:
        """Cheap liveness (no server-side state lock): engine + uptime."""
        return json.loads(self._request("/healthz", None, "GET"))

    def native_edge(self) -> dict | None:
        """The C++ edge tier's /healthz block (r19), or None when the
        CPython worker tier owns the public port — which tier terminated
        this client's bytes, without parsing Server headers."""
        return self.healthz().get("native_edge")

    def metrics(self) -> str:
        """Raw Prometheus text exposition from GET /metrics (parse with
        misaka_tpu.utils.metrics.parse_text where numpy/jax are absent —
        the parser is stdlib-only like this client)."""
        return self._request("/metrics", None, "GET").decode()

    def trace(self, last: int | None = None) -> list[dict]:
        """Decoded INSTRUCTION history (GET /debug/isa_trace — renamed
        from /trace, which the server keeps as a deprecated alias)."""
        path = "/debug/isa_trace" if last is None \
            else f"/debug/isa_trace?last={int(last)}"
        return json.loads(self._request(path, None, "GET"))["entries"]

    def debug_requests(self, slowest: bool = False) -> dict:
        """The request-trace flight recorder (GET /debug/requests):
        recent + slowest completed traces, summaries only."""
        path = "/debug/requests" + ("?slowest=1" if slowest else "")
        return json.loads(self._request(path, None, "GET"))

    def debug_request(self, trace_id: str) -> dict:
        """One completed trace's full span tree."""
        return json.loads(
            self._request(f"/debug/requests/{trace_id}", None, "GET")
        )

    def perfetto(self) -> dict:
        """The flight recorder as Chrome trace-event JSON — dump it to a
        file and load in https://ui.perfetto.dev."""
        return json.loads(self._request("/debug/perfetto", None, "GET"))

    def usage(self) -> dict:
        """The per-program resource ledger (GET /debug/usage): requests,
        values, CPU-seconds, measured native-pool seconds, and
        queue-delay seconds per program (runtime/usage.py)."""
        return json.loads(self._request("/debug/usage", None, "GET"))

    def usage_export(self, since: float = 0.0,
                     verify_secret: str | None = None) -> list[dict]:
        """Billing-grade usage export (GET /usage/export, admin-gated):
        HMAC-signed JSONL periods of cumulative per-tenant counters from
        the durable ledger, one parsed dict per line.  ``since`` (unix
        seconds) bounds the window.  Pass ``verify_secret`` (the plane
        secret) to verify every signature locally — a tampered or
        unsigned line raises MisakaClientError.  Against a fleet hub the
        stream carries every replica's and peer's lines verbatim behind
        ``{"kind": "source"}`` envelopes."""
        raw = self._request(f"/usage/export?since={since:g}", None, "GET")
        if isinstance(raw, bytes):
            raw = raw.decode()
        lines = [
            json.loads(ln) for ln in raw.splitlines() if ln.strip()
        ]
        if verify_secret is not None:
            from misaka_tpu.runtime import usage as usage_mod

            sec = (verify_secret.encode()
                   if isinstance(verify_secret, str) else verify_secret)
            try:
                usage_mod.totals_from_lines(lines, secret=sec)
            except usage_mod.UsageExportError as e:
                raise MisakaClientError(200, str(e)) from None
        return lines

    def alerts(self) -> dict:
        """The SLO burn-rate engine's state (GET /debug/alerts):
        per-program ok/warning/page with per-window burn rates and
        latency quantiles (utils/slo.py; objectives via MISAKA_SLO or
        per-program upload metadata)."""
        return json.loads(self._request("/debug/alerts", None, "GET"))

    def flamegraph(self) -> dict:
        """The continuous profiler's folded-stack aggregate + native
        busy/idle split (GET /debug/flamegraph; append ?html=1 in a
        browser for the self-contained viewer)."""
        return json.loads(self._request("/debug/flamegraph", None, "GET"))

    def series(self, name: str | None = None, window: str = "1h",
               labels: dict | None = None) -> dict:
        """Retained metric history from the embedded TSDB (GET
        /debug/series, utils/tsdb.py).

        ``series()`` with no name lists the catalog (series counts,
        retention stages, drop counters).  With ``name`` — a counter
        (returned as a rate), a gauge, or a derived histogram series
        (``<hist>:p50`` / ``:p99`` / ``:rate``) — returns every matching
        series over the trailing ``window`` ("30s"/"5m"/"1h"/"7d" or
        bare seconds — day windows answer from the durable long-horizon
        tier when MISAKA_TSDB_DIR is armed), each as
        ``{labels, stage_s, points: [[unix, avg,
        max], ...]}``.  ``labels`` filters by exact label values; on a
        fleet endpoint every replica's series carries ``replica="<i>"``.
        Raises MisakaClientError on a malformed window or filter (400)."""
        from urllib.parse import quote

        if name is None:
            return json.loads(self._request("/debug/series", None, "GET"))
        path = (
            f"/debug/series?name={quote(name, safe=':')}"
            f"&window={quote(str(window))}"
        )
        for k, v in (labels or {}).items():
            path += f"&label={quote(f'{k}={v}')}"
        return json.loads(self._request(path, None, "GET"))

    def canary_status(self) -> dict | None:
        """The synthetic canary's last cycle (runtime/canary.py), from
        the /healthz ``canary`` block: per-tier outcomes, the
        first-failing-tier attribution, and the consecutive full-stack
        failure count.  None when the server runs no canary
        (MISAKA_CANARY=0, or a bare test server)."""
        return self.healthz().get("canary")

    # --- the engine fleet (server must run with MISAKA_FLEET >= 1) ----------

    def fleet_status(self) -> dict:
        """The fleet manager's state (GET /fleet): per-replica rows
        (state, pid, port, restarts), restart/roll totals, and the
        aggregate `degraded` flag (runtime/fleet.py)."""
        return json.loads(self._request("/fleet", None, "GET"))

    def fleet_roll(self, timeout: float | None = None) -> dict:
        """Zero-loss rolling restart of every engine replica (POST
        /fleet/roll): drain to quiescence -> manifest-verified checkpoint
        -> replace -> bit-identical restore -> readmit, one replica at a
        time.  Synchronous — returns the per-replica report; pass a
        generous `timeout` (each replica pays an engine boot).  409 when
        a roll is already in progress."""
        if timeout is None:
            timeout = max(self.timeout, 120.0 * 4)
        # deliberately NOT the pooled _request path: a roll blocks for
        # minutes (one engine boot per replica), and parking a pooled
        # keep-alive connection on it — or mutating its timeout — would
        # poison the pool for every concurrent compute call
        conn = self._connection()
        conn.timeout = timeout  # applied at connect time
        try:
            headers = {"Content-Length": "0"}
            if self.api_key is not None:
                headers["X-Misaka-Key"] = self.api_key
            conn.request("POST", self._prefix + "/fleet/roll", b"", headers)
            resp = conn.getresponse()
            body = resp.read()
            if resp.status >= 400:
                raise MisakaClientError(
                    resp.status, body.decode(errors="replace").strip()
                )
            return json.loads(body)
        finally:
            conn.close()

    # --- the program registry (server must run with MISAKA_PROGRAMS_DIR) ---

    def upload_program(self, name: str, program: str | None = None,
                       topology: "dict | str | None" = None,
                       compose: str | None = None,
                       verify: str | None = None) -> dict:
        """Publish one program version (POST /programs) and return the
        server's {"name", "version", "created", "latest", "swapped"}.

        Exactly one source form: `program` is bare TIS text (served as a
        single-node network), `topology` a {"nodes": ..., "programs": ...}
        dict or JSON string, `compose` a reference docker-compose YAML
        text.  Identical sources dedup to one content-addressed version;
        publishing a new version over a live engine hot-swaps it with
        zero client-visible errors.

        verify="replay" gates the hot-swap on shadow replay of the live
        capture (POST /programs?verify=replay): the candidate must
        byte-for-byte reproduce every captured response before any
        bookkeeping or swap happens.  A divergence surfaces as
        MisakaClientError(status=409) with ``.diffs`` carrying the
        per-request records; see ``replay()``."""
        fields: dict[str, str] = {"name": name}
        if program is not None:
            fields["program"] = program
        if topology is not None:
            fields["topology"] = (
                topology if isinstance(topology, str) else json.dumps(topology)
            )
        if compose is not None:
            fields["compose"] = compose
        path = "/programs"
        if verify is not None:
            path += "?verify=" + urllib.parse.quote(verify, safe="")
        return json.loads(self._post_form(path, **fields))

    def list_programs(self) -> dict:
        """The registry catalog (GET /programs): every name's versions,
        aliases, and which engines are active."""
        return json.loads(self._request("/programs", None, "GET"))

    def program_info(self, name: str) -> dict:
        """One program's detail (GET /programs/<name>)."""
        return json.loads(
            self._request(
                f"/programs/{urllib.parse.quote(name, safe='')}", None, "GET"
            )
        )

    # --- checkpoint / profiling (additive; server must have dirs enabled) --

    def checkpoint(self, name: str) -> None:
        self._post_form("/checkpoint", name=name)

    def restore(self, name: str) -> None:
        self._post_form("/restore", name=name)

    def profile_start(self, name: str = "profile") -> None:
        self._post_form("/profile/start", name=name)

    def profile_stop(self) -> str:
        return self._request("/profile/stop", b"", "POST").decode()

    # --- traffic capture & shadow replay (runtime/capture.py) --------------
    # Admin-gated when edge auth is configured: construct the client with
    # the admin api_key or the edge answers 403.

    def capture_start(self) -> dict:
        """Arm the wire recorder (POST /captures/start): anchors every
        active engine's state and records sampled request/response pairs
        into the bounded ring.  Returns the recorder status.  409 when
        already recording or killed via MISAKA_CAPTURE=0."""
        return json.loads(self._post_form("/captures/start"))

    def capture_stop(self) -> dict:
        """Disarm the recorder; the ring stays readable for export and
        ?verify=replay until the next capture_start()."""
        return json.loads(self._post_form("/captures/stop"))

    def capture_export(self, path: str | None = None) -> dict:
        """Write the captured ring + per-program anchor checkpoints to
        disk ON THE SERVER (POST /captures/export) and return
        {"path", "records", "dropped", "anchors"}.  path=None lets the
        server pick a timestamped file under MISAKA_CAPTURE_DIR."""
        fields = {"path": path} if path else {}
        return json.loads(self._request(
            "/captures/export",
            urllib.parse.urlencode(fields).encode(), "POST",
        ))

    def capture_status(self, n: int = 0) -> dict:
        """The recorder's live status + the newest ``n`` records with
        value previews (GET /debug/captures?n=...)."""
        return json.loads(
            self._request(f"/debug/captures?n={int(n)}", None, "GET")
        )

    def replay(self, name: str, program: str | None = None,
               topology: "dict | str | None" = None,
               compose: str | None = None) -> dict:
        """Replay-verified publish: upload_program(verify="replay").

        Green replay -> the publish proceeds and the server's publish
        payload returns.  Divergence -> MisakaClientError with
        status=409 and ``.diffs`` listing every captured request the
        candidate answered differently (trace ID, stream offset,
        expected/actual heads) — nothing was swapped or recorded."""
        return self.upload_program(
            name, program=program, topology=topology, compose=compose,
            verify="replay",
        )
