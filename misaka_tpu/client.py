"""Typed Python client for the master's HTTP surface.

The reference ships no client at all — its README drives the five routes
with curl (README.md "Usage"; master.go:90-224).  This wraps those five
byte-compatible routes plus every additive route this build serves, with
the two bulk lanes a throughput client actually wants:

  compute(v)          POST /compute        one value, int -> int
  compute_batch(vals) POST /compute_batch  decimal text, vectorized codec
  compute_raw(vals)   POST /compute_raw    raw little-endian int32 bodies
                                           (the fleet-client wire format)
  run/pause/reset     POST /run /pause /reset
  load(target, prog)  POST /load
  status()/trace()    GET  /status /trace
  healthz()/metrics() GET  /healthz /metrics  (liveness + Prometheus text)
  checkpoint/restore  POST /checkpoint /restore  (server-side .npz)
  profile_start/stop  POST /profile/start /profile/stop

The module imports stdlib only (numpy lazily, inside the two bulk
methods) and none of the jax-backed misaka_tpu packages — the scalar and
lifecycle surface is importable on any ops box.

Transport: every request rides a POOLED persistent HTTP/1.1 connection
(the server keeps keep-alive since r8) — the reference pays TCP setup +
teardown per transferred value; a fleet client must not.  A connection
dropped by the server (restart, idle timeout) reconnects cleanly: the
retry happens only when the failure hit a REUSED pooled socket before a
response arrived, so a request is never silently replayed against a
connection that might have executed it.
"""

from __future__ import annotations

import http.client
import json
import threading
import urllib.error
import urllib.parse


class MisakaClientError(RuntimeError):
    """Non-2xx response from the master (carries status + body text)."""

    def __init__(self, status: int, body: str):
        super().__init__(f"HTTP {status}: {body}")
        self.status = status
        self.body = body


class MisakaClient:
    """A client session against one master (`base_url`, default port 8000).

    Methods raise MisakaClientError on any non-2xx response (e.g. 400
    "network is not running", 500 compute timeout) and wrap connection
    failures in urllib.error.URLError (the documented socket-error shape
    since r1; the transport is http.client underneath).

    Thread-safe: concurrent callers draw idle connections from a shared
    pool (LIFO — the hottest socket stays warm) and return them after
    each response; `pool_size` caps how many idle sockets are retained.
    """

    def __init__(self, base_url: str = "http://localhost:8000",
                 timeout: float = 30.0, pool_size: int = 4,
                 retry_stale: bool = True, connect_retries: int = 3):
        """`retry_stale` (default True) replays a request ONCE when a
        POOLED connection proves dead at send time or before any
        response byte arrives — the stale-keep-alive case.  This is
        at-least-once: in the rare window where the server executed the
        request and died before writing a byte, the replay executes it
        twice.  Pass False for strict at-most-once (stale pooled sockets
        then surface as URLError and the caller decides).

        `connect_retries` (default 3) retries a request whose FRESH
        connection was refused outright — the server-restarting window
        (a supervisor respawning a frontend worker, a rolling deploy) —
        with exponential backoff (0.1s doubling, jittered).  Distinct
        from `retry_stale` and always safe: connection refused means the
        kernel rejected the dial, so nothing was ever sent to execute.
        Pass 0 to surface the first refusal as URLError immediately."""
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.retry_stale = bool(retry_stale)
        self.connect_retries = max(0, int(connect_retries))
        split = urllib.parse.urlsplit(self.base_url)
        if split.scheme not in ("http", ""):
            raise ValueError(
                f"unsupported scheme {split.scheme!r} (the master speaks "
                f"plain HTTP; TLS terminates at the deployment layer)"
            )
        self._host = split.hostname or "localhost"
        self._port = split.port or 80  # urllib's default, kept exactly
        self._prefix = split.path.rstrip("/")
        self._pool: list[http.client.HTTPConnection] = []
        self._pool_lock = threading.Lock()
        self._pool_size = max(0, int(pool_size))

    def close(self) -> None:
        """Drop every pooled connection (sessions are reusable after)."""
        with self._pool_lock:
            pool, self._pool = self._pool, []
        for conn in pool:
            conn.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # --- plumbing ----------------------------------------------------------

    def _checkout(self) -> tuple[http.client.HTTPConnection, bool]:
        """An idle pooled connection (reused=True) or a fresh one."""
        with self._pool_lock:
            if self._pool:
                return self._pool.pop(), True
        return (
            http.client.HTTPConnection(
                self._host, self._port, timeout=self.timeout
            ),
            False,
        )

    def _checkin(self, conn: http.client.HTTPConnection) -> None:
        with self._pool_lock:
            if len(self._pool) < self._pool_size:
                self._pool.append(conn)
                return
        conn.close()

    def _request(self, path: str, data: bytes | None, method: str) -> bytes:
        headers = {}
        if data is not None:
            # the server's bulk lanes answer 411 without a length;
            # http.client sets it for bytes bodies, but be explicit
            headers["Content-Length"] = str(len(data))
        refused = 0
        while True:
            conn, reused = self._checkout()
            try:
                conn.request(method, self._prefix + path, data, headers)
                resp = conn.getresponse()
            except (http.client.HTTPException, ConnectionError, OSError) as e:
                conn.close()
                if self.retry_stale and reused and isinstance(
                    e, (http.client.RemoteDisconnected, ConnectionError,
                        BrokenPipeError)
                ):
                    # a pooled socket the server dropped between requests:
                    # the send failed or ZERO response bytes arrived —
                    # replay once on a fresh connection (see __init__'s
                    # retry_stale for the at-least-once caveat).  Any
                    # other failure shape (e.g. a garbled partial status
                    # line) may mean a response was in flight — never
                    # replay those.
                    continue
                if (
                    not reused
                    and isinstance(e, ConnectionRefusedError)
                    and refused < self.connect_retries
                ):
                    # fresh dial refused: the server-restarting window.
                    # Nothing was sent, so retrying is exactly-once safe;
                    # back off exponentially to ride out the respawn (see
                    # __init__'s connect_retries).  Lazy import: the
                    # shared policy module is stdlib-only, but the happy
                    # path shouldn't even pay the import.
                    import time

                    from misaka_tpu.utils.backoff import Backoff

                    time.sleep(Backoff(base=0.1, cap=2.0).delay_for(refused))
                    refused += 1
                    continue
                raise urllib.error.URLError(e) from e
            try:
                body = resp.read()
            except (http.client.HTTPException, ConnectionError, OSError) as e:
                # response headers arrived: the request executed — a
                # mid-body failure must surface, never retry
                conn.close()
                raise urllib.error.URLError(e) from e
            if resp.will_close:
                conn.close()
            else:
                self._checkin(conn)
            if resp.status >= 400:
                raise MisakaClientError(
                    resp.status, body.decode(errors="replace").strip()
                )
            return body

    def _post_form(self, path: str, **fields) -> bytes:
        return self._request(
            path, urllib.parse.urlencode(fields).encode(), "POST"
        )

    # --- the reference's five routes (master.go:90-224) --------------------

    def run(self) -> None:
        self._post_form("/run")

    def pause(self) -> None:
        self._post_form("/pause")

    def reset(self) -> None:
        self._post_form("/reset")

    def load(self, target: str, program: str) -> None:
        """Reprogram one node (resets the network, like the reference)."""
        self._post_form("/load", targetURI=target, program=program)

    def compute(self, value: int) -> int:
        raw = self._post_form("/compute", value=str(int(value)))
        return int(json.loads(raw)["value"])

    # --- bulk compute lanes -------------------------------------------------

    def compute_batch(self, values, spread: bool = True):
        """A value stream in ONE round trip (decimal text wire format).
        Returns an int32 numpy array (numpy imported here, not at module
        scope — the scalar/lifecycle surface stays stdlib-only)."""
        import numpy as np

        vals = np.ascontiguousarray(values, dtype=np.int32)
        body = b"values=" + b"+".join(b"%d" % v for v in vals.tolist())
        if spread:
            body += b"&spread=1"
        raw = self._request("/compute_batch", body, "POST")
        return np.asarray(json.loads(raw)["values"], dtype=np.int32)

    def compute_raw(self, values, spread: bool = True):
        """The wire-efficient lane: raw little-endian int32 both ways.
        Returns an int32 numpy array."""
        import numpy as np

        vals = np.ascontiguousarray(values, dtype="<i4")
        path = "/compute_raw?spread=" + ("1" if spread else "0")
        raw = self._request(path, vals.tobytes(), "POST")
        return np.frombuffer(raw, dtype="<i4").copy()

    # --- observability ------------------------------------------------------

    def status(self) -> dict:
        return json.loads(self._request("/status", None, "GET"))

    def healthz(self) -> dict:
        """Cheap liveness (no server-side state lock): engine + uptime."""
        return json.loads(self._request("/healthz", None, "GET"))

    def metrics(self) -> str:
        """Raw Prometheus text exposition from GET /metrics (parse with
        misaka_tpu.utils.metrics.parse_text where numpy/jax are absent —
        the parser is stdlib-only like this client)."""
        return self._request("/metrics", None, "GET").decode()

    def trace(self, last: int | None = None) -> list[dict]:
        path = "/trace" if last is None else f"/trace?last={int(last)}"
        return json.loads(self._request(path, None, "GET"))["entries"]

    # --- checkpoint / profiling (additive; server must have dirs enabled) --

    def checkpoint(self, name: str) -> None:
        self._post_form("/checkpoint", name=name)

    def restore(self, name: str) -> None:
        self._post_form("/restore", name=name)

    def profile_start(self, name: str = "profile") -> None:
        self._post_form("/profile/start", name=name)

    def profile_stop(self) -> str:
        return self._request("/profile/stop", b"", "POST").decode()
