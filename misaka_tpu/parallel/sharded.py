"""Lane-sharded superstep: shard_map + explicit XLA collectives over ICI.

This is the multi-chip version of core/step.py.  Each shard owns a contiguous
slice of program-node lanes (their registers, ports, hold latches, and code);
stacks and master I/O rings are replicated and kept consistent by applying
collectively-agreed updates on every shard.  Where the single-chip kernel
resolves arbitration with a cumsum over the full lane axis, the sharded kernel
agrees globally with three tiny collectives per tick:

  all_gather (port occupancy)  — senders must see every shard's port state
  pmin       (winner election) — lowest-global-lane arbitration for ports,
                                 stacks, IN and OUT (same discipline as
                                 core/step.py, now cross-chip)
  psum       (value broadcast) — the unique winner's value reaches the shard
                                 that owns the destination / applies the
                                 replicated stack/ring update

All three ride ICI inside one jitted scan; there is no host round-trip and no
per-message dial (the reference's transport cost, program.go:492-565).

The replacement map for the reference's gRPC data plane (messenger.proto:9-41):
  Program.Send  -> all_gather + pmin + psum routing into the dest shard's port
  Stack.Push/Pop-> pmin election + replicated stack update
  Master.GetInput/SendOutput -> pmin election + replicated ring update
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

from misaka_tpu.core import regs64
from misaka_tpu.core.state import NetworkState, rebase_rings
from misaka_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, state_specs
from misaka_tpu.tis import isa

_I32 = jnp.int32
# "no contender" sentinel for pmin elections.  A numpy scalar, NOT jnp: a
# module-level jnp constant would initialize the XLA backend at import time,
# which breaks jax.distributed.initialize (it must run before any backend
# touch — parallel/multihost.py).
_BIG = np.int32(2**31 - 1)


def _elect(contender: jnp.ndarray, lane_global: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Global lowest-lane election over `model` for [Nl, K] contender matrix.

    Returns (winner_key [K] — global lane id or _BIG, local_win [Nl, K]).
    """
    local_key = jnp.min(
        jnp.where(contender, lane_global[:, None], _BIG), axis=0, initial=_BIG
    )
    winner_key = jax.lax.pmin(local_key, MODEL_AXIS)
    local_win = contender & (lane_global[:, None] == winner_key[None, :])
    return winner_key, local_win


def _winner_val(local_win: jnp.ndarray, values: jnp.ndarray) -> jnp.ndarray:
    """psum-broadcast the unique winner's value: [Nl,K] mask x [Nl] -> [K]."""
    partial = (local_win.astype(_I32) * values[:, None]).sum(axis=0)
    return jax.lax.psum(partial, MODEL_AXIS)


def step_local(code: jnp.ndarray, prog_len: jnp.ndarray, state: NetworkState,
               n_total_lanes: int) -> NetworkState:
    """One superstep on this shard's lane slice (single network instance).

    Mirrors core/step.py phase by phase; comments there apply.  Lane-local
    arrays have shape [Nl]; stack/ring state is `model`-replicated.
    """
    n_local, _, _ = code.shape
    n_ports = isa.NUM_PORTS
    n_dests = n_total_lanes * n_ports
    n_stacks, stack_cap = state.stack_mem.shape
    in_cap = state.in_buf.shape[0]
    out_cap = state.out_buf.shape[0]
    shard = jax.lax.axis_index(MODEL_AXIS)
    lane_offset = shard * n_local
    lane_l = jnp.arange(n_local)
    lane_global = lane_offset + lane_l

    # --- fetch & decode (local) -------------------------------------------
    fields = code[lane_l, state.pc]
    op = fields[:, isa.F_OP]
    src = fields[:, isa.F_SRC]
    imm = fields[:, isa.F_IMM]
    dst = fields[:, isa.F_DST]
    tgt = fields[:, isa.F_TGT]
    tport = fields[:, isa.F_PORT]
    jmp = fields[:, isa.F_JMP]

    # --- phase A: consume ready port sources into the hold latch (local) ---
    is_port_src = src >= isa.SRC_R0
    pidx = jnp.clip(src - isa.SRC_R0, 0, n_ports - 1)
    port_v = state.port_val[lane_l, pidx]
    port_f = state.port_full[lane_l, pidx]
    reads_src = jnp.isin(op, jnp.asarray(isa.READS_SRC, dtype=_I32))
    reads_port = reads_src & is_port_src
    consume_now = reads_port & ~state.holding & port_f
    holding = state.holding | consume_now
    hold_val = jnp.where(consume_now, port_v, state.hold_val)
    src_val = jnp.where(
        src == isa.SRC_IMM,
        imm,
        jnp.where(
            src == isa.SRC_ACC,
            state.acc,
            jnp.where(src == isa.SRC_NIL, jnp.zeros_like(imm), hold_val),
        ),
    )
    # 64-bit source view (core/regs64.py): src_val stays the wire word
    src_hi = jnp.where(src == isa.SRC_ACC, state.acc_hi, regs64.sext(src_val))
    src_ok = ~reads_port | holding

    consume_onehot = consume_now[:, None] & (pidx[:, None] == jnp.arange(n_ports)[None, :])
    port_full_after_reads = state.port_full & ~consume_onehot

    # --- phase B: sends — the collective routing fabric --------------------
    # Senders need every shard's occupancy: all_gather [mp, Nl, 4] -> [D].
    global_full = jax.lax.all_gather(port_full_after_reads, MODEL_AXIS).reshape(n_dests)
    want_send = (op == isa.OP_MOV_NET) & src_ok
    dest = tgt * n_ports + tport
    dest_onehot = want_send[:, None] & (dest[:, None] == jnp.arange(n_dests)[None, :])
    contender = dest_onehot & ~global_full[None, :]
    send_key, send_win = _elect(contender, lane_global)
    send_won = send_win.any(axis=1)
    delivered = send_key < _BIG                      # [D] — replicated value
    deliver_val = _winner_val(send_win, src_val)     # [D] — replicated value
    # Each shard applies only its own slice of the dest axis.
    my_delivered = jax.lax.dynamic_slice_in_dim(
        delivered, lane_offset * n_ports, n_local * n_ports
    ).reshape(n_local, n_ports)
    my_deliver_val = jax.lax.dynamic_slice_in_dim(
        deliver_val, lane_offset * n_ports, n_local * n_ports
    ).reshape(n_local, n_ports)
    new_port_full = port_full_after_reads | my_delivered
    new_port_val = jnp.where(my_delivered, my_deliver_val, state.port_val)

    # --- stacks: elect one op per stack per tick, apply replicated ---------
    is_push = op == isa.OP_PUSH
    is_pop = op == isa.OP_POP
    tgt_stack = jnp.clip(tgt, 0, n_stacks - 1)
    top_at_tgt = state.stack_top[tgt_stack]
    want_sop = (is_push & src_ok & (top_at_tgt < stack_cap)) | (is_pop & (top_at_tgt > 0))
    stack_onehot = want_sop[:, None] & (tgt_stack[:, None] == jnp.arange(n_stacks)[None, :])
    _, stack_win = _elect(stack_onehot, lane_global)
    sop_won = stack_win.any(axis=1)
    push_per_stack = (
        jax.lax.psum((stack_win & is_push[:, None]).astype(_I32).sum(axis=0), MODEL_AXIS) > 0
    )
    pop_per_stack = (
        jax.lax.psum((stack_win & is_pop[:, None]).astype(_I32).sum(axis=0), MODEL_AXIS) > 0
    )
    push_val = _winner_val(stack_win & is_push[:, None], src_val)
    pop_val_lane = state.stack_mem[tgt_stack, jnp.clip(top_at_tgt - 1, 0, stack_cap - 1)]

    # --- master I/O rings: global single-slot elections --------------------
    in_avail = (state.in_wr - state.in_rd) > 0
    want_in = (op == isa.OP_IN) & in_avail
    in_key, in_win_m = _elect(want_in[:, None], lane_global)
    in_win = in_win_m[:, 0]
    in_any = in_key[0] < _BIG
    in_val = state.in_buf[state.in_rd % in_cap]

    out_free = (state.out_wr - state.out_rd) < out_cap
    want_out = (op == isa.OP_OUT) & src_ok & out_free
    out_key, out_win_m = _elect(want_out[:, None], lane_global)
    out_win = out_win_m[:, 0]
    out_any = out_key[0] < _BIG
    out_val = _winner_val(out_win_m, src_val)[0]

    # --- commit + local register/pc updates --------------------------------
    dst_ok = jnp.where(
        op == isa.OP_MOV_NET,
        send_won,
        jnp.where(
            is_push | is_pop,
            sop_won,
            jnp.where(op == isa.OP_IN, in_win, jnp.where(op == isa.OP_OUT, out_win, True)),
        ),
    )
    commit = src_ok & dst_ok

    # 64-bit (hi, lo) register arithmetic — identical discipline to
    # core/step.py; see core/regs64.py
    incoming = jnp.where(is_pop, pop_val_lane, jnp.where(op == isa.OP_IN, in_val, src_val))
    incoming_hi = jnp.where(op == isa.OP_MOV_LOCAL, src_hi, regs64.sext(incoming))
    writes_acc = ((op == isa.OP_MOV_LOCAL) | is_pop | (op == isa.OP_IN)) & (dst == isa.DST_ACC)
    acc = state.acc
    acc_hi = state.acc_hi
    add_hi, add_lo = regs64.add64(acc_hi, acc, src_hi, src_val)
    sub_hi, sub_lo = regs64.sub64(acc_hi, acc, src_hi, src_val)
    neg_hi, neg_lo = regs64.neg64(acc_hi, acc)
    new_acc = jnp.where(commit & writes_acc, incoming, acc)
    new_acc_hi = jnp.where(commit & writes_acc, incoming_hi, acc_hi)
    new_acc = jnp.where(commit & (op == isa.OP_ADD), add_lo, new_acc)
    new_acc_hi = jnp.where(commit & (op == isa.OP_ADD), add_hi, new_acc_hi)
    new_acc = jnp.where(commit & (op == isa.OP_SUB), sub_lo, new_acc)
    new_acc_hi = jnp.where(commit & (op == isa.OP_SUB), sub_hi, new_acc_hi)
    new_acc = jnp.where(commit & (op == isa.OP_NEG), neg_lo, new_acc)
    new_acc_hi = jnp.where(commit & (op == isa.OP_NEG), neg_hi, new_acc_hi)
    new_acc = jnp.where(commit & (op == isa.OP_SWP), state.bak, new_acc)
    new_acc_hi = jnp.where(commit & (op == isa.OP_SWP), state.bak_hi, new_acc_hi)
    saves_bak = commit & ((op == isa.OP_SWP) | (op == isa.OP_SAV))
    new_bak = jnp.where(saves_bak, acc, state.bak)
    new_bak_hi = jnp.where(saves_bak, acc_hi, state.bak_hi)

    # --- replicated stack/ring updates (identical on every shard) ----------
    stack_ids = jnp.arange(n_stacks)
    push_slot = jnp.clip(state.stack_top, 0, stack_cap - 1)
    cur_slot_val = state.stack_mem[stack_ids, push_slot]
    new_stack_mem = state.stack_mem.at[stack_ids, push_slot].set(
        jnp.where(push_per_stack, push_val, cur_slot_val)
    )
    new_stack_top = state.stack_top + push_per_stack.astype(_I32) - pop_per_stack.astype(_I32)

    new_in_rd = state.in_rd + in_any.astype(_I32)
    out_slot = state.out_wr % out_cap
    new_out_buf = state.out_buf.at[out_slot].set(
        jnp.where(out_any, out_val, state.out_buf[out_slot])
    )
    new_out_wr = state.out_wr + out_any.astype(_I32)

    jump_taken = (
        (op == isa.OP_JMP)
        | ((op == isa.OP_JEZ) & regs64.is_zero(acc_hi, acc))
        | ((op == isa.OP_JNZ) & ~regs64.is_zero(acc_hi, acc))
        | ((op == isa.OP_JGZ) & regs64.is_pos(acc_hi, acc))
        | ((op == isa.OP_JLZ) & regs64.is_neg(acc_hi, acc))
    )
    pc_inc = (state.pc + 1) % prog_len
    pc_jro = regs64.jro_target(state.pc, src_hi, src_val, prog_len)
    new_pc = jnp.where(jump_taken, jmp, jnp.where(op == isa.OP_JRO, pc_jro, pc_inc))
    new_pc = jnp.where(commit, new_pc, state.pc)

    return NetworkState(
        acc=new_acc, bak=new_bak, acc_hi=new_acc_hi, bak_hi=new_bak_hi,
        pc=new_pc,
        port_val=new_port_val, port_full=new_port_full,
        hold_val=hold_val, holding=holding & ~commit,
        stack_mem=new_stack_mem, stack_top=new_stack_top,
        in_buf=state.in_buf, in_rd=new_in_rd, in_wr=state.in_wr,
        out_buf=new_out_buf, out_rd=state.out_rd, out_wr=new_out_wr,
        tick=state.tick + 1,
        retired=state.retired + commit.astype(_I32),
    )


def make_sharded_runner(code, prog_len, mesh, num_steps: int, batched: bool = True):
    """Build a jitted chunk runner: state -> state, lane-sharded over `model`.

    code [N,L,F] / prog_len [N] are sharded over `model`; the state follows
    mesh.state_specs.  N must divide evenly by the mesh's model-axis size.
    """
    n_total = code.shape[0]
    mp = mesh.shape[MODEL_AXIS]
    if n_total % mp:
        raise ValueError(f"{n_total} lanes not divisible by model axis size {mp}")

    specs = state_specs(batched)
    step1 = functools.partial(step_local, n_total_lanes=n_total)

    def chunk(code_l, prog_len_l, state):
        step_fn = step1 if not batched else jax.vmap(step1, in_axes=(None, None, 0))

        def body(s, _):
            return step_fn(code_l, prog_len_l, s), None

        out, _ = jax.lax.scan(body, state, None, length=num_steps)
        return rebase_rings(out)

    sharded = shard_map(
        chunk,
        mesh=mesh,
        in_specs=(P(MODEL_AXIS, None, None), P(MODEL_AXIS), specs),
        out_specs=specs,
        check_vma=False,
    )

    # make_array_from_callback (not device_put): each process contributes only
    # the table shards its local devices own, so the same path works on a
    # single host and across a multi-host DCN mesh (parallel/multihost.py).
    def _put(arr, spec):
        arr = np.asarray(arr, dtype=np.int32)
        return jax.make_array_from_callback(
            arr.shape, NamedSharding(mesh, spec), lambda idx: arr[idx]
        )

    code_sh = _put(code, P(MODEL_AXIS, None, None))
    len_sh = _put(prog_len, P(MODEL_AXIS))
    jitted = jax.jit(functools.partial(sharded, code_sh, len_sh), donate_argnums=(0,))
    return jitted
