"""Lane-sharded superstep, first generation: per-tick occupancy all_gather.

NOTE: this kernel is no longer the default model-parallel engine — the
statically-routed two-collective kernel (parallel/routed.py) replaced it
after it measured 0.73x single-chip speed at mp=8 (BENCH_sharded r3).  It
stays servable behind MasterNode(engine="gather") as the A/B baseline the
routed design is benched against.

This is the multi-chip version of core/step.py.  Each shard owns a contiguous
slice of program-node lanes (their registers, ports, hold latches, and code);
stacks and master I/O rings are replicated and kept consistent by applying
collectively-agreed updates on every shard.  Where the single-chip kernel
resolves arbitration with a cumsum over the full lane axis, the sharded kernel
agrees globally with three tiny collectives per tick:

  all_gather (port occupancy)  — senders must see every shard's port state
  pmin       (winner election) — lowest-global-lane arbitration for ports,
                                 stacks, IN and OUT (same discipline as
                                 core/step.py, now cross-chip)
  psum       (value broadcast) — the unique winner's value reaches the shard
                                 that owns the destination / applies the
                                 replicated stack/ring update

All three ride ICI inside one jitted scan; there is no host round-trip and no
per-message dial (the reference's transport cost, program.go:492-565).

The replacement map for the reference's gRPC data plane (messenger.proto:9-41):
  Program.Send  -> all_gather + pmin + psum routing into the dest shard's port
  Stack.Push/Pop-> pmin election + replicated stack update
  Master.GetInput/SendOutput -> pmin election + replicated ring update
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from misaka_tpu.core.phases import (
    apply_stack_ring_updates,
    commit_lane_state,
    decode_and_consume,
)
from misaka_tpu.core.state import NetworkState
from misaka_tpu.parallel.mesh import MODEL_AXIS, build_lane_sharded_runner
from misaka_tpu.tis import isa

_I32 = jnp.int32
# "no contender" sentinel for pmin elections.  A numpy scalar, NOT jnp: a
# module-level jnp constant would initialize the XLA backend at import time,
# which breaks jax.distributed.initialize (it must run before any backend
# touch — parallel/multihost.py).
_BIG = np.int32(2**31 - 1)


def _elect(contender: jnp.ndarray, lane_global: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Global lowest-lane election over `model` for [Nl, K] contender matrix.

    Returns (winner_key [K] — global lane id or _BIG, local_win [Nl, K]).
    """
    local_key = jnp.min(
        jnp.where(contender, lane_global[:, None], _BIG), axis=0, initial=_BIG
    )
    winner_key = jax.lax.pmin(local_key, MODEL_AXIS)
    local_win = contender & (lane_global[:, None] == winner_key[None, :])
    return winner_key, local_win


def _winner_val(local_win: jnp.ndarray, values: jnp.ndarray) -> jnp.ndarray:
    """psum-broadcast the unique winner's value: [Nl,K] mask x [Nl] -> [K]."""
    partial = (local_win.astype(_I32) * values[:, None]).sum(axis=0)
    return jax.lax.psum(partial, MODEL_AXIS)


def step_local(code: jnp.ndarray, prog_len: jnp.ndarray, state: NetworkState,
               n_total_lanes: int) -> NetworkState:
    """One superstep on this shard's lane slice (single network instance).

    Mirrors core/step.py phase by phase; comments there apply.  Lane-local
    arrays have shape [Nl]; stack/ring state is `model`-replicated.
    """
    n_local, _, _ = code.shape
    n_ports = isa.NUM_PORTS
    n_dests = n_total_lanes * n_ports
    n_stacks, stack_cap = state.stack_mem.shape
    in_cap = state.in_buf.shape[0]
    out_cap = state.out_buf.shape[0]
    shard = jax.lax.axis_index(MODEL_AXIS)
    lane_offset = shard * n_local
    lane_global = lane_offset + jnp.arange(n_local)

    # --- fetch & decode + phase A (shared: core/phases.py) -----------------
    d = decode_and_consume(code, state)
    op, src_ok, src_val, tgt = d.op, d.src_ok, d.src_val, d.tgt
    port_full_after_reads = d.port_full_after_reads

    # --- phase B: sends — the collective routing fabric --------------------
    # Senders need every shard's occupancy: all_gather [mp, Nl, 4] -> [D].
    global_full = jax.lax.all_gather(port_full_after_reads, MODEL_AXIS).reshape(n_dests)
    want_send = (op == isa.OP_MOV_NET) & src_ok
    dest = tgt * n_ports + d.tport
    dest_onehot = want_send[:, None] & (dest[:, None] == jnp.arange(n_dests)[None, :])
    contender = dest_onehot & ~global_full[None, :]
    send_key, send_win = _elect(contender, lane_global)
    send_won = send_win.any(axis=1)
    delivered = send_key < _BIG                      # [D] — replicated value
    deliver_val = _winner_val(send_win, src_val)     # [D] — replicated value
    # Each shard applies only its own slice of the dest axis.
    my_delivered = jax.lax.dynamic_slice_in_dim(
        delivered, lane_offset * n_ports, n_local * n_ports
    ).reshape(n_local, n_ports)
    my_deliver_val = jax.lax.dynamic_slice_in_dim(
        deliver_val, lane_offset * n_ports, n_local * n_ports
    ).reshape(n_local, n_ports)
    new_port_full = port_full_after_reads | my_delivered
    new_port_val = jnp.where(my_delivered, my_deliver_val, state.port_val)

    # --- stacks: elect one op per stack per tick, apply replicated ---------
    is_push = op == isa.OP_PUSH
    is_pop = op == isa.OP_POP
    tgt_stack = jnp.clip(tgt, 0, n_stacks - 1)
    top_at_tgt = state.stack_top[tgt_stack]
    want_sop = (is_push & src_ok & (top_at_tgt < stack_cap)) | (is_pop & (top_at_tgt > 0))
    stack_onehot = want_sop[:, None] & (tgt_stack[:, None] == jnp.arange(n_stacks)[None, :])
    _, stack_win = _elect(stack_onehot, lane_global)
    sop_won = stack_win.any(axis=1)
    push_per_stack = (
        jax.lax.psum((stack_win & is_push[:, None]).astype(_I32).sum(axis=0), MODEL_AXIS) > 0
    )
    pop_per_stack = (
        jax.lax.psum((stack_win & is_pop[:, None]).astype(_I32).sum(axis=0), MODEL_AXIS) > 0
    )
    push_val = _winner_val(stack_win & is_push[:, None], src_val)
    pop_val_lane = state.stack_mem[tgt_stack, jnp.clip(top_at_tgt - 1, 0, stack_cap - 1)]

    # --- master I/O rings: global single-slot elections --------------------
    in_avail = (state.in_wr - state.in_rd) > 0
    want_in = (op == isa.OP_IN) & in_avail
    in_key, in_win_m = _elect(want_in[:, None], lane_global)
    in_win = in_win_m[:, 0]
    in_any = in_key[0] < _BIG
    in_val = state.in_buf[state.in_rd % in_cap]

    out_free = (state.out_wr - state.out_rd) < out_cap
    want_out = (op == isa.OP_OUT) & src_ok & out_free
    out_key, out_win_m = _elect(want_out[:, None], lane_global)
    out_win = out_win_m[:, 0]
    out_any = out_key[0] < _BIG
    out_val = _winner_val(out_win_m, src_val)[0]

    # --- commit decision ---------------------------------------------------
    dst_ok = jnp.where(
        op == isa.OP_MOV_NET,
        send_won,
        jnp.where(
            is_push | is_pop,
            sop_won,
            jnp.where(op == isa.OP_IN, in_win, jnp.where(op == isa.OP_OUT, out_win, True)),
        ),
    )
    commit = src_ok & dst_ok

    # --- commit-time register/PC + stack/ring writes (shared) --------------
    updates = commit_lane_state(d, prog_len, state, commit, pop_val_lane, in_val)
    updates.update(
        apply_stack_ring_updates(
            state, push_per_stack, pop_per_stack, push_val, in_any, out_any, out_val
        )
    )
    return state._replace(
        port_val=new_port_val,
        port_full=new_port_full,
        tick=state.tick + 1,
        retired=state.retired + commit.astype(_I32),
        **updates,
    )


def make_sharded_runner(code, prog_len, mesh, num_steps: int, batched: bool = True):
    """Build a jitted chunk runner: state -> state, lane-sharded over `model`.

    code [N,L,F] / prog_len [N] are sharded over `model`; the state follows
    mesh.state_specs.  N must divide evenly by the mesh's model-axis size.
    """
    step1 = functools.partial(step_local, n_total_lanes=code.shape[0])
    return build_lane_sharded_runner(step1, code, prog_len, mesh, num_steps, batched)
