"""Multi-chip execution: mesh/shardings + collective-routed superstep."""

from misaka_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    make_mesh,
    shard_state,
    state_specs,
)
from misaka_tpu.parallel.sharded import make_sharded_runner, step_local
from misaka_tpu.parallel.routed import build_route_table, make_routed_runner
from misaka_tpu.parallel.multihost import (
    hybrid_mesh,
    initialize_from_env,
    make_global_state,
    put_global,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "make_mesh",
    "shard_state",
    "state_specs",
    "make_sharded_runner",
    "make_routed_runner",
    "build_route_table",
    "step_local",
    "hybrid_mesh",
    "initialize_from_env",
    "make_global_state",
    "put_global",
]
