"""Multi-host (DCN) execution: jax.distributed + hybrid ICI/DCN meshes.

The reference scales out by adding Docker containers to one TLS/gRPC LAN
(docker-compose.yml:26-74) — every hop pays a fresh dial (SURVEY.md quirk #6).
The TPU-native equivalent when a network outgrows one slice is JAX's
multi-process runtime: one process per host, a gRPC coordinator for setup,
and XLA collectives that ride ICI within a slice and DCN between slices
(SURVEY.md §5 "distributed comm backend").

Layout doctrine (the scaling-book recipe): put the *batch* axis across DCN —
pure data parallelism, zero cross-slice traffic per tick — and keep the
*lane* axis (whose port-routing collectives run every tick) inside a slice on
ICI.  `hybrid_mesh` builds exactly that: `data` spans processes, `model`
never crosses a process/slice boundary.

Pieces:
  * initialize_from_env  — process bootstrap from MISAKA_COORDINATOR /
    MISAKA_NUM_PROCESSES / MISAKA_PROCESS_ID (or jax's own auto-detect on
    Cloud TPU, where no env is needed).
  * hybrid_mesh          — (data, model) Mesh with model confined to a slice.
  * make_global_state    — a NetworkState of global jax.Arrays assembled from
    per-process shards (jax.make_array_from_callback), since multi-host
    arrays cannot be device_put from one host's buffer.
  * put_global           — same mechanism for any single array (code tables).

Verified end-to-end by tests/test_multihost.py: two OS processes, a real
coordinator handshake, and the full sharded superstep (all_gather/pmin/psum
from parallel/sharded.py) crossing the process boundary with parity.
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from misaka_tpu.core.state import NetworkState
from misaka_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh, state_specs

COORDINATOR_ENV = "MISAKA_COORDINATOR"
NUM_PROCESSES_ENV = "MISAKA_NUM_PROCESSES"
PROCESS_ID_ENV = "MISAKA_PROCESS_ID"


def initialize_from_env(environ=os.environ) -> bool:
    """Join the multi-process runtime if MISAKA_COORDINATOR is configured.

    Returns True when distributed mode was (or already is) initialized.  On
    Cloud TPU pods jax.distributed can auto-detect everything, so a bare
    `MISAKA_COORDINATOR=auto` defers entirely to that autodetection.
    """
    coordinator = environ.get(COORDINATOR_ENV)
    if not coordinator:
        return False
    if jax.distributed.is_initialized():
        return True
    if coordinator == "auto":
        jax.distributed.initialize()
        return True
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=int(environ[NUM_PROCESSES_ENV]),
        process_id=int(environ[PROCESS_ID_ENV]),
    )
    return True


def hybrid_mesh(model_parallel: int = 1) -> Mesh:
    """A (data, model) mesh where `model` never crosses a process boundary.

    Single-process: identical to make_mesh.  Multi-process: the DCN axis
    (processes/slices) is folded into `data`, so per-tick lane collectives
    stay on ICI and only the embarrassingly-parallel batch spans hosts.
    """
    n_procs = jax.process_count()
    if n_procs == 1:
        return make_mesh(model_parallel=model_parallel)

    all_devices = jax.devices()
    n_slices = len({getattr(d, "slice_index", 0) for d in all_devices})
    if n_slices > 1:
        # Real multi-slice TPU: let mesh_utils optimize intra-slice placement
        # and fold the DCN (slice) axis into `data`.  mesh_shape must account
        # for a whole slice's devices, which can span several processes.
        from jax.experimental import mesh_utils

        per_slice = len(all_devices) // n_slices
        if per_slice % model_parallel:
            raise ValueError(
                f"{per_slice} devices per slice not divisible by "
                f"model_parallel={model_parallel}"
            )
        devices = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(per_slice // model_parallel, model_parallel),
            dcn_mesh_shape=(n_slices, 1),
        )
        return Mesh(devices, (DATA_AXIS, MODEL_AXIS))

    # Single physical slice but multiple processes (CPU fleets, TPU VMs that
    # share a slice): group by process so `model` rows never cross a process.
    n_local = len(jax.local_devices())
    if n_local % model_parallel:
        raise ValueError(
            f"{n_local} local devices not divisible by model_parallel={model_parallel}"
        )
    devs = sorted(all_devices, key=lambda d: (d.process_index, d.id))
    grid = np.asarray(devs).reshape(-1, model_parallel)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def put_global(arr: np.ndarray, mesh: Mesh, spec: P) -> jax.Array:
    """Assemble a global array from identical host copies of `arr`.

    Every process holds the full logical value (cheap here: code tables and
    init states) and contributes only the shards its local devices own.
    """
    arr = np.asarray(arr)
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(arr.shape, sharding, lambda idx: arr[idx])


def make_global_state(
    init: NetworkState, mesh: Mesh, batched: bool = True
) -> NetworkState:
    """Place a host-built NetworkState onto a (possibly multi-host) mesh with
    the canonical shardings (parallel/mesh.state_specs)."""
    specs = state_specs(batched)
    return jax.tree.map(
        lambda x, spec: put_global(np.asarray(x), mesh, spec), init, specs
    )
