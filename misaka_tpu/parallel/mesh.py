"""Device mesh + sharding specs for multi-chip execution.

The reference's "distribution" is one Docker container per node wired by
gRPC/TLS (SURVEY.md §1 L1).  The TPU build distributes over a
jax.sharding.Mesh with two named axes:

  data   — lockstep batch of independent network instances (pure DP; the
           throughput axis; no cross-shard traffic at all)
  model  — program-node lanes sharded across chips (the TP/PP analogue: the
           lane graph IS the pipeline, so sharding lanes shards the pipeline
           stages; inter-lane MOV traffic rides ICI collectives)

Stacks and master I/O rings are replicated over `model` and kept consistent
by having every shard apply the identical (collectively agreed) update.
"""

from __future__ import annotations

import functools

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from misaka_tpu.core.state import NetworkState, rebase_rings

DATA_AXIS = "data"
MODEL_AXIS = "model"


def shard_map_compat(f, *, mesh, in_specs, out_specs):
    """jax.shard_map across jax versions: the public jax.shard_map (keyword
    check_vma) where it exists, jax.experimental.shard_map (keyword
    check_rep) on pre-0.5 jax — same relaxed-replication semantics."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
    )


def make_mesh(n_devices: int | None = None, model_parallel: int = 1) -> Mesh:
    """A (data, model) mesh over the first n_devices."""
    devices = jax.devices()[: n_devices or len(jax.devices())]
    n = len(devices)
    if n % model_parallel:
        raise ValueError(f"{n} devices not divisible by model_parallel={model_parallel}")
    grid = np.asarray(devices).reshape(n // model_parallel, model_parallel)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def state_specs(batched: bool = True) -> NetworkState:
    """PartitionSpec pytree for NetworkState (leading batch axis if batched).

    Lane-major arrays shard over `model`; stacks/rings replicate over `model`
    and shard over `data` with the batch.
    """
    d = (DATA_AXIS,) if batched else ()
    lane = P(*d, MODEL_AXIS)
    lane_port = P(*d, MODEL_AXIS, None)
    repl1 = P(*d, None)
    repl2 = P(*d, None, None)
    scalar = P(*d)
    return NetworkState(
        acc=lane, bak=lane, acc_hi=lane, bak_hi=lane, pc=lane,
        port_val=lane_port, port_full=lane_port,
        hold_val=lane, holding=lane,
        stack_mem=repl2, stack_top=repl1,
        in_buf=repl1, in_rd=scalar, in_wr=scalar,
        out_buf=repl1, out_rd=scalar, out_wr=scalar,
        tick=scalar, retired=lane,
    )


def shard_state(state: NetworkState, mesh: Mesh, batched: bool = True) -> NetworkState:
    """Place a state pytree onto the mesh with the canonical shardings."""
    specs = state_specs(batched)
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)), state, specs
    )


def build_lane_sharded_runner(step1, code, prog_len, mesh, num_steps: int,
                              batched: bool = True):
    """Shared scaffolding for the lane-sharded chunk runners.

    `step1(code_local, prog_len_local, state) -> state` is one per-shard
    superstep (an unbatched single instance); this wraps it in the scan
    chunk, vmaps the batch axis, shard_maps over the mesh with the canonical
    state specs, places the code tables, and jits with donated state.  Both
    multi-chip kernels (parallel/sharded.py, parallel/routed.py) differ only
    in `step1` — everything else lives here, once.
    """
    n_total = code.shape[0]
    mp = mesh.shape[MODEL_AXIS]
    if n_total % mp:
        raise ValueError(f"{n_total} lanes not divisible by model axis size {mp}")

    specs = state_specs(batched)
    step_fn = step1 if not batched else jax.vmap(step1, in_axes=(None, None, 0))

    def chunk(code_l, prog_len_l, state):
        def body(s, _):
            return step_fn(code_l, prog_len_l, s), None

        out, _ = jax.lax.scan(body, state, None, length=num_steps)
        return rebase_rings(out)

    sharded = shard_map_compat(
        chunk,
        mesh=mesh,
        in_specs=(P(MODEL_AXIS, None, None), P(MODEL_AXIS), specs),
        out_specs=specs,
    )

    # make_array_from_callback (not device_put): each process contributes only
    # the table shards its local devices own, so the same path works on a
    # single host and across a multi-host DCN mesh (parallel/multihost.py).
    def _put(arr, spec):
        arr = np.asarray(arr, dtype=np.int32)
        return jax.make_array_from_callback(
            arr.shape, NamedSharding(mesh, spec), lambda idx: arr[idx]
        )

    code_sh = _put(code, P(MODEL_AXIS, None, None))
    len_sh = _put(prog_len, P(MODEL_AXIS))
    # The un-jitted chunk (tables bound): callable INSIDE another jit, so the
    # master can fuse feed + sharded chunk + counter/ring snapshot into its
    # one-dispatch serve iteration (engine.make_batched_serve) instead of
    # paying four device interactions per loop on the mesh path.
    inner = functools.partial(sharded, code_sh, len_sh)
    jitted = jax.jit(inner, donate_argnums=(0,))
    jitted.inner = inner
    return jitted
