"""Device mesh + sharding specs for multi-chip execution.

The reference's "distribution" is one Docker container per node wired by
gRPC/TLS (SURVEY.md §1 L1).  The TPU build distributes over a
jax.sharding.Mesh with two named axes:

  data   — lockstep batch of independent network instances (pure DP; the
           throughput axis; no cross-shard traffic at all)
  model  — program-node lanes sharded across chips (the TP/PP analogue: the
           lane graph IS the pipeline, so sharding lanes shards the pipeline
           stages; inter-lane MOV traffic rides ICI collectives)

Stacks and master I/O rings are replicated over `model` and kept consistent
by having every shard apply the identical (collectively agreed) update.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from misaka_tpu.core.state import NetworkState

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(n_devices: int | None = None, model_parallel: int = 1) -> Mesh:
    """A (data, model) mesh over the first n_devices."""
    devices = jax.devices()[: n_devices or len(jax.devices())]
    n = len(devices)
    if n % model_parallel:
        raise ValueError(f"{n} devices not divisible by model_parallel={model_parallel}")
    grid = np.asarray(devices).reshape(n // model_parallel, model_parallel)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def state_specs(batched: bool = True) -> NetworkState:
    """PartitionSpec pytree for NetworkState (leading batch axis if batched).

    Lane-major arrays shard over `model`; stacks/rings replicate over `model`
    and shard over `data` with the batch.
    """
    d = (DATA_AXIS,) if batched else ()
    lane = P(*d, MODEL_AXIS)
    lane_port = P(*d, MODEL_AXIS, None)
    repl1 = P(*d, None)
    repl2 = P(*d, None, None)
    scalar = P(*d)
    return NetworkState(
        acc=lane, bak=lane, acc_hi=lane, bak_hi=lane, pc=lane,
        port_val=lane_port, port_full=lane_port,
        hold_val=lane, holding=lane,
        stack_mem=repl2, stack_top=repl1,
        in_buf=repl1, in_rd=scalar, in_wr=scalar,
        out_buf=repl1, out_rd=scalar, out_wr=scalar,
        tick=scalar, retired=lane,
    )


def shard_state(state: NetworkState, mesh: Mesh, batched: bool = True) -> NetworkState:
    """Place a state pytree onto the mesh with the canonical shardings."""
    specs = state_specs(batched)
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)), state, specs
    )
