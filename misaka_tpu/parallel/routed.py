"""Statically-routed lane-sharded superstep: TWO collectives per tick.

The first-generation sharded kernel (parallel/sharded.py) reaches global
agreement with an `all_gather` of the ENTIRE port-occupancy axis plus ~9
per-tick collectives, with dense one-hot election matrices over the full
[Nl, 4N] dest axis on every shard — so adding chips subtracted speed
(BENCH_sharded r3: ratio 0.73).  None of that traffic is necessary: a TIS
network's route table is STATIC — every MOV_NET instruction names its
destination (lane, port) at assembly time (program.go:242-275).

The compact-slot kernel that exploits this lives in core/routing.py (shared
with the single-chip large-N engine); this module binds it to a mesh axis,
where agreement costs exactly TWO collectives per tick, both over one
compact [Da + num_stacks + 2] vector (Da = dest slots actually named by
some MOV_NET; for a pipeline Da ~ N, for sparse graphs Da << 4N):

  pmin(keys)   — election + occupancy veto for sends, stacks, IN, OUT
                 (key = global_lane*2 + is_push keeps lowest-lane order
                 while telling every shard whether a stack winner pushes
                 or pops; the shard OWNING a dest port contributes key -1
                 when the port is full, so fullness and arbitration
                 resolve in the same reduction)
  psum(values) — the unique winners' wire values reach the dest shard
                 (sends) and the replicated stack/ring state (push, OUT)

Both ride ICI inside one jitted scan.  Stack memories and master I/O rings
stay `model`-replicated (a few dozen words; what made gen 1 slow was the
dest-axis gather, not these), and every shard applies the identical
collectively-agreed update, so state remains bit-identical to
core/step.py — pinned by tests/test_parallel.py running both generations.

Measured on the 8-device virtual mesh (mesh8, mp=8, r5 artifacts): the
routed kernel beats the gather kernel 1.7-2.0x (`routed_vs_gather`,
BENCH_tpu_r05_final*.json / BENCH_cpu_r05.json) but runs at 0.47-0.54x the
single-chip PLATFORM-AUTO scan engine (`sharded_vs_single` 0.48 in the
final r5 capture) — the r5 crossover change made CPU auto-select the
compact kernel, which is ~2.7x the dense baseline the earlier 1.5x claim
was measured against (routed still beats that legacy dense denominator,
`sharded_vs_single_dense` ~1.5x).  On the loopback mesh the two
collectives per tick cost more than 8x one core's compact arithmetic, so
today model-parallel is a CAPACITY feature; whether it pays for per-tick
SPEED is a real-ICI question (docs/ARCHITECTURE.md "Measured scaling
character").
"""

from __future__ import annotations

import functools

from misaka_tpu.core.routing import RouteTable, build_route_table, step_slots
from misaka_tpu.parallel.mesh import MODEL_AXIS, build_lane_sharded_runner

__all__ = ["RouteTable", "build_route_table", "make_routed_runner", "step_local"]


def step_local(route, code, prog_len, state, n_total_lanes):
    """One superstep on this shard's lane slice (core/routing.py, bound to
    the `model` mesh axis)."""
    return step_slots(
        route, code, prog_len, state, axis=MODEL_AXIS, n_total_lanes=n_total_lanes
    )


def make_routed_runner(code, prog_len, mesh, num_steps: int, batched: bool = True):
    """Build a jitted chunk runner: state -> state, lane-sharded over `model`.

    Drop-in replacement for parallel/sharded.make_sharded_runner with the
    two-collective compact-slot fabric.  code [N,L,F] / prog_len [N] shard
    over `model`; the state follows mesh.state_specs.
    """
    route = build_route_table(code, prog_len)
    step1 = functools.partial(step_local, route, n_total_lanes=code.shape[0])
    return build_lane_sharded_runner(step1, code, prog_len, mesh, num_steps, batched)
