"""Statically-routed lane-sharded superstep: TWO collectives per tick.

The first-generation sharded kernel (parallel/sharded.py) reaches global
agreement with an `all_gather` of the ENTIRE port-occupancy axis plus ~9
per-tick collectives (four pmin elections, five psum broadcasts), and its
on-shard election math is dense one-hot over the full [Nl, 4N] dest axis —
so adding chips subtracted speed (BENCH_sharded r3: ratio 0.73).  None of
that traffic is necessary: a TIS network's route table is STATIC.  Every
MOV_NET instruction names its destination (lane, port) at assembly time
(program.go:242-275), so the set of port slots that can EVER receive a value
is known before the first tick.  This kernel exploits that three ways:

  1. **Compact slot space.**  Elections run over the `Da` *active* dest
     slots (those named by some MOV_NET instruction) + one slot per stack +
     one IN + one OUT slot — not the full `4N` dest axis.  For a pipeline,
     Da ~ N; for sparse graphs Da << 4N.

  2. **Scatter elections, not one-hot matrices.**  Each lane contends for
     at most one slot per tick, so lowest-lane arbitration is a scatter-min
     of encoded keys into a [K] vector — O(Nl) work — instead of the
     [Nl, 4N] mask-and-cumsum of the gather kernel.

  3. **Occupancy veto folded into the election.**  Senders must not win a
     FULL port.  Instead of gathering every shard's occupancy, the shard
     that OWNS a dest slot contributes key `-1` ("vetoed") when the port is
     full; pmin makes -1 beat every real contender, so fullness and
     arbitration resolve in the same reduction.

Per tick that leaves exactly TWO collectives, both over a [K] vector with
K = Da + num_stacks + 2:

  pmin(keys)   — election + occupancy veto for sends, stacks, IN, OUT
                 (key = global_lane*2 + is_push keeps lowest-lane order
                 while telling every shard whether a stack winner pushes
                 or pops)
  psum(values) — the unique winners' wire values reach the dest shard
                 (sends) and the replicated stack/ring state (push, OUT)

Both ride ICI inside one jitted scan.  Stack memories and master I/O rings
stay `model`-replicated (a few dozen words; the O(N) traffic the verdict
flagged was the dest-axis gather, not these), and every shard applies the
identical collectively-agreed update, so state remains bit-identical to
core/step.py — pinned by tests/test_parallel.py running both kernels.

Semantics (arbitration, hold latch, consume-then-send visibility) are
EXACTLY core/step.py's; see its module docstring for the reference mapping
(program.go:78-92, :219-432, stack.go:133-155, master.go:233-246).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from misaka_tpu.core.phases import (
    apply_stack_ring_updates,
    commit_lane_state,
    decode_and_consume,
)
from misaka_tpu.core.state import NetworkState
from misaka_tpu.parallel.mesh import MODEL_AXIS, build_lane_sharded_runner
from misaka_tpu.tis import isa

_I32 = jnp.int32
# "no contender" sentinel for pmin elections (numpy, not jnp: a module-level
# jnp constant would initialize the XLA backend at import time, breaking
# jax.distributed.initialize — see parallel/multihost.py).
_BIG = np.int32(2**31 - 1)


class RouteTable(NamedTuple):
    """Static routing metadata extracted from the lowered code tables.

    All arrays are host numpy; they become jit-time constants inside the
    kernel closure (never traced, never transferred per tick).
    """

    dest_to_slot: np.ndarray  # [N*4] int32: full dest id -> send slot, or n_send
    slot_lane: np.ndarray     # [n_send] int32: dest lane of each send slot
    slot_port: np.ndarray     # [n_send] int32: dest port of each send slot
    n_send: int               # Da — number of active dest slots


def build_route_table(code: np.ndarray, prog_len: np.ndarray) -> RouteTable:
    """Scan the lowered programs for every MOV_NET destination.

    Only rows below each lane's true length count (pc wraps at prog_len,
    program.go:429, so padding rows never execute — and they are NOP anyway).
    """
    code = np.asarray(code)
    prog_len = np.asarray(prog_len)
    n_lanes = code.shape[0]
    n_ports = isa.NUM_PORTS
    n_dests = n_lanes * n_ports

    live = np.arange(code.shape[1])[None, :] < prog_len[:, None]  # [N, L]
    is_send = (code[:, :, isa.F_OP] == isa.OP_MOV_NET) & live
    dest = code[:, :, isa.F_TGT] * n_ports + code[:, :, isa.F_PORT]
    active = np.unique(dest[is_send]).astype(np.int32)
    if active.size and (active.min() < 0 or active.max() >= n_dests):
        raise ValueError("MOV_NET destination out of range in lowered code")

    dest_to_slot = np.full((n_dests,), active.size, dtype=np.int32)
    dest_to_slot[active] = np.arange(active.size, dtype=np.int32)
    return RouteTable(
        dest_to_slot=dest_to_slot,
        slot_lane=(active // n_ports).astype(np.int32),
        slot_port=(active % n_ports).astype(np.int32),
        n_send=int(active.size),
    )


def step_local(route: RouteTable, code: jnp.ndarray, prog_len: jnp.ndarray,
               state: NetworkState, n_total_lanes: int) -> NetworkState:
    """One superstep on this shard's lane slice (single network instance).

    Phase structure mirrors core/step.py line for line; only the agreement
    fabric differs (compact-slot pmin/psum instead of dense one-hot).
    """
    n_local, _, _ = code.shape
    n_ports = isa.NUM_PORTS
    n_dests = n_total_lanes * n_ports
    n_stacks, stack_cap = state.stack_mem.shape
    in_cap = state.in_buf.shape[0]
    out_cap = state.out_buf.shape[0]
    shard = jax.lax.axis_index(MODEL_AXIS)
    lane_offset = shard * n_local
    lane_global = lane_offset + jnp.arange(n_local)

    # Election-vector slot layout (K live slots + 1 trash):
    da = route.n_send
    in_slot = da + n_stacks
    out_slot = in_slot + 1
    trash = out_slot + 1
    kv = trash + 1

    # --- fetch & decode + phase A (shared: core/phases.py) -----------------
    d = decode_and_consume(code, state)
    op, src_ok, src_val, tgt = d.op, d.src_ok, d.src_val, d.tgt
    port_full_after_reads = d.port_full_after_reads

    # --- contender classification (all local) ------------------------------
    want_send = (op == isa.OP_MOV_NET) & src_ok
    dest = tgt * n_ports + d.tport
    send_slot = jnp.asarray(route.dest_to_slot)[jnp.clip(dest, 0, n_dests - 1)]

    is_push = op == isa.OP_PUSH
    is_pop = op == isa.OP_POP
    tgt_stack = jnp.clip(tgt, 0, n_stacks - 1)
    top_at_tgt = state.stack_top[tgt_stack]
    want_sop = (is_push & src_ok & (top_at_tgt < stack_cap)) | (is_pop & (top_at_tgt > 0))

    in_avail = (state.in_wr - state.in_rd) > 0
    want_in = (op == isa.OP_IN) & in_avail
    out_free = (state.out_wr - state.out_rd) < out_cap
    want_out = (op == isa.OP_OUT) & src_ok & out_free

    slot = jnp.where(
        want_send,
        send_slot,
        jnp.where(
            want_sop,
            da + tgt_stack,
            jnp.where(want_in, in_slot, jnp.where(want_out, out_slot, trash)),
        ),
    )
    contend = want_send | want_sop | want_in | want_out
    # key = lane*2 + bit: monotone in lane (lowest lane still wins) while
    # carrying the push/pop discriminator every shard needs for the
    # replicated stack update.
    my_key = lane_global * 2 + (want_sop & is_push).astype(_I32)

    # --- collective 1: pmin election with occupancy veto -------------------
    keys = jnp.full((kv,), _BIG, _I32).at[slot].min(jnp.where(contend, my_key, _BIG))
    slot_lane = jnp.asarray(route.slot_lane)
    slot_port = jnp.asarray(route.slot_port)
    local_row = slot_lane - lane_offset
    mine = (local_row >= 0) & (local_row < n_local)
    occ = port_full_after_reads[jnp.clip(local_row, 0, n_local - 1), slot_port]
    veto = jnp.where(mine & occ, jnp.asarray(-1, _I32), _BIG)
    keys = keys.at[jnp.arange(da)].min(veto)
    keys_global = jax.lax.pmin(keys, MODEL_AXIS)

    gathered = keys_global[slot]
    won = contend & (gathered == my_key)

    # --- collective 2: psum winner values ----------------------------------
    carries_val = won & (want_send | is_push | want_out)
    vals = jnp.zeros((kv,), _I32).at[slot].add(jnp.where(carries_val, src_val, 0))
    vals_global = jax.lax.psum(vals, MODEL_AXIS)

    # --- port delivery (owner shard applies its own slots) -----------------
    sk = keys_global[:da]
    delivered = (sk != _BIG) & (sk >= 0)  # a sender won and the port was free
    row = jnp.where(mine & delivered, jnp.clip(local_row, 0, n_local - 1), n_local)
    pf_pad = jnp.concatenate(
        [port_full_after_reads, jnp.zeros((1, n_ports), bool)], axis=0
    )
    pv_pad = jnp.concatenate([state.port_val, jnp.zeros((1, n_ports), _I32)], axis=0)
    new_port_full = pf_pad.at[row, slot_port].set(True)[:n_local]
    new_port_val = pv_pad.at[row, slot_port].set(vals_global[:da])[:n_local]

    # --- stack agreement (replicated update, identical on every shard) -----
    skeys = keys_global[da : da + n_stacks]
    stack_live = skeys != _BIG
    push_per_stack = stack_live & ((skeys & 1) == 1)
    pop_per_stack = stack_live & ((skeys & 1) == 0)
    push_val = vals_global[da : da + n_stacks]
    pop_val_lane = state.stack_mem[tgt_stack, jnp.clip(top_at_tgt - 1, 0, stack_cap - 1)]

    # --- master I/O rings ---------------------------------------------------
    in_any = keys_global[in_slot] != _BIG
    in_val = state.in_buf[state.in_rd % in_cap]
    out_any = keys_global[out_slot] != _BIG
    out_val = vals_global[out_slot]

    # --- commit decision ---------------------------------------------------
    commit = src_ok & jnp.where(
        (op == isa.OP_MOV_NET) | is_push | is_pop | (op == isa.OP_IN) | (op == isa.OP_OUT),
        won,
        True,
    )

    # --- commit-time register/PC + stack/ring writes (shared) --------------
    updates = commit_lane_state(d, prog_len, state, commit, pop_val_lane, in_val)
    updates.update(
        apply_stack_ring_updates(
            state, push_per_stack, pop_per_stack, push_val, in_any, out_any, out_val
        )
    )
    return state._replace(
        port_val=new_port_val,
        port_full=new_port_full,
        tick=state.tick + 1,
        retired=state.retired + commit.astype(_I32),
        **updates,
    )


def make_routed_runner(code, prog_len, mesh, num_steps: int, batched: bool = True):
    """Build a jitted chunk runner: state -> state, lane-sharded over `model`.

    Drop-in replacement for parallel/sharded.make_sharded_runner with the
    two-collective compact-slot fabric.  code [N,L,F] / prog_len [N] shard
    over `model`; the state follows mesh.state_specs.
    """
    route = build_route_table(code, prog_len)
    step1 = functools.partial(step_local, route, n_total_lanes=code.shape[0])
    return build_lane_sharded_runner(step1, code, prog_len, mesh, num_steps, batched)
