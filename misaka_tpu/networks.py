"""Predefined networks: the five BASELINE.json benchmark configurations.

These are the rebuild's "model zoo" — each returns a runtime.Topology ready to
compile.  Config #1 is the reference's own docker-compose deployment
(docker-compose.yml:26-74); the rest are the driver-specified coverage
configs (BASELINE.md).
"""

from __future__ import annotations

from misaka_tpu.runtime.topology import Topology

ADD2_PROGRAMS = {
    # docker-compose.yml:35-40 / :54-59, verbatim (trailing newline included —
    # YAML block scalars end with one, and it costs a NOP slot, parity).
    "misaka1": "IN ACC\nADD 1\nMOV ACC, misaka2:R0\nMOV R0, ACC\nOUT ACC\n",
    "misaka2": "MOV R0, ACC\nADD 1\nPUSH ACC, misaka3\nPOP misaka3, ACC\nMOV ACC, misaka1:R0\n",
}


def add2(**kw) -> Topology:
    """Config #1: the compose 'add-2' network — 2 program nodes + 1 stack."""
    return Topology(
        node_info={"misaka1": "program", "misaka2": "program", "misaka3": "stack"},
        programs=dict(ADD2_PROGRAMS),
        **kw,
    )


def acc_loop(**kw) -> Topology:
    """Config #2: single program node, ADD/SUB/NEG/SAV/SWP coverage.

    Flow per value: acc=v+3, bak=v+3 (SAV), acc=-(v+3) (NEG), acc+=1,
    SWP restores acc=v+3, OUT v+3, SUB ACC zeroes, wrap.  Output = v + 3.
    """
    program = "IN ACC\nADD 3\nSAV\nNEG\nADD 1\nSWP\nNOP\nOUT ACC\nSUB ACC\n"
    return Topology(node_info={"solo": "program"}, programs={"solo": program}, **kw)


def ring(n: int = 4, **kw) -> Topology:
    """Config #3: n-node MOV ring — pure port-routing pipeline, no stack.

    node0 ingests and adds 1, each hop adds 1, node0 emits after a full lap:
    output = input + n.
    """
    if n < 2:
        raise ValueError(f"ring needs at least 2 nodes, got {n}")
    names = [f"ring{i}" for i in range(n)]
    programs = {}
    programs[names[0]] = (
        f"IN ACC\nADD 1\nMOV ACC, {names[1]}:R0\nMOV R0, ACC\nOUT ACC\n"
    )
    for i in range(1, n):
        nxt = names[(i + 1) % n]
        programs[names[i]] = f"MOV R0, ACC\nADD 1\nMOV ACC, {nxt}:R0\n"
    return Topology(
        node_info={name: "program" for name in names}, programs=programs, **kw
    )


def sorter(**kw) -> Topology:
    """Config #4: branch-heavy JEZ/JNZ/JGZ/JLZ/JRO classifier.

    Emits sign(v)*10 + (|v| clamped to 1 if nonzero): -11 / 0 / 11 mapped as:
    v>0 -> 11, v<0 -> -11, v==0 -> 0.  Exercises every conditional jump and a
    computed JRO dispatch per value.
    """
    program = (
        "IN ACC\n"
        "JGZ pos\n"
        "JLZ neg\n"
        "JEZ zero\n"
        "pos: MOV 11, ACC\n"
        "JMP emit\n"
        "neg: MOV -11, ACC\n"
        "JMP emit\n"
        "zero: MOV 0, ACC\n"
        "JRO 1\n"
        "emit: OUT ACC\n"
    )
    return Topology(node_info={"sorter": "program"}, programs={"sorter": program}, **kw)


def mesh8(**kw) -> Topology:
    """Config #5: 8 program nodes in a 2-wide/4-deep mesh + 2 stack nodes.

    Two parallel 4-stage pipelines (a-lane and b-lane) sharing the input
    stream; each stage adds 1; stage 2 round-trips its value through a stack
    node.  Output per value: v + 4.  Exercises concurrent IN arbitration,
    cross-lane sends, and two stacks under contention.
    """
    programs = {
        "a0": "IN ACC\nADD 1\nMOV ACC, a1:R0\n",
        "a1": "MOV R0, ACC\nADD 1\nPUSH ACC, sa\nPOP sa, ACC\nMOV ACC, a2:R1\n",
        "a2": "MOV R1, ACC\nADD 1\nMOV ACC, a3:R2\n",
        "a3": "MOV R2, ACC\nADD 1\nOUT ACC\n",
        "b0": "IN ACC\nADD 1\nMOV ACC, b1:R0\n",
        "b1": "MOV R0, ACC\nADD 1\nPUSH ACC, sb\nPOP sb, ACC\nMOV ACC, b2:R1\n",
        "b2": "MOV R1, ACC\nADD 1\nMOV ACC, b3:R2\n",
        "b3": "MOV R2, ACC\nADD 1\nOUT ACC\n",
    }
    node_info = {name: "program" for name in programs}
    node_info["sa"] = "stack"
    node_info["sb"] = "stack"
    return Topology(node_info=node_info, programs=programs, **kw)


def pipeline(n: int = 8, **kw) -> Topology:
    """An n-stage add-1 chain: the lane-scaling workload.

    Unlike ring(), every stage holds a different value in flight, so steady
    state retires one value per ~3 ticks regardless of n — which isolates the
    per-tick routing cost as the lane axis grows (the scan engine's one-hot
    dest matrix is O(N·4N)); this is the workload behind the bench's
    lane-ceiling numbers.  Edges are strictly lane i -> i+1, so a contiguous
    model-parallel sharding sees only boundary-crossing traffic ("arbitrary
    number of program nodes", README.md:10-18).  Output per value: v + n.
    """
    if n < 2:
        raise ValueError(f"pipeline needs at least 2 stages, got {n}")
    names = [f"p{i}" for i in range(n)]
    programs = {names[0]: f"IN ACC\nADD 1\nMOV ACC, {names[1]}:R0\n"}
    for i in range(1, n - 1):
        programs[names[i]] = f"MOV R0, ACC\nADD 1\nMOV ACC, {names[i + 1]}:R0\n"
    programs[names[-1]] = "MOV R0, ACC\nADD 1\nOUT ACC\n"
    return Topology(
        node_info={name: "program" for name in names}, programs=programs, **kw
    )


BASELINE_CONFIGS = {
    "add2": add2,
    "acc_loop": acc_loop,
    "ring4": lambda **kw: ring(4, **kw),
    "sorter": sorter,
    "mesh8": mesh8,
}
