"""Compact-slot routing: the scatter-election superstep kernel.

The dense kernel (core/step.py) arbitrates with one-hot matrices over the
FULL dest axis — [N, 4N] per tick — which is the right shape when N is a
handful of lanes but quadratic in the lane count ("arbitrary number of
program nodes", README.md:10-18; at N=256 the dense matrices are large
enough to fault the TPU worker at production batch sizes).  A TIS network's
route table is static: every MOV_NET instruction names its destination
(lane, port) at assembly time (program.go:242-275).  This kernel exploits
that: elections run as scatter-min of encoded lane keys into a compact slot
vector of the `Da` ACTIVE dest slots + one slot per stack + IN + OUT —
O(N + Da) per tick.

One parameterized function serves two execution modes:

  * `axis=None` — single chip.  The "global" reduction is the local scatter
    itself; the occupancy veto (key -1 for full ports) replaces the dense
    kernel's contender exclusion with identical semantics (no winner on a
    full port either way).
  * `axis="model"` — lane-sharded multi-chip (parallel/routed.py).  The
    scatter results are combined across shards with exactly TWO collectives
    per tick: pmin(keys) — election + occupancy veto in one reduction —
    and psum(values).

Arbitration, hold latch, and visibility semantics are EXACTLY core/step.py's
(its module docstring maps each rule to program.go / stack.go / master.go);
bit-identity is pinned by tests/test_parallel.py, tests/test_scale.py and
the fuzzed differential suites.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from misaka_tpu.core.phases import (
    apply_stack_ring_updates,
    commit_lane_state,
    decode_and_consume,
)
from misaka_tpu.core.state import NetworkState
from misaka_tpu.tis import isa

_I32 = jnp.int32
# "no contender" sentinel for min-elections (numpy, not jnp: a module-level
# jnp constant would initialize the XLA backend at import time, breaking
# jax.distributed.initialize — see parallel/multihost.py).
BIG = np.int32(2**31 - 1)


class RouteTable(NamedTuple):
    """Static routing metadata extracted from the lowered code tables.

    All arrays are host numpy; they become jit-time constants inside the
    kernel closure (never traced, never transferred per tick).
    """

    dest_to_slot: np.ndarray  # [N*4] int32: full dest id -> send slot, or n_send
    slot_lane: np.ndarray     # [n_send] int32: dest lane of each send slot
    slot_port: np.ndarray     # [n_send] int32: dest port of each send slot
    n_send: int               # Da — number of active dest slots


def build_route_table(code: np.ndarray, prog_len: np.ndarray) -> RouteTable:
    """Scan the lowered programs for every MOV_NET destination.

    Only rows below each lane's true length count (pc wraps at prog_len,
    program.go:429, so padding rows never execute — and they are NOP anyway).
    """
    code = np.asarray(code)
    prog_len = np.asarray(prog_len)
    n_lanes = code.shape[0]
    n_ports = isa.NUM_PORTS
    n_dests = n_lanes * n_ports

    live = np.arange(code.shape[1])[None, :] < prog_len[:, None]  # [N, L]
    is_send = (code[:, :, isa.F_OP] == isa.OP_MOV_NET) & live
    dest = code[:, :, isa.F_TGT] * n_ports + code[:, :, isa.F_PORT]
    active = np.unique(dest[is_send]).astype(np.int32)
    if active.size and (active.min() < 0 or active.max() >= n_dests):
        raise ValueError("MOV_NET destination out of range in lowered code")

    dest_to_slot = np.full((n_dests,), active.size, dtype=np.int32)
    dest_to_slot[active] = np.arange(active.size, dtype=np.int32)
    return RouteTable(
        dest_to_slot=dest_to_slot,
        slot_lane=(active // n_ports).astype(np.int32),
        slot_port=(active % n_ports).astype(np.int32),
        n_send=int(active.size),
    )


class ChainTable(NamedTuple):
    """Static contender structure for the chained (scatter-free) election.

    Derived once from the lowered code: which lanes can EVER contend for
    each election slot, and which slots each lane can ever address.  Python
    tuples of ints — pure trace-time constants.
    """

    slot_contenders: tuple  # [kv-1] tuples of lane ids
    lane_slots: tuple       # [N] tuples of slot ids


def build_chain_table(
    code: np.ndarray, prog_len: np.ndarray, route: RouteTable, n_stacks: int
) -> ChainTable:
    """Invert the route table into per-slot contender lists.

    Slots follow step_slots' layout: [0, Da) sends, [Da, Da+S) stacks,
    then IN, then OUT (trash excluded — it never elects)."""
    code = np.asarray(code)
    prog_len = np.asarray(prog_len)
    n_lanes = code.shape[0]
    n_ports = isa.NUM_PORTS
    da = route.n_send
    kv_live = da + max(1, n_stacks) + 2

    slot_sets: list[set] = [set() for _ in range(kv_live)]
    lane_sets: list[set] = [set() for _ in range(n_lanes)]
    live = np.arange(code.shape[1])[None, :] < prog_len[:, None]
    for n in range(n_lanes):
        for l in range(code.shape[1]):
            if not live[n, l]:
                continue
            op = code[n, l, isa.F_OP]
            if op == isa.OP_MOV_NET:
                dest = code[n, l, isa.F_TGT] * n_ports + code[n, l, isa.F_PORT]
                s = int(route.dest_to_slot[dest])
            elif op in (isa.OP_PUSH, isa.OP_POP):
                s = da + int(np.clip(code[n, l, isa.F_TGT], 0, max(1, n_stacks) - 1))
            elif op == isa.OP_IN:
                s = da + max(1, n_stacks)
            elif op == isa.OP_OUT:
                s = da + max(1, n_stacks) + 1
            else:
                continue
            slot_sets[s].add(n)
            lane_sets[n].add(s)
    return ChainTable(
        slot_contenders=tuple(tuple(sorted(s)) for s in slot_sets),
        lane_slots=tuple(tuple(sorted(s)) for s in lane_sets),
    )


def step_slots(
    route: RouteTable,
    code: jnp.ndarray,
    prog_len: jnp.ndarray,
    state: NetworkState,
    axis: str | None = None,
    n_total_lanes: int | None = None,
    chain: ChainTable | None = None,
) -> NetworkState:
    """One superstep via compact-slot elections (single instance).

    axis=None runs the whole network on one device; axis=<mesh axis name>
    runs inside shard_map on this shard's lane slice (code/state are the
    local shards, n_total_lanes the global lane count).

    chain=None elects via scatter-min/scatter-add (the r4 kernel — XLA CPU
    lowers these well; TPU serializes them).  Passing a ChainTable replaces
    every scatter/gather with STATICALLY-UNROLLED min/sum chains over the
    slots' possible contenders (O(total network-op instructions) dense
    vector ops per tick, no scatters at all) — the r5 cut at the measured
    TPU wide-lane ceiling (ARCHITECTURE.md "Wide-network design
    position").  Single-chip only: per-shard contender structure is not
    uniform, so the sharded kernel keeps scatter + pmin/psum.
    """
    if chain is not None and axis is not None:
        raise ValueError("chained election is single-chip (axis=None) only")
    n_local, _, _ = code.shape
    n_ports = isa.NUM_PORTS
    if n_total_lanes is None:
        n_total_lanes = n_local
    n_dests = n_total_lanes * n_ports
    n_stacks, stack_cap = state.stack_mem.shape
    in_cap = state.in_buf.shape[0]
    out_cap = state.out_buf.shape[0]
    if axis is None:
        lane_offset = jnp.asarray(0, _I32)
    else:
        lane_offset = jax.lax.axis_index(axis) * n_local
    lane_global = lane_offset + jnp.arange(n_local)

    # Election-vector slot layout (K live slots + 1 trash):
    da = route.n_send
    in_slot = da + n_stacks
    out_slot = in_slot + 1
    trash = out_slot + 1
    kv = trash + 1

    # --- fetch & decode + phase A (shared: core/phases.py) -----------------
    d = decode_and_consume(code, state)
    op, src_ok, src_val, tgt = d.op, d.src_ok, d.src_val, d.tgt
    port_full_after_reads = d.port_full_after_reads

    # --- contender classification (all local) ------------------------------
    want_send = (op == isa.OP_MOV_NET) & src_ok
    dest = tgt * n_ports + d.tport
    send_slot = jnp.asarray(route.dest_to_slot)[jnp.clip(dest, 0, n_dests - 1)]

    is_push = op == isa.OP_PUSH
    is_pop = op == isa.OP_POP
    tgt_stack = jnp.clip(tgt, 0, n_stacks - 1)
    top_at_tgt = state.stack_top[tgt_stack]
    want_sop = (is_push & src_ok & (top_at_tgt < stack_cap)) | (is_pop & (top_at_tgt > 0))

    in_avail = (state.in_wr - state.in_rd) > 0
    want_in = (op == isa.OP_IN) & in_avail
    out_free = (state.out_wr - state.out_rd) < out_cap
    want_out = (op == isa.OP_OUT) & src_ok & out_free

    slot = jnp.where(
        want_send,
        send_slot,
        jnp.where(
            want_sop,
            da + tgt_stack,
            jnp.where(want_in, in_slot, jnp.where(want_out, out_slot, trash)),
        ),
    )
    contend = want_send | want_sop | want_in | want_out
    # key = lane*2 + bit: monotone in lane (lowest lane still wins) while
    # carrying the push/pop discriminator every shard needs for the
    # replicated stack update.
    my_key = lane_global * 2 + (want_sop & is_push).astype(_I32)

    # --- election: keys per slot (+ pmin across shards) --------------------
    key_masked = jnp.where(contend, my_key, BIG)
    slot_lane = jnp.asarray(route.slot_lane)
    slot_port = jnp.asarray(route.slot_port)
    local_row = slot_lane - lane_offset
    mine = (local_row >= 0) & (local_row < n_local)
    occ = port_full_after_reads[jnp.clip(local_row, 0, n_local - 1), slot_port]
    veto = jnp.where(mine & occ, jnp.asarray(-1, _I32), BIG)
    if chain is None:
        keys = jnp.full((kv,), BIG, _I32).at[slot].min(key_masked)
        keys = keys.at[jnp.arange(da)].min(veto)
    else:
        # per-slot terms stacked then min-reduced (log-depth tree, not a
        # linear dependency chain — contended slots would otherwise
        # serialize over their contender count, the very cost this
        # election exists to remove)
        ks = []
        for s_idx, lanes_for in enumerate(chain.slot_contenders):
            if not lanes_for:
                ks.append(jnp.asarray(BIG))
                continue
            terms = jnp.stack(
                [jnp.where(slot[c] == s_idx, key_masked[c], BIG) for c in lanes_for]
            )
            ks.append(jnp.min(terms, axis=0))
        ks.append(jnp.asarray(BIG))  # trash
        keys = jnp.stack(ks)
        keys = jnp.concatenate([jnp.minimum(keys[:da], veto), keys[da:]])
    keys_global = keys if axis is None else jax.lax.pmin(keys, axis)

    if chain is None:
        gathered = keys_global[slot]
    else:
        # exactly one slot matches each lane's current classification, so a
        # min over (match ? key : BIG) terms is the gather (tree-reduced)
        gs = []
        for n in range(n_local):
            slots_n = chain.lane_slots[n]
            if not slots_n:
                gs.append(jnp.asarray(BIG))
                continue
            terms = jnp.stack(
                [
                    jnp.where(slot[n] == s_idx, keys_global[s_idx], BIG)
                    for s_idx in slots_n
                ]
            )
            gs.append(jnp.min(terms, axis=0))
        gathered = jnp.stack(gs)
    won = contend & (gathered == my_key)

    # --- winner values: per-slot sums (+ psum across shards) ---------------
    carries_val = won & (want_send | is_push | want_out)
    if chain is None:
        vals = jnp.zeros((kv,), _I32).at[slot].add(
            jnp.where(carries_val, src_val, 0)
        )
    else:
        vs = []
        for s_idx, lanes_for in enumerate(chain.slot_contenders):
            if not lanes_for:
                vs.append(jnp.asarray(np.int32(0)))
                continue
            terms = jnp.stack(
                [
                    jnp.where(
                        carries_val[c] & (slot[c] == s_idx), src_val[c], 0
                    )
                    for c in lanes_for
                ]
            )
            vs.append(jnp.sum(terms, axis=0))
        vs.append(jnp.asarray(np.int32(0)))  # trash
        vals = jnp.stack(vs).astype(_I32)
    vals_global = vals if axis is None else jax.lax.psum(vals, axis)

    # --- port delivery (owner shard applies its own slots) -----------------
    sk = keys_global[:da]
    delivered = (sk != BIG) & (sk >= 0)  # a sender won and the port was free
    row = jnp.where(mine & delivered, jnp.clip(local_row, 0, n_local - 1), n_local)
    pf_pad = jnp.concatenate(
        [port_full_after_reads, jnp.zeros((1, n_ports), bool)], axis=0
    )
    pv_pad = jnp.concatenate([state.port_val, jnp.zeros((1, n_ports), _I32)], axis=0)
    new_port_full = pf_pad.at[row, slot_port].set(True)[:n_local]
    new_port_val = pv_pad.at[row, slot_port].set(vals_global[:da])[:n_local]

    # --- stack agreement (replicated update, identical on every shard) -----
    skeys = keys_global[da : da + n_stacks]
    stack_live = skeys != BIG
    push_per_stack = stack_live & ((skeys & 1) == 1)
    pop_per_stack = stack_live & ((skeys & 1) == 0)
    push_val = vals_global[da : da + n_stacks]
    pop_val_lane = state.stack_mem[tgt_stack, jnp.clip(top_at_tgt - 1, 0, stack_cap - 1)]

    # --- master I/O rings ---------------------------------------------------
    in_any = keys_global[in_slot] != BIG
    in_val = state.in_buf[state.in_rd % in_cap]
    out_any = keys_global[out_slot] != BIG
    out_val = vals_global[out_slot]

    # --- commit decision ---------------------------------------------------
    commit = src_ok & jnp.where(
        (op == isa.OP_MOV_NET) | is_push | is_pop | (op == isa.OP_IN) | (op == isa.OP_OUT),
        won,
        True,
    )

    # --- commit-time register/PC + stack/ring writes (shared) --------------
    updates = commit_lane_state(d, prog_len, state, commit, pop_val_lane, in_val)
    updates.update(
        apply_stack_ring_updates(
            state, push_per_stack, pop_per_stack, push_val, in_any, out_any, out_val
        )
    )
    return state._replace(
        port_val=new_port_val,
        port_full=new_port_full,
        tick=state.tick + 1,
        retired=state.retired + commit.astype(_I32),
        **updates,
    )
