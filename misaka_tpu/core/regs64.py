"""64-bit local registers as int32 (hi, lo) pairs — reference register parity.

The reference's acc/bak are Go `int` (64-bit, program.go:27-33); ONLY the
wire truncates to int32 (sint32 fields, messenger.proto:34-41).  Round 1/2
kept the whole rebuild int32, a documented divergence that was still a real
behavioral gap: a single-node program whose ACC legitimately passes 2^31
(repeated ADDs) branches differently than the Go binary without ever
touching the wire (VERDICT r2 missing #2).

TPUs have no native int64 (and Mosaic/Pallas cannot hold int64 in VMEM), so
the engines carry acc/bak as two int32 planes: `lo` holds bits 0-31 (and IS
the wire value — Go's int32(v) truncation is "take the low word"), `hi`
holds bits 32-63.  Everything here is pure int32 arithmetic with wrapping
adds, so the same code runs under XLA scan, shard_map, and inside the
Pallas kernel; overflow wraps at 64 bits exactly like Go's int.

Operations follow two's-complement identities:
  carry(a+b)  = (a+b) <u a          borrow(a-b) = a <u b
with unsigned comparison built from signed by biasing both sides by
int32-min (x ^ 0x80000000 == x + INT32_MIN under wrapping add).
"""

from __future__ import annotations

import jax.numpy as jnp

_I32 = jnp.int32
_BIAS = -(2**31)  # int32 min; adding it (wrapping) flips the sign bit


def _ult(a, b):
    """Unsigned a < b, elementwise, on int32 arrays."""
    bias = jnp.int32(_BIAS)
    return (a + bias) < (b + bias)


def sext(lo):
    """Sign-extend an int32 into its hi word: 0 or -1 (arithmetic shift)."""
    return lo >> 31


def add64(hi, lo, s_hi, s_lo):
    """(hi, lo) + (s_hi, s_lo), wrapping at 64 bits."""
    lo2 = lo + s_lo
    carry = _ult(lo2, lo).astype(_I32)
    return hi + s_hi + carry, lo2


def sub64(hi, lo, s_hi, s_lo):
    """(hi, lo) - (s_hi, s_lo), wrapping at 64 bits."""
    lo2 = lo - s_lo
    borrow = _ult(lo, s_lo).astype(_I32)
    return hi - s_hi - borrow, lo2


def neg64(hi, lo):
    """-(hi, lo), wrapping at 64 bits (0 - value)."""
    zero = jnp.zeros_like(lo)
    return sub64(zero, zero, hi, lo)


def is_zero(hi, lo):
    return (hi == 0) & (lo == 0)


def is_pos(hi, lo):
    # hi==0 with ANY nonzero lo means value in [1, 2^32-1]: positive
    return (hi > 0) | ((hi == 0) & (lo != 0))


def is_neg(hi, lo):
    return hi < 0


def jro_target(pc, hi, lo, prog_len):
    """clip(pc + value64, 0, prog_len-1) without int32 overflow.

    program.go:354 clamps the computed target into the program.  When the
    64-bit offset exceeds int32 range the result saturates by sign; within
    range, `lo` is pre-clipped so pc + lo cannot wrap (prog_len is tiny).
    """
    small = hi == sext(lo)  # value fits signed 32-bit
    bound = jnp.int32(1 << 20)  # far above any real program length
    lo_c = jnp.clip(lo, -bound, bound)
    in_range = jnp.clip(pc + lo_c, 0, prog_len - 1)
    saturated = jnp.where(is_neg(hi, lo), jnp.zeros_like(pc), prog_len - 1)
    return jnp.where(small, in_range, saturated)
