"""The superstep kernel: one lockstep tick of the whole node network.

This replaces the reference's per-node free-running interpreter loop
(program.go:78-92 + the 24-case switch at :219-432) and its gRPC data plane
(one TLS dial per transferred integer, program.go:492-565) with a single dense
SPMD function: every program node is a lane, every semantic decision is a
masked vector op, every inter-node transfer is one-hot routing resolved inside
the step.  No data-dependent control flow — the function jits once and runs
under lax.scan.

Stall discipline (SURVEY.md §7): each lane either COMMITS its current
instruction (state effects + PC advance) or PARKS with PC unchanged, exactly
mirroring the reference's "error => retry same instruction" loop
(program.go:80-92,:429-431) and its blocking primitives:

  * reading an empty inbound port parks        (getFromSrc, program.go:441-468)
  * sending to a full cap-1 port parks         (Send handler, program.go:160-175)
  * popping an empty stack parks               (waitPop, stack.go:133-155)
  * IN with no queued master input parks       (GetInput, master.go:233-242)
  * OUT with a full output ring parks          (outChan send, master.go:246)

Two-phase port reads (the hold latch): the reference's blocking ops consume
their source FIRST and only then block on delivery — getFromSrc drains the
channel (program.go:441-468) before sendValue/outputValue blocks in the RPC.
An atomic "source ready AND destination free" commit would deadlock programs
the reference completes (e.g. `MOV R0, self:R0` with the port full).  So
phase A of every tick consumes any ready port source into the lane's hold
latch (clearing the port), and phase B retries delivery from the latch until
it commits.  Consequences, all matching Go: a parked sender's inbound port can
refill behind it, and a send can target a port freed by a phase-A consume in
the same tick (consume-then-send interleaving).

Determinism where the Go scheduler was racy (SURVEY.md quirks #2-#5): all
same-tick conflicts (two sends to one port, two ops on one stack, two INs,
two OUTs) are arbitrated by LOWEST LANE INDEX; losers park and retry.  At most
one push or pop commits per stack per tick, one IN and one OUT per network per
tick.  Visibility rule: consumers (port reads, pops, IN) see begin-of-tick
state; producers (sends, pushes, OUT) require begin-of-tick free space.  Every
superstep therefore corresponds to one legal interleaving of the reference's
concurrent semantics — parity tests exploit this.

The lane-LOCAL phases (decode, hold-latch consume, commit-time register/PC
update, stack/ring writes) are shared with the multi-chip kernels via
core/phases.py; what is unique here is the single-chip agreement fabric:
dense one-hot election matrices over the full dest axis, the right shape
when N is small (the multi-chip kernels and the compact large-N variant
replace exactly this part).
"""

from __future__ import annotations

import jax.numpy as jnp

from misaka_tpu.core.phases import (
    apply_stack_ring_updates,
    commit_lane_state,
    decode_and_consume,
)
from misaka_tpu.core.state import NetworkState
from misaka_tpu.tis import isa

_I32 = jnp.int32


def _first_true_per_column(contender: jnp.ndarray) -> jnp.ndarray:
    """[N, K] bool -> same shape with at most one True per column: the lowest
    row (= lane) index among contenders.  The deterministic arbiter."""
    return contender & (jnp.cumsum(contender.astype(_I32), axis=0) == 1)


def step(code: jnp.ndarray, prog_len: jnp.ndarray, state: NetworkState) -> NetworkState:
    """Advance one network instance by one superstep.

    code:     [N, L, NFIELDS] int32 — lowered per-lane programs (padded)
    prog_len: [N] int32 — true per-lane program lengths (PC wrap modulus,
              program.go:429)
    """
    n_lanes, _, _ = code.shape
    n_ports = isa.NUM_PORTS
    n_dests = n_lanes * n_ports
    n_stacks, stack_cap = state.stack_mem.shape
    in_cap = state.in_buf.shape[0]
    out_cap = state.out_buf.shape[0]

    # --- fetch & decode + phase A (shared: core/phases.py) -----------------
    d = decode_and_consume(code, state)
    op, src_ok, src_val, tgt = d.op, d.src_ok, d.src_val, d.tgt

    # --- phase B: network sends (OP_MOV_NET): one-hot routing + arbitration
    want_send = (op == isa.OP_MOV_NET) & src_ok
    dest = tgt * n_ports + d.tport
    dest_onehot = want_send[:, None] & (dest[:, None] == jnp.arange(n_dests)[None, :])
    dest_free = ~d.port_full_after_reads.reshape(n_dests)
    send_win = _first_true_per_column(dest_onehot & dest_free[None, :])  # [N, D]
    send_won = send_win.any(axis=1)
    delivered = send_win.any(axis=0)                                    # [D]
    deliver_val = (send_win.astype(_I32) * src_val[:, None]).sum(axis=0)

    # --- stack ops: at most ONE op (push or pop) per stack per tick --------
    is_push = op == isa.OP_PUSH
    is_pop = op == isa.OP_POP
    tgt_stack = jnp.clip(tgt, 0, n_stacks - 1)
    top_at_tgt = state.stack_top[tgt_stack]
    want_sop = (is_push & src_ok & (top_at_tgt < stack_cap)) | (
        is_pop & (top_at_tgt > 0)
    )
    stack_onehot = want_sop[:, None] & (
        tgt_stack[:, None] == jnp.arange(n_stacks)[None, :]
    )
    stack_win = _first_true_per_column(stack_onehot)  # [N, S]
    sop_won = stack_win.any(axis=1)
    push_win = stack_win & is_push[:, None]
    pop_win = stack_win & is_pop[:, None]
    push_per_stack = push_win.any(axis=0)  # [S]
    pop_per_stack = pop_win.any(axis=0)
    push_val = (push_win.astype(_I32) * src_val[:, None]).sum(axis=0)
    pop_val_lane = state.stack_mem[tgt_stack, jnp.clip(top_at_tgt - 1, 0, stack_cap - 1)]

    # --- master I/O rings --------------------------------------------------
    in_avail = (state.in_wr - state.in_rd) > 0
    want_in = (op == isa.OP_IN) & in_avail
    in_win = _first_true_per_column(want_in[:, None])[:, 0]
    in_any = in_win.any()
    in_val = state.in_buf[state.in_rd % in_cap]

    out_free = (state.out_wr - state.out_rd) < out_cap
    want_out = (op == isa.OP_OUT) & src_ok & out_free
    out_win = _first_true_per_column(want_out[:, None])[:, 0]
    out_any = out_win.any()
    out_val = (out_win.astype(_I32) * src_val).sum()

    # --- commit decision ---------------------------------------------------
    dst_ok = jnp.where(
        op == isa.OP_MOV_NET,
        send_won,
        jnp.where(
            is_push | is_pop,
            sop_won,
            jnp.where(op == isa.OP_IN, in_win, jnp.where(op == isa.OP_OUT, out_win, True)),
        ),
    )
    commit = src_ok & dst_ok

    # --- port updates: phase-A consumes cleared, winning sends fill --------
    flat_full = d.port_full_after_reads.reshape(n_dests)
    new_port_full = (flat_full | delivered).reshape(n_lanes, n_ports)
    new_port_val = jnp.where(delivered, deliver_val, state.port_val.reshape(n_dests)).reshape(
        n_lanes, n_ports
    )

    # --- commit-time register/PC + stack/ring writes (shared) --------------
    updates = commit_lane_state(d, prog_len, state, commit, pop_val_lane, in_val)
    updates.update(
        apply_stack_ring_updates(
            state, push_per_stack, pop_per_stack, push_val, in_any, out_any, out_val
        )
    )
    return state._replace(
        port_val=new_port_val,
        port_full=new_port_full,
        tick=state.tick + 1,
        retired=state.retired + commit.astype(_I32),
        **updates,
    )
