"""The superstep kernel: one lockstep tick of the whole node network.

This replaces the reference's per-node free-running interpreter loop
(program.go:78-92 + the 24-case switch at :219-432) and its gRPC data plane
(one TLS dial per transferred integer, program.go:492-565) with a single dense
SPMD function: every program node is a lane, every semantic decision is a
masked vector op, every inter-node transfer is one-hot routing resolved inside
the step.  No data-dependent control flow — the function jits once and runs
under lax.scan.

Stall discipline (SURVEY.md §7): each lane either COMMITS its current
instruction (state effects + PC advance) or PARKS with PC unchanged, exactly
mirroring the reference's "error => retry same instruction" loop
(program.go:80-92,:429-431) and its blocking primitives:

  * reading an empty inbound port parks        (getFromSrc, program.go:441-468)
  * sending to a full cap-1 port parks         (Send handler, program.go:160-175)
  * popping an empty stack parks               (waitPop, stack.go:133-155)
  * IN with no queued master input parks       (GetInput, master.go:233-242)
  * OUT with a full output ring parks          (outChan send, master.go:246)

Two-phase port reads (the hold latch): the reference's blocking ops consume
their source FIRST and only then block on delivery — getFromSrc drains the
channel (program.go:441-468) before sendValue/outputValue blocks in the RPC.
An atomic "source ready AND destination free" commit would deadlock programs
the reference completes (e.g. `MOV R0, self:R0` with the port full).  So
phase A of every tick consumes any ready port source into the lane's hold
latch (clearing the port), and phase B retries delivery from the latch until
it commits.  Consequences, all matching Go: a parked sender's inbound port can
refill behind it, and a send can target a port freed by a phase-A consume in
the same tick (consume-then-send interleaving).

Determinism where the Go scheduler was racy (SURVEY.md quirks #2-#5): all
same-tick conflicts (two sends to one port, two ops on one stack, two INs,
two OUTs) are arbitrated by LOWEST LANE INDEX; losers park and retry.  At most
one push or pop commits per stack per tick, one IN and one OUT per network per
tick.  Visibility rule: consumers (port reads, pops, IN) see begin-of-tick
state; producers (sends, pushes, OUT) require begin-of-tick free space.  Every
superstep therefore corresponds to one legal interleaving of the reference's
concurrent semantics — parity tests exploit this.
"""

from __future__ import annotations

import jax.numpy as jnp

from misaka_tpu.core import regs64
from misaka_tpu.core.state import NetworkState
from misaka_tpu.tis import isa

_I32 = jnp.int32


def _first_true_per_column(contender: jnp.ndarray) -> jnp.ndarray:
    """[N, K] bool -> same shape with at most one True per column: the lowest
    row (= lane) index among contenders.  The deterministic arbiter."""
    return contender & (jnp.cumsum(contender.astype(_I32), axis=0) == 1)


def step(code: jnp.ndarray, prog_len: jnp.ndarray, state: NetworkState) -> NetworkState:
    """Advance one network instance by one superstep.

    code:     [N, L, NFIELDS] int32 — lowered per-lane programs (padded)
    prog_len: [N] int32 — true per-lane program lengths (PC wrap modulus,
              program.go:429)
    """
    n_lanes, _, _ = code.shape
    n_ports = isa.NUM_PORTS
    n_dests = n_lanes * n_ports
    n_stacks, stack_cap = state.stack_mem.shape
    in_cap = state.in_buf.shape[0]
    out_cap = state.out_buf.shape[0]
    lane = jnp.arange(n_lanes)

    # --- fetch & decode ----------------------------------------------------
    fields = code[lane, state.pc]  # [N, NFIELDS]
    op = fields[:, isa.F_OP]
    src = fields[:, isa.F_SRC]
    imm = fields[:, isa.F_IMM]
    dst = fields[:, isa.F_DST]
    tgt = fields[:, isa.F_TGT]
    tport = fields[:, isa.F_PORT]
    jmp = fields[:, isa.F_JMP]

    # --- phase A: source resolution + port consume into the hold latch -----
    is_port_src = src >= isa.SRC_R0
    pidx = jnp.clip(src - isa.SRC_R0, 0, n_ports - 1)
    port_v = state.port_val[lane, pidx]
    port_f = state.port_full[lane, pidx]
    reads_src = jnp.isin(op, jnp.asarray(isa.READS_SRC, dtype=_I32))
    reads_port = reads_src & is_port_src
    consume_now = reads_port & ~state.holding & port_f
    holding = state.holding | consume_now
    hold_val = jnp.where(consume_now, port_v, state.hold_val)
    src_val = jnp.where(
        src == isa.SRC_IMM,
        imm,
        jnp.where(
            src == isa.SRC_ACC,
            state.acc,
            jnp.where(src == isa.SRC_NIL, jnp.zeros_like(imm), hold_val),
        ),
    )
    # 64-bit source view: ACC carries its real high word; every other
    # source (imm, NIL, port values) is an int32 sign-extended (regs64.py).
    # src_val (the low word) remains THE wire value for sends/stack/OUT —
    # Go truncates to int32 exactly by taking the low word.
    src_hi = jnp.where(src == isa.SRC_ACC, state.acc_hi, regs64.sext(src_val))
    src_ok = ~reads_port | holding

    # Ports cleared by this tick's consumes are visible to this tick's sends
    # (consume-then-send is a legal interleaving; improves pipelining to one
    # tick per hop).
    consume_onehot = consume_now[:, None] & (pidx[:, None] == jnp.arange(n_ports)[None, :])
    port_full_after_reads = state.port_full & ~consume_onehot

    # --- phase B: network sends (OP_MOV_NET): one-hot routing + arbitration
    want_send = (op == isa.OP_MOV_NET) & src_ok
    dest = tgt * n_ports + tport
    dest_onehot = want_send[:, None] & (dest[:, None] == jnp.arange(n_dests)[None, :])
    dest_free = ~port_full_after_reads.reshape(n_dests)
    send_win = _first_true_per_column(dest_onehot & dest_free[None, :])  # [N, D]
    send_won = send_win.any(axis=1)
    delivered = send_win.any(axis=0)                                    # [D]
    deliver_val = (send_win.astype(_I32) * src_val[:, None]).sum(axis=0)

    # --- stack ops: at most ONE op (push or pop) per stack per tick --------
    is_push = op == isa.OP_PUSH
    is_pop = op == isa.OP_POP
    tgt_stack = jnp.clip(tgt, 0, n_stacks - 1)
    top_at_tgt = state.stack_top[tgt_stack]
    want_sop = (is_push & src_ok & (top_at_tgt < stack_cap)) | (
        is_pop & (top_at_tgt > 0)
    )
    stack_onehot = want_sop[:, None] & (
        tgt_stack[:, None] == jnp.arange(n_stacks)[None, :]
    )
    stack_win = _first_true_per_column(stack_onehot)  # [N, S]
    sop_won = stack_win.any(axis=1)
    push_win = stack_win & is_push[:, None]
    pop_win = stack_win & is_pop[:, None]
    push_per_stack = push_win.any(axis=0)  # [S]
    pop_per_stack = pop_win.any(axis=0)
    push_val = (push_win.astype(_I32) * src_val[:, None]).sum(axis=0)
    pop_val_lane = state.stack_mem[tgt_stack, jnp.clip(top_at_tgt - 1, 0, stack_cap - 1)]

    # --- master I/O rings --------------------------------------------------
    in_avail = (state.in_wr - state.in_rd) > 0
    want_in = (op == isa.OP_IN) & in_avail
    in_win = _first_true_per_column(want_in[:, None])[:, 0]
    in_any = in_win.any()
    in_val = state.in_buf[state.in_rd % in_cap]

    out_free = (state.out_wr - state.out_rd) < out_cap
    want_out = (op == isa.OP_OUT) & src_ok & out_free
    out_win = _first_true_per_column(want_out[:, None])[:, 0]
    out_any = out_win.any()
    out_val = (out_win.astype(_I32) * src_val).sum()

    # --- commit decision ---------------------------------------------------
    dst_ok = jnp.where(
        op == isa.OP_MOV_NET,
        send_won,
        jnp.where(
            is_push | is_pop,
            sop_won,
            jnp.where(op == isa.OP_IN, in_win, jnp.where(op == isa.OP_OUT, out_win, True)),
        ),
    )
    commit = src_ok & dst_ok

    # --- register file updates (all read begin-of-tick state) --------------
    # acc/bak are 64-bit (hi, lo) pairs: ADD/SUB/NEG wrap at 64 bits like
    # Go's int; values ARRIVING from the network/stack/IN are int32 and
    # sign-extend; a local MOV ACC, ACC keeps full width (regs64.py).
    incoming = jnp.where(is_pop, pop_val_lane, jnp.where(op == isa.OP_IN, in_val, src_val))
    incoming_hi = jnp.where(
        op == isa.OP_MOV_LOCAL, src_hi, regs64.sext(incoming)
    )
    writes_acc = ((op == isa.OP_MOV_LOCAL) | is_pop | (op == isa.OP_IN)) & (
        dst == isa.DST_ACC
    )
    acc = state.acc
    acc_hi = state.acc_hi
    add_hi, add_lo = regs64.add64(acc_hi, acc, src_hi, src_val)
    sub_hi, sub_lo = regs64.sub64(acc_hi, acc, src_hi, src_val)
    neg_hi, neg_lo = regs64.neg64(acc_hi, acc)
    new_acc = jnp.where(commit & writes_acc, incoming, acc)
    new_acc_hi = jnp.where(commit & writes_acc, incoming_hi, acc_hi)
    new_acc = jnp.where(commit & (op == isa.OP_ADD), add_lo, new_acc)
    new_acc_hi = jnp.where(commit & (op == isa.OP_ADD), add_hi, new_acc_hi)
    new_acc = jnp.where(commit & (op == isa.OP_SUB), sub_lo, new_acc)
    new_acc_hi = jnp.where(commit & (op == isa.OP_SUB), sub_hi, new_acc_hi)
    new_acc = jnp.where(commit & (op == isa.OP_NEG), neg_lo, new_acc)
    new_acc_hi = jnp.where(commit & (op == isa.OP_NEG), neg_hi, new_acc_hi)
    new_acc = jnp.where(commit & (op == isa.OP_SWP), state.bak, new_acc)
    new_acc_hi = jnp.where(commit & (op == isa.OP_SWP), state.bak_hi, new_acc_hi)
    saves_bak = commit & ((op == isa.OP_SWP) | (op == isa.OP_SAV))
    new_bak = jnp.where(saves_bak, acc, state.bak)
    new_bak_hi = jnp.where(saves_bak, acc_hi, state.bak_hi)

    # --- port updates: phase-A consumes cleared, winning sends fill --------
    flat_full = port_full_after_reads.reshape(n_dests)
    new_port_full = (flat_full | delivered).reshape(n_lanes, n_ports)
    new_port_val = jnp.where(delivered, deliver_val, state.port_val.reshape(n_dests)).reshape(
        n_lanes, n_ports
    )

    # --- stack updates -----------------------------------------------------
    stack_ids = jnp.arange(n_stacks)
    push_slot = jnp.clip(state.stack_top, 0, stack_cap - 1)
    cur_slot_val = state.stack_mem[stack_ids, push_slot]
    new_stack_mem = state.stack_mem.at[stack_ids, push_slot].set(
        jnp.where(push_per_stack, push_val, cur_slot_val)
    )
    new_stack_top = (
        state.stack_top + push_per_stack.astype(_I32) - pop_per_stack.astype(_I32)
    )

    # --- I/O ring updates --------------------------------------------------
    new_in_rd = state.in_rd + in_any.astype(_I32)
    out_slot = state.out_wr % out_cap
    new_out_buf = state.out_buf.at[out_slot].set(
        jnp.where(out_any, out_val, state.out_buf[out_slot])
    )
    new_out_wr = state.out_wr + out_any.astype(_I32)

    # --- PC update ---------------------------------------------------------
    # conditions evaluate the FULL 64-bit acc (Go compares the int, not a
    # truncation, program.go:300-340)
    jump_taken = (
        (op == isa.OP_JMP)
        | ((op == isa.OP_JEZ) & regs64.is_zero(acc_hi, acc))
        | ((op == isa.OP_JNZ) & ~regs64.is_zero(acc_hi, acc))
        | ((op == isa.OP_JGZ) & regs64.is_pos(acc_hi, acc))
        | ((op == isa.OP_JLZ) & regs64.is_neg(acc_hi, acc))
    )
    pc_inc = (state.pc + 1) % prog_len                       # program.go:429
    pc_jro = regs64.jro_target(state.pc, src_hi, src_val, prog_len)  # :354
    new_pc = jnp.where(jump_taken, jmp, jnp.where(op == isa.OP_JRO, pc_jro, pc_inc))
    new_pc = jnp.where(commit, new_pc, state.pc)

    return NetworkState(
        acc=new_acc,
        bak=new_bak,
        acc_hi=new_acc_hi,
        bak_hi=new_bak_hi,
        pc=new_pc,
        port_val=new_port_val,
        port_full=new_port_full,
        hold_val=hold_val,
        holding=holding & ~commit,
        stack_mem=new_stack_mem,
        stack_top=new_stack_top,
        in_buf=state.in_buf,
        in_rd=new_in_rd,
        in_wr=state.in_wr,
        out_buf=new_out_buf,
        out_rd=state.out_rd,
        out_wr=new_out_wr,
        tick=state.tick + 1,
        retired=state.retired + commit.astype(_I32),
    )
