"""The native serving engine: serve_chunk on the C++ interpreter.

`MasterNode(engine="native")` serves an unbatched network entirely on the
host — no XLA dispatch anywhere on the request path.  The motivation is
interactive latency: the reference's primary route is one `POST /compute`
at a time (master.go:197-224), and on a relayed TPU every device dispatch
costs a 72-103ms round trip (docs/BENCH_HISTORY.md), so the measured
single-value floor was ~66ms p50 no matter how fast the kernel.  The C++
superstep interpreter (native/interpreter.cpp — the same third
implementation the differential suite pins against the XLA kernels) runs
a 128-tick serve chunk in single-digit microseconds, which puts /compute
latency at queue-hop cost instead of dispatch cost.

Design: the master's canonical state stays the NetworkState pytree.  Each
serve iteration imports the pytree into the interpreter, feeds, runs the
chunk, and exports back — a few KB of memcpy, microseconds, and it makes
the engine STATELESS between calls: checkpoint/restore, /load, stack
auto-grow, and engine swaps all keep working on the pytree with zero
native-specific code.  The serve_chunk contract (feed `count` values,
advance `num_steps`, return (state-with-drained-out-ring, packed
[in_rd, in_wr, out_rd, out_wr, out_buf...])) is byte-compatible with
core/engine.py's `_serve_body`, pinned by tests/test_native_engine.py.

NativeServe is the LATENCY tier of the serving engines (native for
interactive, fused Pallas for throughput, routed mesh for scale-out); it
trades batch throughput away by construction (one instance, one host
core).  NativeServePool below is the host THROUGHPUT tier: B replica
interpreters sharded across OS threads (cinterp.NativePool), twin to the
batched one-dispatch serve jit (core/engine.py make_batched_serve) — the
tier that keeps a driver-scored bench past the 1M inputs/s north star
when no TPU is attached.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import weakref

import numpy as np

from misaka_tpu.core import cinterp
from misaka_tpu.core import specialize
from misaka_tpu.core.state import NetworkState
from misaka_tpu.runtime import usage
from misaka_tpu.utils import faults
from misaka_tpu.utils import metrics
from misaka_tpu.utils import tracespan

# Native-tier instrumentation (served at GET /metrics): one histogram for
# every host-interpreter call kind, plus pool-shape gauges.  The label
# children are resolved once — a pool serve costs single-digit us and must
# not pay per-call dict lookups for its own telemetry.
_H_SERVE = metrics.histogram(
    "misaka_native_serve_seconds",
    "Host C++ interpreter call duration by kind (chunk = unbatched "
    "serve_chunk, serve/idle = the thread-pooled batched twins)",
    ("kind",),
)
_H_SERVE_CHUNK = _H_SERVE.labels(kind="chunk")
_H_SERVE_POOL = _H_SERVE.labels(kind="serve")
_H_SERVE_IDLE = _H_SERVE.labels(kind="idle")
_C_CALLS = metrics.counter(
    "misaka_native_serve_calls_total", "Host C++ interpreter calls by kind",
    ("kind",),
)
_C_CALLS_CHUNK = _C_CALLS.labels(kind="chunk")
_C_CALLS_POOL = _C_CALLS.labels(kind="serve")
_C_CALLS_IDLE = _C_CALLS.labels(kind="idle")
_G_POOL_THREADS = metrics.gauge(
    "misaka_native_pool_threads", "OS threads in the live native replica pool"
)
_G_POOL_REPLICAS = metrics.gauge(
    "misaka_native_pool_replicas", "Replica interpreters in the live native pool"
)
_G_POOL_FILL = metrics.gauge(
    "misaka_native_pool_fill_ratio",
    "Fraction of replicas fed on the last pool serve (replica-batch fill)",
)
# The pool gauges aggregate over EVERY live pool at scrape time (the
# set_function bindings live below _live_pools) — the same multi-tenant
# discipline as pool_counters(): a last-constructed-pool binding reported
# the wrong tenant's pool after an activation or eviction, and a closed
# or collected pool must read 0, not its last live values.


def available() -> bool:
    return cinterp.available()


# Every live pool, for the usage/flamegraph planes: a multi-tenant
# registry server runs one pool per active program engine, so the debug
# surfaces aggregate across ALL of them (a single last-constructed slot
# reported the wrong tenant's pool after an activation or eviction).
# Weakrefs only — this module must not keep a swapped-out engine alive;
# dead/closed entries are pruned on read.
_pool_refs: list = []
_pool_refs_lock = threading.Lock()


def _live_pools() -> list:
    with _pool_refs_lock:
        pools = []
        keep = []
        for r in _pool_refs:
            p = r()
            if p is not None and not p._closed:
                pools.append(p)
                keep.append(r)
        _pool_refs[:] = keep
    return pools


def _fill_ratio() -> float:
    # replica-weighted mean across pools: the per-pool value already is
    # "fraction of replicas fed on the last serve"
    pools = _live_pools()
    total = sum(p._replicas for p in pools)
    if not total:
        return 0.0
    return sum(p._last_fill * p._replicas for p in pools) / total


_G_POOL_THREADS.set_function(
    lambda: sum(p.threads for p in _live_pools())
)
_G_POOL_REPLICAS.set_function(
    lambda: sum(p._replicas for p in _live_pools())
)
_G_POOL_FILL.set_function(_fill_ratio)

# SIMD / specialization observability (ISSUE 12): lane width is the
# replica-group width of the widest live pool (8 = the AVX2 group path, 0
# = scalar per-replica ticks — MISAKA_SIMD=0 or no pool), specialized
# counts pools executing per-program baked tick functions.  The
# specialize-outcome counter lives in core/specialize.py.
_G_SIMD_WIDTH = metrics.gauge(
    "misaka_native_simd_lane_width",
    "Replicas stepped per SIMD group by the widest live native pool "
    "(0 = scalar per-replica path)",
)
_G_SPECIALIZED = metrics.gauge(
    "misaka_native_specialized_active",
    "Live native pools executing per-program specialized tick functions",
)


def _simd_width() -> float:
    width = 0
    for p in _live_pools():
        try:
            info = p.simd_info()
        except Exception:
            continue
        width = max(width, info["width"])
    return float(width)


def _specialized_active() -> float:
    count = 0
    for p in _live_pools():
        try:
            if p.simd_info()["specialized"]:
                count += 1
        except Exception:
            continue
    return float(count)


_G_SIMD_WIDTH.set_function(_simd_width)
_G_SPECIALIZED.set_function(_specialized_active)

_G_POOL_BUSY = metrics.gauge(
    "misaka_native_pool_busy_fraction",
    "Fraction of pool thread time spent executing (vs cv-parked) over "
    "the last ~1s window, from the C++ per-thread busy/idle counters — "
    "the dashboard's native-tier saturation signal (the since-boot "
    "fraction lives on /debug/usage)",
)


class _BusyWindow:
    """Windowed busy fraction from the cumulative C++ ns counters: the
    since-boot ratio converges and stops moving, so the gauge deltas the
    counters over >= 1 s between refreshes — every scraper inside that
    second sees one coherent value."""

    def __init__(self):
        self._lock = threading.Lock()
        self._prev: tuple[float, int, int] | None = None
        self._value = 0.0

    def read(self) -> float:
        work = total = 0
        for p in _live_pools():
            try:
                c = p._pool.counters()
            except Exception:
                continue
            w = c["busy_ns"] + c["serial_ns"]
            work += w
            total += w + c["idle_ns"]
        now = time.monotonic()
        with self._lock:
            prev = self._prev
            if prev is None:
                self._prev = (now, work, total)
                return 0.0
            dt_total = total - prev[2]
            if now - prev[0] >= 1.0:
                if dt_total > 0:
                    self._value = max(
                        0.0, min(1.0, (work - prev[1]) / dt_total)
                    )
                elif total == 0:
                    self._value = 0.0  # pools closed: not busy
                self._prev = (now, work, total)
            return self._value


_G_POOL_BUSY.set_function(_BusyWindow().read)


# --- resident-state serving (r17) ------------------------------------------
#
# The native engines keep their state IN C++ between serve calls on the
# trusted-identity path: the device loop passes back the exact state
# object the engine returned last call, so as long as that identity holds
# nothing else touched the state and the per-call import/export round
# trip (~200us/call at B=256) is pure waste.  Lifecycle paths —
# checkpoint, /load, /restore, autogrow, registry eviction/hot-swap,
# /status — export lazily through export_resident() (MasterNode calls it
# before reading self._state's content).  MISAKA_NATIVE_RESIDENT=0 kills
# the layer (the exact r16 stateless behavior); the `resident_fallback`
# chaos point forces the stateless path per-call with a coherent export
# first.

def resident_enabled() -> bool:
    return os.environ.get("MISAKA_NATIVE_RESIDENT", "1") not in ("0", "off")


_C_RESIDENT = metrics.counter(
    "misaka_native_resident_total",
    "Resident-state serve events: hit = served on in-C++ state, miss = "
    "state replaced, re-imported + armed, export = a lifecycle path "
    "materialized the state, fallback = stateless serve while armed "
    "(kill switch / resident_fallback chaos) after a coherent export",
    ("event",),
)
_C_RES_HIT = _C_RESIDENT.labels(event="hit")
_C_RES_MISS = _C_RESIDENT.labels(event="miss")
_C_RES_EXPORT = _C_RESIDENT.labels(event="export")
_C_RES_FALLBACK = _C_RESIDENT.labels(event="fallback")

# module-level mirrors of hit/miss for the windowed ratio gauge (reading
# our own counter objects back is not part of the metrics API)
_res_events = {"hit": 0, "miss": 0}

_G_RES_ACTIVE = metrics.gauge(
    "misaka_native_resident_active",
    "Live native pools currently serving on in-C++ resident state",
)
_G_RES_RATIO = metrics.gauge(
    "misaka_native_resident_hit_ratio",
    "Resident-state hit ratio (hits / serve calls) over the last ~1s "
    "window — the dashboard's residency signal; 0 with residency "
    "disabled or the pool cold",
)


def _resident_active() -> float:
    count = 0
    for p in _live_pools():
        try:
            if p._pool.is_resident():
                count += 1
        except Exception:
            continue
    return float(count)


_G_RES_ACTIVE.set_function(_resident_active)


class _HitWindow:
    """Windowed hit ratio from the cumulative event mirrors (the
    _BusyWindow discipline: delta over >= 1 s, coherent within it)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._prev: tuple[float, int, int] | None = None
        self._value = 0.0

    def read(self) -> float:
        hit, miss = _res_events["hit"], _res_events["miss"]
        now = time.monotonic()
        with self._lock:
            prev = self._prev
            if prev is None:
                self._prev = (now, hit, miss)
                return 0.0
            if now - prev[0] >= 1.0:
                dh = hit - prev[1]
                dm = miss - prev[2]
                self._value = dh / (dh + dm) if dh + dm > 0 else 0.0
                self._prev = (now, hit, miss)
            return self._value


_G_RES_RATIO.set_function(_HitWindow().read)


def pool_counters() -> dict | None:
    """Busy/idle nanosecond counters across every live native pool (None
    when no pool is serving): process-wide aggregate + a per-pool block
    per program, read lock-free from the C++ side
    (native/interpreter.cpp misaka_pool_counters).  `busy` includes the
    serial fast-path time (small passes run on the calling thread) — a
    box saturated in the partial-fill regime is busy, not idle."""
    pools = []
    for p in _live_pools():
        try:
            c = p._pool.counters()
            busy, idle = p._pool.thread_counters()
        except Exception:  # a closing pool must not 500 the debug surface
            continue
        try:
            label = p.usage_label()
        except Exception:
            label = usage.DEFAULT_LABEL
        c["program"] = label
        c["busy_ns_per_thread"] = [int(v) for v in busy]
        c["idle_ns_per_thread"] = [int(v) for v in idle]
        work = c["busy_ns"] + c["serial_ns"]
        total = work + c["idle_ns"]
        c["busy_fraction"] = round(work / total, 6) if total else 0.0
        pools.append(c)
    if not pools:
        return None
    out = {
        "threads": sum(c["threads"] for c in pools),
        "busy_ns": sum(c["busy_ns"] for c in pools),
        "idle_ns": sum(c["idle_ns"] for c in pools),
        "serial_ns": sum(c["serial_ns"] for c in pools),
        "busy_ns_per_thread": [
            v for c in pools for v in c["busy_ns_per_thread"]
        ],
        "idle_ns_per_thread": [
            v for c in pools for v in c["idle_ns_per_thread"]
        ],
    }
    work = out["busy_ns"] + out["serial_ns"]
    total = work + out["idle_ns"]
    out["busy_fraction"] = round(work / total, 6) if total else 0.0
    if len(pools) > 1:
        out["pools"] = pools  # the per-program split, one block per pool
    return out


class NativeServe:
    """serve_chunk twin for one CompiledNetwork, backed by NativeInterpreter."""

    is_native = True  # engine_name dispatch marker (runtime/master.py)

    def __init__(self, net):
        if net.batch is not None:
            raise ValueError("the native engine serves a single network instance")
        self._interp = cinterp.NativeInterpreter(
            np.asarray(net.code), np.asarray(net.prog_len),
            net.num_stacks, net.stack_cap, net.in_cap, net.out_cap,
        )
        self._out_cap = net.out_cap
        self._resident = resident_enabled()
        # Residency anchor: while set, the interpreter ITSELF holds the
        # authoritative state and `_last_state`'s array contents are
        # stale — only its identity matters.  None = the interpreter's
        # content mirrors whatever the last export produced (stateless).
        self._last_state: NetworkState | None = None
        # usage attribution: the unbatched interpreter runs synchronously
        # on the calling thread, so the call wall IS its busy time (the
        # pooled tier uses the C++ busy-ns counters instead)
        self.usage_label = lambda: usage.DEFAULT_LABEL

    def close(self) -> None:
        self._interp.close()

    def validate_state(self, state: NetworkState) -> None:
        """Raise ValueError on a state this engine cannot execute (pc beyond
        the program, stack_top beyond capacity, broken ring counters).
        Importing IS the validation — all-or-nothing on the C side, so a
        rejected state leaves the interpreter (and an armed residency
        anchor) untouched; a SUCCESSFUL import replaces the resident
        content, so the anchor is cleared (the next serve re-imports its
        own state)."""
        self._interp.import_arrays({
            f: np.asarray(getattr(state, f)) for f in NetworkState._fields
        })
        self._last_state = None

    def export_resident(self, anchor=None) -> NetworkState | None:
        """Materialize the resident state (None when not armed — the
        caller's state object is already authoritative).  Residency stays
        armed, re-anchored on the returned object.  `anchor` (the caller's
        current state object) gates the export: when given and NOT this
        engine's identity anchor, the resident copy is superseded (a
        lifecycle path replaced the state) and None is returned."""
        if self._last_state is None:
            return None
        if anchor is not None and anchor is not self._last_state:
            return None
        d = self._interp.export_arrays()
        st = NetworkState(**{f: d[f] for f in NetworkState._fields})
        _C_RES_EXPORT.inc()
        self._last_state = st
        return st

    def serve_chunk(self, state: NetworkState, values, count, num_steps: int):
        """See core/engine.py serve_chunk — same contract, host execution.

        Resident fast path (r17): when the caller hands back the exact
        state object this engine returned last chunk, the import is
        skipped (the interpreter already holds that state) and the export
        collapses to the packed row — the returned state is the SAME
        object, with lifecycle reads going through export_resident."""
        t0 = time.perf_counter()
        it = self._interp
        anchored = (
            self._last_state is not None and state is self._last_state
        )
        track = self._resident and faults.fire("resident_fallback") is None
        if not anchored:
            it.import_arrays({
                f: np.asarray(getattr(state, f))
                for f in NetworkState._fields
            })
        if track:
            (_C_RES_HIT if anchored else _C_RES_MISS).inc()
            _res_events["hit" if anchored else "miss"] += 1
        count = int(count)
        if count:
            fed = it.feed(np.asarray(values[:count], np.int32))
            if fed != count:  # caller cut to free space; a miss is a bug
                raise RuntimeError(f"native feed accepted {fed}/{count}")
        it.run(int(num_steps))
        # snapshot + INTERNAL drain: the interpreter's ring state stays
        # coherent whether or not the next call skips the import
        packed = it.pack(drain=True)
        if track:
            self._last_state = state
            out = state, packed
        else:
            if anchored:
                _C_RES_FALLBACK.inc()  # chaos/kill switch: export fresh
            d = it.export_arrays()  # rings already drained above
            self._last_state = None
            out = NetworkState(**{f: d[f] for f in NetworkState._fields}), \
                packed
        _C_CALLS_CHUNK.inc()
        dur = time.perf_counter() - t0
        usage.add_native(self.usage_label(), dur)
        _H_SERVE_CHUNK.observe(dur)
        return out


class NativeServePool:
    """Batched serve twins for one CompiledNetwork on the C++ thread pool.

    `serve`/`idle` are drop-in twins of the (serve_fn, idle_fn) pair built
    by CompiledNetwork.make_batched_serve — same signatures, same packed
    [B, 4+out_cap] snapshot layout, same drained-on-serve / untouched-on-
    idle ring discipline — so MasterNode's batched device loop drives this
    tier through the exact code path it drives the jitted engines through.
    B network replicas are embarrassingly parallel (independent instances,
    deterministic per request); the pool shards them across OS threads
    inside one GIL-releasing call.  The canonical state stays the
    NetworkState pytree: each call imports/exports batch-major slices, so
    checkpoint/restore, /load, and stack auto-grow keep working unchanged.
    """

    is_native = True

    def __init__(self, net, chunk_steps: int = 128, threads: int | None = None,
                 specialized: str | None = None):
        if net.batch is None:
            raise ValueError("NativeServePool serves a batched network "
                             "(use NativeServe for batch=None)")
        # `specialized` names a per-program interpreter .so built by
        # core/specialize.py.  The fallback ladder is total: a load
        # failure, a pool whose baked tables don't engage (C++-side
        # mismatch), or ANY other error serves on the generic library —
        # specialization may only ever add speed, never an outage.
        lib = None
        if specialized is not None:
            try:
                lib = cinterp.load_specialized(specialized)
            except Exception as e:
                specialize.M_SPECIALIZE.labels(status="fallback").inc()
                logging.getLogger("misaka.specialize").warning(
                    "specialized build %s failed to load (%s); "
                    "serving generic", specialized, e,
                )
                lib = None
        self._pool = cinterp.NativePool(
            np.asarray(net.code), np.asarray(net.prog_len),
            net.num_stacks, net.stack_cap, net.in_cap, net.out_cap,
            replicas=net.batch, threads=threads, lib=lib,
        )
        if lib is not None and not self._pool.simd_info()["specialized"]:
            # the .so loaded but its baked tables did not engage (key'd
            # wrong, SIMD off, or batch below the group width): count it
            # so a silent always-generic fleet is visible on /metrics
            specialize.M_SPECIALIZE.labels(status="fallback").inc()
        self.threads = self._pool.threads
        self._chunk = int(chunk_steps)
        self._replicas = net.batch
        self._closed = False
        self._last_fill = 0.0
        # Steady-state identity cache: the master's device loop passes back
        # the exact NetworkState this pool returned last call, whose dict
        # round-trips the exact arrays the C++ side exported — when that
        # identity holds, cinterp skips per-call re-validation (the trusted
        # fast path).  Any lifecycle path that builds a fresh state (load,
        # restore, autogrow pad, drain_batched's _replace) simply misses
        # the cache and takes the validated path.
        self._last_state = None
        self._last_dict = None
        # Resident-state mode (r17): when armed, the identity cache proves
        # MORE — the batch state lives in C++ between calls and `state` is
        # just the anchor object, so serve/idle skip the import/export
        # round trip entirely.  _progress carries the last resident call's
        # per-replica hot flags for the device loop (the stateless path
        # leaves it None and the loop derives hotness from `retired`).
        self._resident = resident_enabled()
        self._progress = None
        # Usage attribution (runtime/usage.py): which program this pool's
        # busy time bills to.  MasterNode rebinds this to its live
        # program_label (through a weakref — the registry names engines
        # AFTER construction); direct constructions bill "default".
        self.usage_label = lambda: usage.DEFAULT_LABEL
        # busy-ns watermark for take_busy_ns deltas (device-loop thread
        # only — one serializing caller per pool by construction)
        self._busy_mark = 0
        with _pool_refs_lock:
            _pool_refs.append(weakref.ref(self))

    def close(self) -> None:
        self._closed = True
        self._pool.close()

    def simd_info(self) -> dict:
        """The pool's execution mode (cinterp.NativePool.simd_info)."""
        return self._pool.simd_info()

    def take_busy_ns(self) -> int:
        """Busy-ns accumulated since the last take (worker + serial-path
        time): the MEASURED native cost of the call(s) in between, which
        the device loop attributes to its program.  Device-loop thread
        only — one serializing caller per pool by construction."""
        c = self._pool.counters()
        busy = c["busy_ns"] + c["serial_ns"]
        delta = busy - self._busy_mark
        self._busy_mark = busy
        return max(0, delta)

    def _account_native(self) -> None:
        # ALWAYS advance the watermark — billing gated after.  Skipping
        # the take while the kill switch is off would leave the mark
        # stale, and re-enabling would bill the entire disabled period's
        # busy time to one call in a single bogus spike.
        delta = self.take_busy_ns()
        if usage.enabled():
            usage.add_native(self.usage_label(), delta * 1e-9)

    def _to_dict(self, state: NetworkState) -> dict:
        return {f: np.asarray(getattr(state, f)) for f in NetworkState._fields}

    def _to_state(self, d: dict) -> NetworkState:
        d = dict(d)
        d["port_full"] = d["port_full"].astype(bool)
        d["holding"] = d["holding"].astype(bool)
        return NetworkState(**{f: d[f] for f in NetworkState._fields})

    def validate_state(self, state: NetworkState) -> None:
        """Raise ValueError on a state this engine cannot execute (pc beyond
        the program, stack_top beyond capacity, broken ring counters) —
        a zero-tick idle round trip; importing IS the validation.  Runs on
        the pool's stateless scratch interpreters, so an armed resident
        state is never touched (a restore whose validation fails must
        leave the live network serving its current state)."""
        self._pool.idle(self._to_dict(state), 0)

    def export_resident(self, anchor=None) -> NetworkState | None:
        """Materialize the in-C++ resident state into a fresh NetworkState
        and re-anchor the identity cache on it (residency stays armed, so
        the next serve with the returned state is still a resident hit).
        None when residency is not armed — the caller's state object is
        already authoritative.  `anchor` (the caller's current state
        object) gates the export: when given and NOT the identity anchor,
        the resident copy is superseded by a lifecycle replacement and
        None is returned (exporting would clobber the fresh state).
        MasterNode calls this before any path that READS state content:
        checkpoint, snapshot/restore, autogrow, /status, the loop's boot
        counters."""
        if anchor is not None and anchor is not self._last_state:
            return None
        d = self._pool.export_state()
        if d is None:
            return None
        _C_RES_EXPORT.inc()
        st = self._to_state(d)
        self._last_state, self._last_dict = st, d
        return st

    def consume_progress(self):
        """Per-replica progress flags ([B] uint8) from the last resident
        serve/idle — the device loop's hot-set signal; None when the last
        call went down the stateless path (the loop falls back to
        exported retired deltas)."""
        return self._progress

    def _serve_resident(self, state, values, counts, ticks, active):
        """The resident fast path: serve on the in-C++ state with no
        import/export.  Returns (packed, progress), or None when this
        call cannot be served resident (import validation refused the
        state) — the caller falls back to the stateless ladder."""
        pool = self._pool
        if state is self._last_state and pool.is_resident():
            _C_RES_HIT.inc()
            _res_events["hit"] += 1
        else:
            # a lifecycle path replaced the state: the resident copy (if
            # any) is superseded — discard and re-arm from the new state
            pool.discard_resident()
            if not pool.import_state(self._to_dict(state)):
                return None
            _C_RES_MISS.inc()
            _res_events["miss"] += 1
        return pool.serve_resident(values, counts, ticks, active=active)

    def _stateless_input(self, state):
        """(trusted, d_in) for the stateless ladder.  If residency is
        armed on this state's identity, the state object's arrays are
        STALE — export the authoritative copy first and serve trusted on
        it (the resident_fallback chaos point and the kill switch land
        here)."""
        pool = self._pool
        if pool.is_resident():
            if state is self._last_state:
                d = pool.export_state()
                if d is not None:
                    _C_RES_FALLBACK.inc()
                    pool.discard_resident()
                    self._last_dict = d
                    return True, d
            pool.discard_resident()
        trusted = state is self._last_state and self._last_dict is not None
        return trusted, (self._last_dict if trusted else self._to_dict(state))

    def _resident_ok(self) -> bool:
        return self._resident and faults.fire("resident_fallback") is None

    def serve(self, state: NetworkState, values, counts,
              num_steps: int | None = None, active=None):
        """serve_fn twin: feed counts[b] leading entries of values[b] into
        replica b, advance the chunk, return (state, packed [B, 4+out_cap])
        with the returned state's output rings drained.

        `active` (optional, strictly increasing replica indices covering
        every fed replica) is the partial-fill fast path: only those
        replicas tick — an underfilled pass pays for the replicas doing
        work, not the whole batch (cinterp.NativePool.serve).

        Resident fast path (r17): on the trusted-identity path the state
        stays in C++ — the returned state is the SAME object handed in
        (its array contents are stale; export_resident materializes them
        for lifecycle reads) and the packed rows carry everything the
        device loop consumes per chunk."""
        t0 = time.perf_counter()
        ticks = self._chunk if num_steps is None else num_steps
        res = self._serve_resident(state, values, counts, ticks, active) \
            if self._resident_ok() else None
        if res is not None:
            packed, self._progress = res
            new_state = state
            self._last_state = state
        else:
            trusted, d_in = self._stateless_input(state)
            d, packed = self._pool.serve(
                d_in, values, counts, ticks, active=active, trusted=trusted,
            )
            new_state = self._to_state(d)
            self._last_state, self._last_dict = new_state, d
            self._progress = None
        out = new_state, packed
        self._account_native()
        _C_CALLS_POOL.inc()
        dur = time.perf_counter() - t0
        _H_SERVE_POOL.observe(dur)
        # native-tier flight-recorder event (one deque append): the pool
        # call underlying a fused pass, visible in GET /debug/perfetto
        tracespan.note_tier(
            "native.tick",
            dur,
            attrs={"replicas": self._replicas if active is None
                   else int(len(active))},
        )
        self._last_fill = (
            float((np.asarray(counts) > 0).sum()) / max(1, self._replicas)
        )
        return out

    def idle(self, state: NetworkState, num_steps: int | None = None,
             active=None):
        """idle_fn twin: advance the chunk with no feed, return
        (state, ctrs [B, 4]); output rings left undrained.  `active`
        restricts the pass to the given replica indices (partial fill).
        Same resident fast path as serve()."""
        t0 = time.perf_counter()
        ticks = self._chunk if num_steps is None else num_steps
        res = self._serve_resident(state, None, None, ticks, active) \
            if self._resident_ok() else None
        if res is not None:
            ctrs, self._progress = res
            new_state = state
            self._last_state = state
        else:
            trusted, d_in = self._stateless_input(state)
            d, ctrs = self._pool.idle(
                d_in, ticks, active=active, trusted=trusted,
            )
            new_state = self._to_state(d)
            self._last_state, self._last_dict = new_state, d
            self._progress = None
        out = new_state, ctrs
        self._account_native()
        _C_CALLS_IDLE.inc()
        _H_SERVE_IDLE.observe(time.perf_counter() - t0)
        return out
