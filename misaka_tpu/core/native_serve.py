"""The native serving engine: serve_chunk on the C++ interpreter.

`MasterNode(engine="native")` serves an unbatched network entirely on the
host — no XLA dispatch anywhere on the request path.  The motivation is
interactive latency: the reference's primary route is one `POST /compute`
at a time (master.go:197-224), and on a relayed TPU every device dispatch
costs a 72-103ms round trip (docs/BENCH_HISTORY.md), so the measured
single-value floor was ~66ms p50 no matter how fast the kernel.  The C++
superstep interpreter (native/interpreter.cpp — the same third
implementation the differential suite pins against the XLA kernels) runs
a 128-tick serve chunk in single-digit microseconds, which puts /compute
latency at queue-hop cost instead of dispatch cost.

Design: the master's canonical state stays the NetworkState pytree.  Each
serve iteration imports the pytree into the interpreter, feeds, runs the
chunk, and exports back — a few KB of memcpy, microseconds, and it makes
the engine STATELESS between calls: checkpoint/restore, /load, stack
auto-grow, and engine swaps all keep working on the pytree with zero
native-specific code.  The serve_chunk contract (feed `count` values,
advance `num_steps`, return (state-with-drained-out-ring, packed
[in_rd, in_wr, out_rd, out_wr, out_buf...])) is byte-compatible with
core/engine.py's `_serve_body`, pinned by tests/test_native_engine.py.

NativeServe is the LATENCY tier of the serving engines (native for
interactive, fused Pallas for throughput, routed mesh for scale-out); it
trades batch throughput away by construction (one instance, one host
core).  NativeServePool below is the host THROUGHPUT tier: B replica
interpreters sharded across OS threads (cinterp.NativePool), twin to the
batched one-dispatch serve jit (core/engine.py make_batched_serve) — the
tier that keeps a driver-scored bench past the 1M inputs/s north star
when no TPU is attached.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
import weakref

import numpy as np

from misaka_tpu.core import cinterp
from misaka_tpu.core import jit
from misaka_tpu.core import specialize
from misaka_tpu.core.state import NetworkState
from misaka_tpu.runtime import usage
from misaka_tpu.utils import faults
from misaka_tpu.utils import metrics
from misaka_tpu.utils import tracespan

# Native-tier instrumentation (served at GET /metrics): one histogram for
# every host-interpreter call kind, plus pool-shape gauges.  The label
# children are resolved once — a pool serve costs single-digit us and must
# not pay per-call dict lookups for its own telemetry.
_H_SERVE = metrics.histogram(
    "misaka_native_serve_seconds",
    "Host C++ interpreter call duration by kind (chunk = unbatched "
    "serve_chunk, serve/idle = the thread-pooled batched twins)",
    ("kind",),
)
_H_SERVE_CHUNK = _H_SERVE.labels(kind="chunk")
_H_SERVE_POOL = _H_SERVE.labels(kind="serve")
_H_SERVE_IDLE = _H_SERVE.labels(kind="idle")
_C_CALLS = metrics.counter(
    "misaka_native_serve_calls_total", "Host C++ interpreter calls by kind",
    ("kind",),
)
_C_CALLS_CHUNK = _C_CALLS.labels(kind="chunk")
_C_CALLS_POOL = _C_CALLS.labels(kind="serve")
_C_CALLS_IDLE = _C_CALLS.labels(kind="idle")
_G_POOL_THREADS = metrics.gauge(
    "misaka_native_pool_threads", "OS threads in the live native replica pool"
)
_G_POOL_REPLICAS = metrics.gauge(
    "misaka_native_pool_replicas", "Replica interpreters in the live native pool"
)
_G_POOL_FILL = metrics.gauge(
    "misaka_native_pool_fill_ratio",
    "Fraction of replicas fed on the last pool serve (replica-batch fill)",
)
# The pool gauges aggregate over EVERY live pool at scrape time (the
# set_function bindings live below _live_pools) — the same multi-tenant
# discipline as pool_counters(): a last-constructed-pool binding reported
# the wrong tenant's pool after an activation or eviction, and a closed
# or collected pool must read 0, not its last live values.


def available() -> bool:
    return cinterp.available()


# Every live pool, for the usage/flamegraph planes: a multi-tenant
# registry server runs one pool per active program engine, so the debug
# surfaces aggregate across ALL of them (a single last-constructed slot
# reported the wrong tenant's pool after an activation or eviction).
# Weakrefs only — this module must not keep a swapped-out engine alive;
# dead/closed entries are pruned on read.
_pool_refs: list = []
_pool_refs_lock = threading.Lock()


def _live_pools() -> list:
    with _pool_refs_lock:
        pools = []
        keep = []
        for r in _pool_refs:
            p = r()
            if p is not None and not p._closed:
                pools.append(p)
                keep.append(r)
        _pool_refs[:] = keep
    return pools


def _fill_ratio() -> float:
    # replica-weighted mean across pools: the per-pool value already is
    # "fraction of replicas fed on the last serve"
    pools = _live_pools()
    total = sum(p._replicas for p in pools)
    if not total:
        return 0.0
    return sum(p._last_fill * p._replicas for p in pools) / total


_G_POOL_THREADS.set_function(
    lambda: sum(p.threads for p in _live_pools())
)
_G_POOL_REPLICAS.set_function(
    lambda: sum(p._replicas for p in _live_pools())
)
_G_POOL_FILL.set_function(_fill_ratio)

# SIMD / specialization observability (ISSUE 12): lane width is the
# replica-group width of the widest live pool (8 = the AVX2 group path, 0
# = scalar per-replica ticks — MISAKA_SIMD=0 or no pool), specialized
# counts pools executing per-program baked tick functions.  The
# specialize-outcome counter lives in core/specialize.py.
_G_SIMD_WIDTH = metrics.gauge(
    "misaka_native_simd_lane_width",
    "Replicas stepped per SIMD group by the widest live native pool "
    "(0 = scalar per-replica path)",
)
_G_SPECIALIZED = metrics.gauge(
    "misaka_native_specialized_active",
    "Live native pools executing per-program specialized tick functions",
)
_G_JIT_ACTIVE = metrics.gauge(
    "misaka_native_jit_active",
    "Live native pools dispatching group ticks through copy-and-patch "
    "JIT fragment tables (r21; the splice/arm outcome counter is "
    "misaka_native_jit_total in core/jit.py)",
)
# Pack-row elision (r21): quiescent replicas whose packed-row write was
# skipped because the caller's reused buffer already held the current row
# vs rows actually (re)written down the skip path.  elided / (elided +
# written) is the sparse-fill win ratio.
_C_ELIDED_ROWS = metrics.counter(
    "misaka_native_elided_rows_total",
    "Quiescent pack rows elided on resident serves (row write skipped: "
    "the reused packed buffer was already current)",
)
_C_SKIP_PACKED_ROWS = metrics.counter(
    "misaka_native_skip_packed_rows_total",
    "Quiescent pack rows written down the skipped-replica path (the "
    "rows elision did NOT cover)",
)
# Satellite: first-class per-rung tick counter (previously rung share was
# only derivable from flight-recorder exemplars).  Replica-ticks executed
# per ladder rung — sum across shapes of the recorder's reps aggregate —
# so JIT coverage is one PromQL query:
#   sum by (rung) (rate(misaka_native_tick_rung_total[5m]))
_C_TICK_RUNG = metrics.counter(
    "misaka_native_tick_rung_total",
    "Replica-ticks executed per native-ladder rung (scalar / generic / "
    "avx2 / spec-* / jit*)",
    ("rung",),
)


def _simd_width() -> float:
    width = 0
    for p in _live_pools():
        try:
            info = p.simd_info()
        except Exception:
            continue
        width = max(width, info["width"])
    return float(width)


def _specialized_active() -> float:
    count = 0
    for p in _live_pools():
        try:
            if p.simd_info()["specialized"]:
                count += 1
        except Exception:
            continue
    return float(count)


def _jit_active() -> float:
    count = 0
    for p in _live_pools():
        try:
            if p.simd_info().get("jit"):
                count += 1
        except Exception:
            continue
    return float(count)


_G_SIMD_WIDTH.set_function(_simd_width)
_G_SPECIALIZED.set_function(_specialized_active)
_G_JIT_ACTIVE.set_function(_jit_active)

_G_POOL_BUSY = metrics.gauge(
    "misaka_native_pool_busy_fraction",
    "Fraction of pool thread time spent executing (vs cv-parked) over "
    "the last ~1s window, from the C++ per-thread busy/idle counters — "
    "the dashboard's native-tier saturation signal (the since-boot "
    "fraction lives on /debug/usage)",
)


class _BusyWindow:
    """Windowed busy fraction from the cumulative C++ ns counters: the
    since-boot ratio converges and stops moving, so the gauge deltas the
    counters over >= 1 s between refreshes — every scraper inside that
    second sees one coherent value."""

    def __init__(self):
        self._lock = threading.Lock()
        self._prev: tuple[float, int, int] | None = None
        self._value = 0.0

    def read(self) -> float:
        work = total = 0
        for p in _live_pools():
            try:
                c = p._pool.counters()
            except Exception:
                continue
            w = c["work_ns"]  # the one derivation site: cinterp counters()
            work += w
            total += w + c["idle_ns"]
        now = time.monotonic()
        with self._lock:
            prev = self._prev
            if prev is None:
                self._prev = (now, work, total)
                return 0.0
            dt_total = total - prev[2]
            if now - prev[0] >= 1.0:
                if dt_total > 0:
                    self._value = max(
                        0.0, min(1.0, (work - prev[1]) / dt_total)
                    )
                elif total == 0:
                    self._value = 0.0  # pools closed: not busy
                self._prev = (now, work, total)
            return self._value


_G_POOL_BUSY.set_function(_BusyWindow().read)


# --- resident-state serving (r17) ------------------------------------------
#
# The native engines keep their state IN C++ between serve calls on the
# trusted-identity path: the device loop passes back the exact state
# object the engine returned last call, so as long as that identity holds
# nothing else touched the state and the per-call import/export round
# trip (~200us/call at B=256) is pure waste.  Lifecycle paths —
# checkpoint, /load, /restore, autogrow, registry eviction/hot-swap,
# /status — export lazily through export_resident() (MasterNode calls it
# before reading self._state's content).  MISAKA_NATIVE_RESIDENT=0 kills
# the layer (the exact r16 stateless behavior); the `resident_fallback`
# chaos point forces the stateless path per-call with a coherent export
# first.

def resident_enabled() -> bool:
    return os.environ.get("MISAKA_NATIVE_RESIDENT", "1") not in ("0", "off")


_C_RESIDENT = metrics.counter(
    "misaka_native_resident_total",
    "Resident-state serve events: hit = served on in-C++ state, miss = "
    "state replaced, re-imported + armed, export = a lifecycle path "
    "materialized the state, fallback = stateless serve while armed "
    "(kill switch / resident_fallback chaos) after a coherent export",
    ("event",),
)
_C_RES_HIT = _C_RESIDENT.labels(event="hit")
_C_RES_MISS = _C_RESIDENT.labels(event="miss")
_C_RES_EXPORT = _C_RESIDENT.labels(event="export")
_C_RES_FALLBACK = _C_RESIDENT.labels(event="fallback")

# module-level mirrors of hit/miss for the windowed ratio gauge (reading
# our own counter objects back is not part of the metrics API)
_res_events = {"hit": 0, "miss": 0}

_G_RES_ACTIVE = metrics.gauge(
    "misaka_native_resident_active",
    "Live native pools currently serving on in-C++ resident state",
)
_G_RES_RATIO = metrics.gauge(
    "misaka_native_resident_hit_ratio",
    "Resident-state hit ratio (hits / serve calls) over the last ~1s "
    "window — the dashboard's residency signal; 0 with residency "
    "disabled or the pool cold",
)


def _resident_active() -> float:
    count = 0
    for p in _live_pools():
        try:
            if p._pool.is_resident():
                count += 1
        except Exception:
            continue
    return float(count)


_G_RES_ACTIVE.set_function(_resident_active)


class _HitWindow:
    """Windowed hit ratio from the cumulative event mirrors (the
    _BusyWindow discipline: delta over >= 1 s, coherent within it)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._prev: tuple[float, int, int] | None = None
        self._value = 0.0

    def read(self) -> float:
        hit, miss = _res_events["hit"], _res_events["miss"]
        now = time.monotonic()
        with self._lock:
            prev = self._prev
            if prev is None:
                self._prev = (now, hit, miss)
                return 0.0
            if now - prev[0] >= 1.0:
                dh = hit - prev[1]
                dm = miss - prev[2]
                self._value = dh / (dh + dm) if dh + dm > 0 else 0.0
                self._prev = (now, hit, miss)
            return self._value


_G_RES_RATIO.set_function(_HitWindow().read)


# --- native flight recorder (r18) ------------------------------------------
#
# The C++ pool journals steady-clock-stamped events into bounded
# lock-free per-thread rings (native/interpreter.cpp, the r18 block):
# serve-call lifecycle, dispenser wait phases, per-unit tick execution
# tagged by engine rung, residency import/export.  This layer exports it
# upward: derived metrics (dispenser wait, spin-vs-park, unit imbalance,
# per-rung tick share) pulled into the registry at a throttled cadence
# from the serve path, correlation of ring events with the request-trace
# IDs active during each pool call (the per-call windows below), the raw
# dump behind GET /debug/native_trace, and a tier source feeding native
# worker-thread spans into the GET /debug/perfetto export.  Always-on
# like the PR 7 sampler; MISAKA_NATIVE_TRACE=0 kills the whole plane
# (C++ rings unallocated, every hook below a no-op) and set_trace()
# flips a built recorder at runtime for the overhead A/B.

def trace_enabled() -> bool:
    return os.environ.get("MISAKA_NATIVE_TRACE", "1") not in ("0", "off")


_TRACE_ON = trace_enabled()


def set_trace(on: bool) -> bool:
    """Arm/disarm the flight recorder at runtime: every live pool's C++
    emit flag plus the Python-side correlation/pull plumbing (the
    bench --native-trace-ab toggle).  False when some pool was created
    under MISAKA_NATIVE_TRACE=0 and has no rings to arm."""
    global _TRACE_ON
    _TRACE_ON = bool(on)
    ok = True
    for p in _live_pools():
        try:
            ok = p._pool.trace_set(on) and ok
        except Exception:
            ok = False
    return ok


_H_DISP_WAIT = metrics.histogram(
    "misaka_native_dispenser_wait_seconds",
    "Caller-side dispenser wait per published pool call (time the "
    "calling thread waited on the done futex AFTER helping drain the "
    "unit list — the straggler tail the r17 flat dispenser replaced the "
    "~180us barrier with).  Sampled from the recorder at the ~50ms pull "
    "cadence: each observation is the mean wait of one pull window",
)
_H_UNIT_IMBALANCE = metrics.histogram(
    "misaka_native_unit_imbalance",
    "Units-drained spread (max - min) across worker threads on the last "
    "published pool call per pull window — sustained nonzero at full "
    "batch means one thread runs the tail while siblings wait",
)
_C_DISP_PHASE = metrics.counter(
    "misaka_native_dispenser_seconds_total",
    "Worker dispenser wait seconds by phase (spin = pause-spin, yield = "
    "yield-spin, park = futex) — the spin-vs-park split the "
    "MISAKA_POOL_SPIN_US budget trades on",
    ("phase",),
)
_C_DISP_SPIN = _C_DISP_PHASE.labels(phase="spin")
_C_DISP_YIELD = _C_DISP_PHASE.labels(phase="yield")
_C_DISP_PARK = _C_DISP_PHASE.labels(phase="park")
_C_UNITS = metrics.counter(
    "misaka_native_units_replicas_total",
    "Replicas ticked by dispensed pool units, by engine rung (scalar / "
    "generic / avx2 / spec-*) and unit shape (group / scalar remainder "
    "/ masked partial-fill) — the per-rung tick share",
    ("rung", "shape"),
)
_C_CALLER_UNITS = metrics.counter(
    "misaka_native_caller_inline_units_total",
    "Units drained on the CALLING thread (the zero-handoff inline path "
    "and the caller helping while workers tick) — the caller-inline "
    "lane's unit count",
)
_C_TRACE_DROPPED = metrics.counter(
    "misaka_native_trace_dropped_total",
    "Flight-recorder records overwritten before any reader saw them "
    "(bounded rings drop oldest-first; size with "
    "MISAKA_NATIVE_TRACE_RING)",
)
_G_SPIN_RATIO = metrics.gauge(
    "misaka_native_dispenser_spin_ratio",
    "Fraction of worker dispenser wait spent spinning (pause + yield) "
    "vs parked on the futex over the last ~1s window — ~1 under "
    "saturation (calls arrive inside the spin budget), ~0 idle",
)


class _SpinWindow:
    """Windowed spin-vs-park ratio from the cumulative phase counters
    (the _BusyWindow discipline: delta over >= 1 s, coherent within)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._prev: tuple[float, int, int] | None = None
        self._value = 0.0

    def read(self) -> float:
        spin = park = 0
        for p in _live_pools():
            try:
                s = p._pool.trace_stats()
            except Exception:
                continue
            spin += s["spin_ns"] + s["yield_ns"]
            park += s["park_ns"]
        now = time.monotonic()
        with self._lock:
            prev = self._prev
            if prev is None:
                self._prev = (now, spin, park)
                return 0.0
            if now - prev[0] >= 1.0:
                ds, dp = spin - prev[1], park - prev[2]
                self._value = ds / (ds + dp) if ds + dp > 0 else 0.0
                self._prev = (now, spin, park)
            return self._value


_G_SPIN_RATIO.set_function(_SpinWindow().read)

# decoded-event field extractors (arg layouts: interpreter.cpp TraceEv)
_EV_NAMES = cinterp.NativePool.TRACE_EVENTS
_RUNG_NAMES = cinterp.NativePool.TRACE_RUNGS
_SHAPE_NAMES = cinterp.NativePool.TRACE_SHAPES


def _decode_event(t0: int, dur: int, kind: int, arg: int) -> dict:
    k = _EV_NAMES.get(kind, str(kind))
    ev = {"t_ns": t0, "dur_ns": dur, "kind": k}
    if k == "unit":
        ev["replicas"] = arg & 0xFFFFFF
        shape = (arg >> 24) & 0x7
        rung = (arg >> 27) & 0x1F
        ev["shape"] = _SHAPE_NAMES.get(shape, f"shape{shape}")
        ev["rung"] = _RUNG_NAMES.get(rung, f"rung{rung}")
        ev["idx"] = arg >> 32
    elif k == "serve":
        ev["active"] = arg & 0xFFFFFFFF
        flags = arg >> 32
        ev["feeding"] = bool(flags & 1)
        ev["resident"] = bool(flags & 2)
        ev["inline"] = bool(flags & 4)
    elif k in ("import", "export", "discard"):
        ev["replicas"] = arg & 0xFFFFFFFF
        if k != "discard":
            ev["failed"] = bool(arg >> 32)
    return ev


def _window_index(pool) -> list[tuple[float, float, tuple]]:
    """The pool's recent (start, end, trace_ids) serve-call windows,
    sorted by start — C++ steady_clock and time.monotonic share
    CLOCK_MONOTONIC on Linux, so ring timestamps land inside them."""
    return sorted(pool._call_windows)


def _ids_for(windows, start_s: float, end_s: float) -> tuple:
    """Trace IDs active during [start_s, end_s] (one serializing caller
    per pool, so windows never overlap and a scan from bisect is
    bounded).  EXACT containment — ring stamps are taken inside the
    Python-measured call window on the same CLOCK_MONOTONIC, and any
    slop here would cross-attribute IDs between adjacent calls at high
    call rates (~50us apart on the r18 call-overhead shape)."""
    import bisect

    if not windows:
        return ()
    i = bisect.bisect_right(windows, (start_s, float("inf"), ())) - 1
    out: list = []
    for j in range(max(0, i), len(windows)):
        w0, w1, ids = windows[j]
        if w0 > end_s:
            break
        if w1 >= start_s and w0 <= end_s:
            for tid in ids:
                if tid not in out:
                    out.append(tid)
    return tuple(out)


def _iter_flight_rings(max_records: int | None):
    """The shared ring walk behind both exporters: yields one tuple per
    readable ring — (pool, program label, ring index, role, cursor,
    dropped, decoded events) — with serve/unit events already carrying
    the request-trace IDs of the call windows they fell inside.  A pool
    or ring that fails to read is skipped (debug surfaces answer), and
    pools without rings (MISAKA_NATIVE_TRACE=0) yield nothing."""
    for p in _live_pools():
        try:
            info = p._pool.trace_info()
        except Exception:
            continue
        try:
            label = p.usage_label()
        except Exception:
            label = usage.DEFAULT_LABEL
        if not info["rings"]:
            yield p, label, info, None, None, None, None
            continue
        windows = _window_index(p)
        for ring in range(info["rings"]):
            try:
                recs, cursor, dropped = p._pool.trace_read(
                    ring, max_records
                )
            except Exception:
                continue
            role = "caller" if ring == p.threads else f"worker{ring}"
            events = []
            for t0, dur, kind, arg in recs.tolist():
                ev = _decode_event(t0, dur, kind, arg)
                if ev["kind"] in ("serve", "unit"):
                    ids = _ids_for(windows, t0 / 1e9, (t0 + dur) / 1e9)
                    if ids:
                        ev["trace_ids"] = list(ids)
                events.append(ev)
            yield p, label, info, ring, role, (cursor, dropped), events


def flight_mem_bytes() -> int:
    """Allocated flight-recorder ring memory across live pools (rings x
    capacity x 32 B records — interpreter.cpp's TraceRec layout), for
    the /healthz debug_mem budget surface shared with the request-trace
    recorder and the capture ring."""
    total = 0
    for p in _live_pools():
        try:
            info = p._pool.trace_info()
        except Exception:
            continue
        total += int(info.get("rings", 0)) * int(
            info.get("capacity", 0)
        ) * 32
    return total


def flight_payload(max_records: int | None = None) -> dict:
    """GET /debug/native_trace: the raw per-thread rings of every live
    pool, decoded, with serve/unit events carrying the request-trace IDs
    active during their pool call.  Reading also refreshes the derived
    metrics (an idle pool's counters stay fresh on scrape)."""
    entries: dict[int, dict] = {}
    pulled: set[int] = set()
    for p, label, info, ring, role, meta, events in \
            _iter_flight_rings(max_records):
        entry = entries.get(id(p))
        if entry is None:
            entry = entries[id(p)] = {
                "program": label,
                "threads": p.threads,
                "capacity": info["capacity"],
                "armed": info["armed"],
                "dropped": info["dropped"],
                "rings": [],
            }
        if ring is None:
            continue
        entry["rings"].append({
            "ring": ring,
            "role": role,
            "cursor": meta[0],
            "dropped": meta[1],
            "events": events,
        })
        if id(p) not in pulled:
            pulled.add(id(p))
            try:
                p._pull_trace_stats(force=True)
            except Exception:
                pass
    return {"enabled": _TRACE_ON, "pools": list(entries.values())}


def flight_spans(window_s: float = 15.0, max_per_ring: int = 512) -> list:
    """Recent flight-recorder events as tracespan.Span objects for the
    Perfetto export (registered as a tier source below): per-thread
    native lanes (attrs['_lane']) plus request-trace correlation
    (attrs['trace_ids']) so one trace ID reads as one timeline from
    http.parse down to the worker-thread units that served it."""
    spans: list = []
    now = time.monotonic()
    for _p, label, _info, ring, role, _meta, events in \
            _iter_flight_rings(max_per_ring):
        if ring is None:
            continue
        for ev in events:
            ev = dict(ev)
            t0 = ev.pop("t_ns")
            dur = ev.pop("dur_ns")
            start = t0 / 1e9
            if now - start > window_s:
                continue
            k = ev.pop("kind")
            ids = ev.pop("trace_ids", None)
            attrs = {"_lane": f"{label}/{role}", "pool": label}
            attrs.update(ev)
            if ids:
                attrs["trace_ids"] = ids
            spans.append(tracespan.Span(
                f"native.{k}", start, dur / 1e9, attrs
            ))
    return spans


tracespan.register_tier_source(flight_spans)


def pool_counters() -> dict | None:
    """Busy/idle nanosecond counters across every live native pool (None
    when no pool is serving): process-wide aggregate + a per-pool block
    per program, read lock-free from the C++ side
    (native/interpreter.cpp misaka_pool_counters).  `busy` includes the
    serial fast-path time (small passes run on the calling thread) — a
    box saturated in the partial-fill regime is busy, not idle."""
    pools = []
    for p in _live_pools():
        try:
            c = p._pool.counters()
            busy, idle = p._pool.thread_counters()
        except Exception:  # a closing pool must not 500 the debug surface
            continue
        try:
            label = p.usage_label()
        except Exception:
            label = usage.DEFAULT_LABEL
        c["program"] = label
        c["busy_ns_per_thread"] = [int(v) for v in busy]
        c["idle_ns_per_thread"] = [int(v) for v in idle]
        # The caller-inline lane, FIRST-CLASS (r18): work booked on the
        # calling thread — the r17 zero-handoff path runs EVERY unit
        # there on 1-worker pools, plus the caller-help and serial fast
        # paths everywhere.  cinterp counters() is the ONE place the
        # caller_inline_ns/work_ns fields are derived; this layer only
        # aggregates them.
        total = c["work_ns"] + c["idle_ns"]
        c["busy_fraction"] = round(c["work_ns"] / total, 6) if total else 0.0
        pools.append(c)
    if not pools:
        return None
    out = {
        "threads": sum(c["threads"] for c in pools),
        "busy_ns": sum(c["busy_ns"] for c in pools),
        "idle_ns": sum(c["idle_ns"] for c in pools),
        "serial_ns": sum(c["serial_ns"] for c in pools),
        "caller_inline_ns": sum(c["caller_inline_ns"] for c in pools),
        "busy_ns_per_thread": [
            v for c in pools for v in c["busy_ns_per_thread"]
        ],
        "idle_ns_per_thread": [
            v for c in pools for v in c["idle_ns_per_thread"]
        ],
    }
    out["work_ns"] = out["busy_ns"] + out["caller_inline_ns"]
    total = out["work_ns"] + out["idle_ns"]
    out["busy_fraction"] = round(out["work_ns"] / total, 6) if total else 0.0
    if len(pools) > 1:
        out["pools"] = pools  # the per-program split, one block per pool
    return out


class NativeServe:
    """serve_chunk twin for one CompiledNetwork, backed by NativeInterpreter."""

    is_native = True  # engine_name dispatch marker (runtime/master.py)

    def __init__(self, net):
        if net.batch is not None:
            raise ValueError("the native engine serves a single network instance")
        self._interp = cinterp.NativeInterpreter(
            np.asarray(net.code), np.asarray(net.prog_len),
            net.num_stacks, net.stack_cap, net.in_cap, net.out_cap,
        )
        self._out_cap = net.out_cap
        self._resident = resident_enabled()
        # Residency anchor: while set, the interpreter ITSELF holds the
        # authoritative state and `_last_state`'s array contents are
        # stale — only its identity matters.  None = the interpreter's
        # content mirrors whatever the last export produced (stateless).
        self._last_state: NetworkState | None = None
        # usage attribution: the unbatched interpreter runs synchronously
        # on the calling thread, so the call wall IS its busy time (the
        # pooled tier uses the C++ busy-ns counters instead)
        self.usage_label = lambda: usage.DEFAULT_LABEL

    def close(self) -> None:
        self._interp.close()

    def validate_state(self, state: NetworkState) -> None:
        """Raise ValueError on a state this engine cannot execute (pc beyond
        the program, stack_top beyond capacity, broken ring counters).
        Importing IS the validation — all-or-nothing on the C side, so a
        rejected state leaves the interpreter (and an armed residency
        anchor) untouched; a SUCCESSFUL import replaces the resident
        content, so the anchor is cleared (the next serve re-imports its
        own state)."""
        self._interp.import_arrays({
            f: np.asarray(getattr(state, f)) for f in NetworkState._fields
        })
        self._last_state = None

    def export_resident(self, anchor=None) -> NetworkState | None:
        """Materialize the resident state (None when not armed — the
        caller's state object is already authoritative).  Residency stays
        armed, re-anchored on the returned object.  `anchor` (the caller's
        current state object) gates the export: when given and NOT this
        engine's identity anchor, the resident copy is superseded (a
        lifecycle path replaced the state) and None is returned."""
        if self._last_state is None:
            return None
        if anchor is not None and anchor is not self._last_state:
            return None
        d = self._interp.export_arrays()
        st = NetworkState(**{f: d[f] for f in NetworkState._fields})
        _C_RES_EXPORT.inc()
        self._last_state = st
        return st

    def serve_chunk(self, state: NetworkState, values, count, num_steps: int):
        """See core/engine.py serve_chunk — same contract, host execution.

        Resident fast path (r17): when the caller hands back the exact
        state object this engine returned last chunk, the import is
        skipped (the interpreter already holds that state) and the export
        collapses to the packed row — the returned state is the SAME
        object, with lifecycle reads going through export_resident."""
        t0 = time.perf_counter()
        it = self._interp
        anchored = (
            self._last_state is not None and state is self._last_state
        )
        track = self._resident and faults.fire("resident_fallback") is None
        if not anchored:
            it.import_arrays({
                f: np.asarray(getattr(state, f))
                for f in NetworkState._fields
            })
        if track:
            (_C_RES_HIT if anchored else _C_RES_MISS).inc()
            _res_events["hit" if anchored else "miss"] += 1
        count = int(count)
        if count:
            fed = it.feed(np.asarray(values[:count], np.int32))
            if fed != count:  # caller cut to free space; a miss is a bug
                raise RuntimeError(f"native feed accepted {fed}/{count}")
        it.run(int(num_steps))
        # snapshot + INTERNAL drain: the interpreter's ring state stays
        # coherent whether or not the next call skips the import
        packed = it.pack(drain=True)
        if track:
            self._last_state = state
            out = state, packed
        else:
            if anchored:
                _C_RES_FALLBACK.inc()  # chaos/kill switch: export fresh
            d = it.export_arrays()  # rings already drained above
            self._last_state = None
            out = NetworkState(**{f: d[f] for f in NetworkState._fields}), \
                packed
        _C_CALLS_CHUNK.inc()
        dur = time.perf_counter() - t0
        usage.add_native(self.usage_label(), dur)
        _H_SERVE_CHUNK.observe(dur)
        return out


class NativeServePool:
    """Batched serve twins for one CompiledNetwork on the C++ thread pool.

    `serve`/`idle` are drop-in twins of the (serve_fn, idle_fn) pair built
    by CompiledNetwork.make_batched_serve — same signatures, same packed
    [B, 4+out_cap] snapshot layout, same drained-on-serve / untouched-on-
    idle ring discipline — so MasterNode's batched device loop drives this
    tier through the exact code path it drives the jitted engines through.
    B network replicas are embarrassingly parallel (independent instances,
    deterministic per request); the pool shards them across OS threads
    inside one GIL-releasing call.  The canonical state stays the
    NetworkState pytree: each call imports/exports batch-major slices, so
    checkpoint/restore, /load, and stack auto-grow keep working unchanged.
    """

    is_native = True

    def __init__(self, net, chunk_steps: int = 128, threads: int | None = None,
                 specialized: str | None = None, jit_program=None):
        if net.batch is None:
            raise ValueError("NativeServePool serves a batched network "
                             "(use NativeServe for batch=None)")
        # `specialized` names a per-program interpreter .so built by
        # core/specialize.py.  The fallback ladder is total: a load
        # failure, a pool whose baked tables don't engage (C++-side
        # mismatch), or ANY other error serves on the generic library —
        # specialization may only ever add speed, never an outage.
        lib = None
        if specialized is not None:
            try:
                lib = cinterp.load_specialized(specialized)
            except Exception as e:
                specialize.M_SPECIALIZE.labels(status="fallback").inc()
                logging.getLogger("misaka.specialize").warning(
                    "specialized build %s failed to load (%s); "
                    "serving generic", specialized, e,
                )
                lib = None
        self._pool = cinterp.NativePool(
            np.asarray(net.code), np.asarray(net.prog_len),
            net.num_stacks, net.stack_cap, net.in_cap, net.out_cap,
            replicas=net.batch, threads=threads, lib=lib,
        )
        if lib is not None and not self._pool.simd_info()["specialized"]:
            # the .so loaded but its baked tables did not engage (key'd
            # wrong, SIMD off, or batch below the group width): count it
            # so a silent always-generic fleet is visible on /metrics
            specialize.M_SPECIALIZE.labels(status="fallback").inc()
        # Copy-and-patch JIT rung (r21): `jit_program` is a core/jit.py
        # JitProgram spliced for this net.  Arm failure falls back ONE
        # rung (the pool keeps serving switch-threaded / generic) with a
        # logged reason and a counted outcome — never an error.
        if jit_program is not None:
            try:
                rc = self._pool.jit_arm(jit_program)
            except Exception as e:  # noqa: BLE001 - total fallback
                rc = -8
                logging.getLogger("misaka.jit").warning(
                    "jit: arm raised (%s); serving one rung down", e)
            if rc == 0:
                jit.M_JIT.labels(status="armed").inc()
            else:
                jit.M_JIT.labels(status="error").inc()
                logging.getLogger("misaka.jit").warning(
                    "jit: arm refused (rc %d); serving one rung down", rc)
        self.threads = self._pool.threads
        self._chunk = int(chunk_steps)
        self._replicas = net.batch
        self._closed = False
        self._last_fill = 0.0
        # Steady-state identity cache: the master's device loop passes back
        # the exact NetworkState this pool returned last call, whose dict
        # round-trips the exact arrays the C++ side exported — when that
        # identity holds, cinterp skips per-call re-validation (the trusted
        # fast path).  Any lifecycle path that builds a fresh state (load,
        # restore, autogrow pad, drain_batched's _replace) simply misses
        # the cache and takes the validated path.
        self._last_state = None
        self._last_dict = None
        # Resident-state mode (r17): when armed, the identity cache proves
        # MORE — the batch state lives in C++ between calls and `state` is
        # just the anchor object, so serve/idle skip the import/export
        # round trip entirely.  _progress carries the last resident call's
        # per-replica hot flags for the device loop (the stateless path
        # leaves it None and the loop derives hotness from `retired`).
        self._resident = resident_enabled()
        self._progress = None
        # Usage attribution (runtime/usage.py): which program this pool's
        # busy time bills to.  MasterNode rebinds this to its live
        # program_label (through a weakref — the registry names engines
        # AFTER construction); direct constructions bill "default".
        self.usage_label = lambda: usage.DEFAULT_LABEL
        # busy-ns watermark for take_busy_ns deltas (device-loop thread
        # only — one serializing caller per pool by construction), plus
        # the elision-counter watermarks riding the same read (r21)
        self._busy_mark = 0
        self._elided_mark = 0
        self._skip_packed_mark = 0
        # Flight-recorder plumbing (r18): per-call (start, end, trace_ids)
        # windows correlate ring events with the request traces the pass
        # served (MasterNode rebinds active_trace_ids like usage_label);
        # the stats watermark feeds the derived metrics at a throttled
        # cadence so the pull never taxes the per-call hot path.
        self._call_windows: collections.deque = collections.deque(maxlen=512)
        self.active_trace_ids = lambda: ()
        self._trace_marks: dict | None = None
        self._trace_last_pull = 0.0
        self._trace_pull_lock = threading.Lock()
        # prime the watermark with the pool's zero snapshot: the FIRST
        # real pull then reports deltas instead of discarding everything
        # ticked before it (a short-lived pool was invisible to the
        # per-rung counters otherwise)
        self._pull_trace_stats(force=True)
        with _pool_refs_lock:
            _pool_refs.append(weakref.ref(self))

    def close(self) -> None:
        self._closed = True
        self._pool.close()

    def simd_info(self) -> dict:
        """The pool's execution mode (cinterp.NativePool.simd_info)."""
        return self._pool.simd_info()

    def take_busy_ns(self) -> int:
        """Busy-ns accumulated since the last take (worker + serial-path
        time): the MEASURED native cost of the call(s) in between, which
        the device loop attributes to its program.  Device-loop thread
        only — one serializing caller per pool by construction."""
        c = self._pool.counters()
        busy = c["work_ns"]
        delta = busy - self._busy_mark
        self._busy_mark = busy
        # pack-row elision deltas ride the same counters read (r21)
        el, sk = c.get("elided_rows", 0), c.get("skip_packed_rows", 0)
        if el > self._elided_mark:
            _C_ELIDED_ROWS.inc(el - self._elided_mark)
            self._elided_mark = el
        if sk > self._skip_packed_mark:
            _C_SKIP_PACKED_ROWS.inc(sk - self._skip_packed_mark)
            self._skip_packed_mark = sk
        return max(0, delta)

    def _account_native(self) -> None:
        # ALWAYS advance the watermark — billing gated after.  Skipping
        # the take while the kill switch is off would leave the mark
        # stale, and re-enabling would bill the entire disabled period's
        # busy time to one call in a single bogus spike.
        delta = self.take_busy_ns()
        if usage.enabled():
            usage.add_native(self.usage_label(), delta * 1e-9)

    def _pull_trace_stats(self, force: bool = False) -> None:
        """Drain the C++ recorder aggregates into the metrics registry:
        counter deltas vs the per-pool watermark, one sampled histogram
        observation per pull window.  Callers race (the device-loop
        serve path vs scrape threads via flight_payload), and the
        read-delta-inc sequence is NOT atomic under the GIL (trace_stats
        releases it inside ctypes) — _trace_pull_lock serializes the
        watermark; a contended caller just skips (the winner already
        drained the same deltas)."""
        if not force and not _TRACE_ON:
            return
        if not self._trace_pull_lock.acquire(blocking=False):
            return
        try:
            self._pull_trace_stats_locked()
        finally:
            self._trace_pull_lock.release()

    def _pull_trace_stats_locked(self) -> None:
        try:
            s = self._pool.trace_stats()
        except Exception:
            return
        prev, self._trace_marks = self._trace_marks, s
        if prev is None:
            return
        d_spin = s["spin_ns"] - prev["spin_ns"]
        d_yield = s["yield_ns"] - prev["yield_ns"]
        d_park = s["park_ns"] - prev["park_ns"]
        if d_spin > 0:
            _C_DISP_SPIN.inc(d_spin * 1e-9)
        if d_yield > 0:
            _C_DISP_YIELD.inc(d_yield * 1e-9)
        if d_park > 0:
            _C_DISP_PARK.inc(d_park * 1e-9)
        d_caller = s["caller_units"] - prev["caller_units"]
        if d_caller > 0:
            _C_CALLER_UNITS.inc(d_caller)
        d_drop = s["dropped"] - prev["dropped"]
        if d_drop > 0:
            _C_TRACE_DROPPED.inc(d_drop)
        d_calls = s["dispatch_calls"] - prev["dispatch_calls"]
        if d_calls > 0:
            d_wait = s["dispatch_wait_ns"] - prev["dispatch_wait_ns"]
            _H_DISP_WAIT.observe(max(0.0, d_wait / d_calls) * 1e-9)
            _H_UNIT_IMBALANCE.observe(float(s["last_unit_imbalance"]))
        for key, v in s["reps"].items():
            dv = v - prev["reps"].get(key, 0)
            if dv > 0:
                rung, shape = key
                _C_UNITS.labels(rung=rung, shape=shape).inc(dv)
                # first-class per-rung tick counter (r21): the same reps
                # aggregate summed across shapes, so ladder coverage is
                # one PromQL query instead of an exemplar join
                _C_TICK_RUNG.labels(rung=rung).inc(dv)

    def _note_trace_call(self, t0: float, t1: float) -> None:
        """Per-serve-call recorder bookkeeping: the correlation window
        (only when request traces are active — an untraced call costs
        one lambda call) and the throttled stats pull."""
        if not _TRACE_ON:
            return
        ids = self.active_trace_ids()
        if ids:
            self._call_windows.append((t0, t1, tuple(ids)))
        if t1 - self._trace_last_pull >= 0.05:
            self._trace_last_pull = t1
            self._pull_trace_stats()

    def _to_dict(self, state: NetworkState) -> dict:
        return {f: np.asarray(getattr(state, f)) for f in NetworkState._fields}

    def _to_state(self, d: dict) -> NetworkState:
        d = dict(d)
        d["port_full"] = d["port_full"].astype(bool)
        d["holding"] = d["holding"].astype(bool)
        return NetworkState(**{f: d[f] for f in NetworkState._fields})

    def validate_state(self, state: NetworkState) -> None:
        """Raise ValueError on a state this engine cannot execute (pc beyond
        the program, stack_top beyond capacity, broken ring counters) —
        a zero-tick idle round trip; importing IS the validation.  Runs on
        the pool's stateless scratch interpreters, so an armed resident
        state is never touched (a restore whose validation fails must
        leave the live network serving its current state)."""
        self._pool.idle(self._to_dict(state), 0)

    def export_resident(self, anchor=None) -> NetworkState | None:
        """Materialize the in-C++ resident state into a fresh NetworkState
        and re-anchor the identity cache on it (residency stays armed, so
        the next serve with the returned state is still a resident hit).
        None when residency is not armed — the caller's state object is
        already authoritative.  `anchor` (the caller's current state
        object) gates the export: when given and NOT the identity anchor,
        the resident copy is superseded by a lifecycle replacement and
        None is returned (exporting would clobber the fresh state).
        MasterNode calls this before any path that READS state content:
        checkpoint, snapshot/restore, autogrow, /status, the loop's boot
        counters."""
        if anchor is not None and anchor is not self._last_state:
            return None
        d = self._pool.export_state()
        if d is None:
            return None
        _C_RES_EXPORT.inc()
        st = self._to_state(d)
        self._last_state, self._last_dict = st, d
        return st

    def consume_progress(self):
        """Per-replica progress flags ([B] uint8) from the last resident
        serve/idle — the device loop's hot-set signal; None when the last
        call went down the stateless path (the loop falls back to
        exported retired deltas)."""
        return self._progress

    def _serve_resident(self, state, values, counts, ticks, active):
        """The resident fast path: serve on the in-C++ state with no
        import/export.  Returns (packed, progress), or None when this
        call cannot be served resident (import validation refused the
        state) — the caller falls back to the stateless ladder."""
        pool = self._pool
        if state is self._last_state and pool.is_resident():
            _C_RES_HIT.inc()
            _res_events["hit"] += 1
        else:
            # a lifecycle path replaced the state: the resident copy (if
            # any) is superseded — discard and re-arm from the new state
            pool.discard_resident()
            if not pool.import_state(self._to_dict(state)):
                return None
            _C_RES_MISS.inc()
            _res_events["miss"] += 1
        # reuse_out: the pool hands back the same packed/progress buffers
        # every call, enabling quiescent pack-row elision (r21).  The
        # device loop's consumption pattern is compatible: it re-reads
        # `packed` after every call and copies what survives the
        # iteration (drain_from_snapshot fancy-indexes into new arrays).
        return pool.serve_resident(values, counts, ticks, active=active,
                                   reuse_out=True)

    def _stateless_input(self, state):
        """(trusted, d_in) for the stateless ladder.  If residency is
        armed on this state's identity, the state object's arrays are
        STALE — export the authoritative copy first and serve trusted on
        it (the resident_fallback chaos point and the kill switch land
        here)."""
        pool = self._pool
        if pool.is_resident():
            if state is self._last_state:
                d = pool.export_state()
                if d is not None:
                    _C_RES_FALLBACK.inc()
                    pool.discard_resident()
                    self._last_dict = d
                    return True, d
            pool.discard_resident()
        trusted = state is self._last_state and self._last_dict is not None
        return trusted, (self._last_dict if trusted else self._to_dict(state))

    def _resident_ok(self) -> bool:
        return self._resident and faults.fire("resident_fallback") is None

    def serve(self, state: NetworkState, values, counts,
              num_steps: int | None = None, active=None):
        """serve_fn twin: feed counts[b] leading entries of values[b] into
        replica b, advance the chunk, return (state, packed [B, 4+out_cap])
        with the returned state's output rings drained.

        `active` (optional, strictly increasing replica indices covering
        every fed replica) is the partial-fill fast path: only those
        replicas tick — an underfilled pass pays for the replicas doing
        work, not the whole batch (cinterp.NativePool.serve).

        Resident fast path (r17): on the trusted-identity path the state
        stays in C++ — the returned state is the SAME object handed in
        (its array contents are stale; export_resident materializes them
        for lifecycle reads) and the packed rows carry everything the
        device loop consumes per chunk."""
        t0 = time.perf_counter()
        ticks = self._chunk if num_steps is None else num_steps
        res = self._serve_resident(state, values, counts, ticks, active) \
            if self._resident_ok() else None
        if res is not None:
            packed, self._progress = res
            new_state = state
            self._last_state = state
        else:
            trusted, d_in = self._stateless_input(state)
            d, packed = self._pool.serve(
                d_in, values, counts, ticks, active=active, trusted=trusted,
            )
            new_state = self._to_state(d)
            self._last_state, self._last_dict = new_state, d
            self._progress = None
        out = new_state, packed
        self._account_native()
        _C_CALLS_POOL.inc()
        dur = time.perf_counter() - t0
        _H_SERVE_POOL.observe(dur)
        self._note_trace_call(t0, t0 + dur)
        # native-tier flight-recorder event (one deque append): the pool
        # call underlying a fused pass, visible in GET /debug/perfetto
        tracespan.note_tier(
            "native.tick",
            dur,
            attrs={"replicas": self._replicas if active is None
                   else int(len(active))},
        )
        self._last_fill = (
            float((np.asarray(counts) > 0).sum()) / max(1, self._replicas)
        )
        return out

    def idle(self, state: NetworkState, num_steps: int | None = None,
             active=None):
        """idle_fn twin: advance the chunk with no feed, return
        (state, ctrs [B, 4]); output rings left undrained.  `active`
        restricts the pass to the given replica indices (partial fill).
        Same resident fast path as serve()."""
        t0 = time.perf_counter()
        ticks = self._chunk if num_steps is None else num_steps
        res = self._serve_resident(state, None, None, ticks, active) \
            if self._resident_ok() else None
        if res is not None:
            ctrs, self._progress = res
            new_state = state
            self._last_state = state
        else:
            trusted, d_in = self._stateless_input(state)
            d, ctrs = self._pool.idle(
                d_in, ticks, active=active, trusted=trusted,
            )
            new_state = self._to_state(d)
            self._last_state, self._last_dict = new_state, d
            self._progress = None
        out = new_state, ctrs
        self._account_native()
        _C_CALLS_IDLE.inc()
        t1 = time.perf_counter()
        _H_SERVE_IDLE.observe(t1 - t0)
        self._note_trace_call(t0, t1)
        return out
