"""The native serving engine: serve_chunk on the C++ interpreter.

`MasterNode(engine="native")` serves an unbatched network entirely on the
host — no XLA dispatch anywhere on the request path.  The motivation is
interactive latency: the reference's primary route is one `POST /compute`
at a time (master.go:197-224), and on a relayed TPU every device dispatch
costs a 72-103ms round trip (docs/BENCH_HISTORY.md), so the measured
single-value floor was ~66ms p50 no matter how fast the kernel.  The C++
superstep interpreter (native/interpreter.cpp — the same third
implementation the differential suite pins against the XLA kernels) runs
a 128-tick serve chunk in single-digit microseconds, which puts /compute
latency at queue-hop cost instead of dispatch cost.

Design: the master's canonical state stays the NetworkState pytree.  Each
serve iteration imports the pytree into the interpreter, feeds, runs the
chunk, and exports back — a few KB of memcpy, microseconds, and it makes
the engine STATELESS between calls: checkpoint/restore, /load, stack
auto-grow, and engine swaps all keep working on the pytree with zero
native-specific code.  The serve_chunk contract (feed `count` values,
advance `num_steps`, return (state-with-drained-out-ring, packed
[in_rd, in_wr, out_rd, out_wr, out_buf...])) is byte-compatible with
core/engine.py's `_serve_body`, pinned by tests/test_native_engine.py.

NativeServe is the LATENCY tier of the serving engines (native for
interactive, fused Pallas for throughput, routed mesh for scale-out); it
trades batch throughput away by construction (one instance, one host
core).  NativeServePool below is the host THROUGHPUT tier: B replica
interpreters sharded across OS threads (cinterp.NativePool), twin to the
batched one-dispatch serve jit (core/engine.py make_batched_serve) — the
tier that keeps a driver-scored bench past the 1M inputs/s north star
when no TPU is attached.
"""

from __future__ import annotations

import time

import numpy as np

from misaka_tpu.core import cinterp
from misaka_tpu.core.state import NetworkState
from misaka_tpu.utils import metrics
from misaka_tpu.utils import tracespan

# Native-tier instrumentation (served at GET /metrics): one histogram for
# every host-interpreter call kind, plus pool-shape gauges.  The label
# children are resolved once — a pool serve costs single-digit us and must
# not pay per-call dict lookups for its own telemetry.
_H_SERVE = metrics.histogram(
    "misaka_native_serve_seconds",
    "Host C++ interpreter call duration by kind (chunk = unbatched "
    "serve_chunk, serve/idle = the thread-pooled batched twins)",
    ("kind",),
)
_H_SERVE_CHUNK = _H_SERVE.labels(kind="chunk")
_H_SERVE_POOL = _H_SERVE.labels(kind="serve")
_H_SERVE_IDLE = _H_SERVE.labels(kind="idle")
_C_CALLS = metrics.counter(
    "misaka_native_serve_calls_total", "Host C++ interpreter calls by kind",
    ("kind",),
)
_C_CALLS_CHUNK = _C_CALLS.labels(kind="chunk")
_C_CALLS_POOL = _C_CALLS.labels(kind="serve")
_C_CALLS_IDLE = _C_CALLS.labels(kind="idle")
_G_POOL_THREADS = metrics.gauge(
    "misaka_native_pool_threads", "OS threads in the live native replica pool"
)
_G_POOL_REPLICAS = metrics.gauge(
    "misaka_native_pool_replicas", "Replica interpreters in the live native pool"
)
_G_POOL_FILL = metrics.gauge(
    "misaka_native_pool_fill_ratio",
    "Fraction of replicas fed on the last pool serve (replica-batch fill)",
)
# The pool gauges are weakref callbacks bound at pool construction (last
# pool wins, like master.py's queue-depth gauges): a closed or collected
# pool must read 0, not its last live values — an engine swap away from
# the native tier would otherwise leave /metrics reporting a running pool
# that no longer exists.


def available() -> bool:
    return cinterp.available()


class NativeServe:
    """serve_chunk twin for one CompiledNetwork, backed by NativeInterpreter."""

    is_native = True  # engine_name dispatch marker (runtime/master.py)

    def __init__(self, net):
        if net.batch is not None:
            raise ValueError("the native engine serves a single network instance")
        self._interp = cinterp.NativeInterpreter(
            np.asarray(net.code), np.asarray(net.prog_len),
            net.num_stacks, net.stack_cap, net.in_cap, net.out_cap,
        )
        self._out_cap = net.out_cap

    def close(self) -> None:
        self._interp.close()

    def validate_state(self, state: NetworkState) -> None:
        """Raise ValueError on a state this engine cannot execute (pc beyond
        the program, stack_top beyond capacity, broken ring counters).
        Importing IS the validation — the interpreter is stateless between
        serve calls, so the imported content is simply overwritten next."""
        self._interp.import_arrays({
            f: np.asarray(getattr(state, f)) for f in NetworkState._fields
        })

    def serve_chunk(self, state: NetworkState, values, count, num_steps: int):
        """See core/engine.py serve_chunk — same contract, host execution."""
        t0 = time.perf_counter()
        it = self._interp
        it.import_arrays({
            f: np.asarray(getattr(state, f)) for f in NetworkState._fields
        })
        count = int(count)
        if count:
            fed = it.feed(np.asarray(values[:count], np.int32))
            if fed != count:  # caller cut to free space; a miss is a bug
                raise RuntimeError(f"native feed accepted {fed}/{count}")
        it.run(int(num_steps))
        d = it.export_arrays()
        packed = np.concatenate([
            np.array([d["in_rd"], d["in_wr"], d["out_rd"], d["out_wr"]],
                     np.int32),
            d["out_buf"],
        ])
        d["out_rd"] = d["out_wr"]  # the returned state's ring is drained
        out = NetworkState(**{f: d[f] for f in NetworkState._fields}), packed
        _C_CALLS_CHUNK.inc()
        _H_SERVE_CHUNK.observe(time.perf_counter() - t0)
        return out


class NativeServePool:
    """Batched serve twins for one CompiledNetwork on the C++ thread pool.

    `serve`/`idle` are drop-in twins of the (serve_fn, idle_fn) pair built
    by CompiledNetwork.make_batched_serve — same signatures, same packed
    [B, 4+out_cap] snapshot layout, same drained-on-serve / untouched-on-
    idle ring discipline — so MasterNode's batched device loop drives this
    tier through the exact code path it drives the jitted engines through.
    B network replicas are embarrassingly parallel (independent instances,
    deterministic per request); the pool shards them across OS threads
    inside one GIL-releasing call.  The canonical state stays the
    NetworkState pytree: each call imports/exports batch-major slices, so
    checkpoint/restore, /load, and stack auto-grow keep working unchanged.
    """

    is_native = True

    def __init__(self, net, chunk_steps: int = 128, threads: int | None = None):
        if net.batch is None:
            raise ValueError("NativeServePool serves a batched network "
                             "(use NativeServe for batch=None)")
        self._pool = cinterp.NativePool(
            np.asarray(net.code), np.asarray(net.prog_len),
            net.num_stacks, net.stack_cap, net.in_cap, net.out_cap,
            replicas=net.batch, threads=threads,
        )
        self.threads = self._pool.threads
        self._chunk = int(chunk_steps)
        self._replicas = net.batch
        self._closed = False
        self._last_fill = 0.0
        # Steady-state identity cache: the master's device loop passes back
        # the exact NetworkState this pool returned last call, whose dict
        # round-trips the exact arrays the C++ side exported — when that
        # identity holds, cinterp skips per-call re-validation (the trusted
        # fast path).  Any lifecycle path that builds a fresh state (load,
        # restore, autogrow pad, drain_batched's _replace) simply misses
        # the cache and takes the validated path.
        self._last_state = None
        self._last_dict = None
        import weakref

        ref = weakref.ref(self)
        _G_POOL_THREADS.set_function(
            lambda: 0 if (p := ref()) is None or p._closed else p.threads
        )
        _G_POOL_REPLICAS.set_function(
            lambda: 0 if (p := ref()) is None or p._closed else p._replicas
        )
        _G_POOL_FILL.set_function(
            lambda: 0.0 if (p := ref()) is None or p._closed else p._last_fill
        )

    def close(self) -> None:
        self._closed = True
        self._pool.close()

    def _to_dict(self, state: NetworkState) -> dict:
        return {f: np.asarray(getattr(state, f)) for f in NetworkState._fields}

    def _to_state(self, d: dict) -> NetworkState:
        d = dict(d)
        d["port_full"] = d["port_full"].astype(bool)
        d["holding"] = d["holding"].astype(bool)
        return NetworkState(**{f: d[f] for f in NetworkState._fields})

    def validate_state(self, state: NetworkState) -> None:
        """Raise ValueError on a state this engine cannot execute (pc beyond
        the program, stack_top beyond capacity, broken ring counters) —
        a zero-tick idle round trip; importing IS the validation."""
        self._pool.idle(self._to_dict(state), 0)

    def serve(self, state: NetworkState, values, counts,
              num_steps: int | None = None, active=None):
        """serve_fn twin: feed counts[b] leading entries of values[b] into
        replica b, advance the chunk, return (state, packed [B, 4+out_cap])
        with the returned state's output rings drained.

        `active` (optional, strictly increasing replica indices covering
        every fed replica) is the partial-fill fast path: only those
        replicas tick — an underfilled pass pays for the replicas doing
        work, not the whole batch (cinterp.NativePool.serve)."""
        t0 = time.perf_counter()
        trusted = state is self._last_state
        d_in = self._last_dict if trusted else self._to_dict(state)
        d, packed = self._pool.serve(
            d_in, values, counts,
            self._chunk if num_steps is None else num_steps,
            active=active, trusted=trusted,
        )
        new_state = self._to_state(d)
        self._last_state, self._last_dict = new_state, d
        out = new_state, packed
        _C_CALLS_POOL.inc()
        dur = time.perf_counter() - t0
        _H_SERVE_POOL.observe(dur)
        # native-tier flight-recorder event (one deque append): the pool
        # call underlying a fused pass, visible in GET /debug/perfetto
        tracespan.note_tier(
            "native.tick",
            dur,
            attrs={"replicas": self._replicas if active is None
                   else int(len(active))},
        )
        self._last_fill = (
            float((np.asarray(counts) > 0).sum()) / max(1, self._replicas)
        )
        return out

    def idle(self, state: NetworkState, num_steps: int | None = None,
             active=None):
        """idle_fn twin: advance the chunk with no feed, return
        (state, ctrs [B, 4]); output rings left undrained.  `active`
        restricts the pass to the given replica indices (partial fill)."""
        t0 = time.perf_counter()
        trusted = state is self._last_state
        d_in = self._last_dict if trusted else self._to_dict(state)
        d, ctrs = self._pool.idle(
            d_in,
            self._chunk if num_steps is None else num_steps,
            active=active, trusted=trusted,
        )
        new_state = self._to_state(d)
        self._last_state, self._last_dict = new_state, d
        out = new_state, ctrs
        _C_CALLS_IDLE.inc()
        _H_SERVE_IDLE.observe(time.perf_counter() - t0)
        return out
