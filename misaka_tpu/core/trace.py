"""Per-lane instruction trace ring buffer — device-resident execution history.

The reference's only execution visibility is a stdout log line per instruction
(program.go:222-223, marked "TODO: remove this") — unusable at TPU rates and
gone the moment the scroll passes.  Here the equivalent is an HBM-resident
ring (SURVEY.md §5 "optional per-lane instruction trace ring buffer"): each
traced tick appends every lane's (pc, opcode, committed, acc-after) to a
fixed-capacity ring entirely inside the jitted scan — zero host syncs while
recording — and the host decodes it afterwards with the disassembler.

This is the debug path, deliberately separate from the hot kernel: `step`
stays trace-free, `traced_step` wraps it.  Recording costs one dynamic-slice
store per tick; capacity is a compile-time constant.

Layout: `buf[lane, slot, field]` with slot = tick % cap and four fields
(TR_PC, TR_OP, TR_COMMIT, TR_ACC).  `wr` counts traced ticks; when wr > cap
the ring has wrapped and only the last `cap` ticks survive.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax.numpy as jnp
import numpy as np

from misaka_tpu.core.state import NetworkState
from misaka_tpu.core.step import step
from misaka_tpu.tis import isa
from misaka_tpu.tis.disasm import disassemble_line

_I32 = jnp.int32

# Trace record fields.
TR_PC = 0      # pc at fetch
TR_OP = 1      # opcode fetched
TR_COMMIT = 2  # 1 if the instruction committed, 0 if the lane parked
TR_ACC = 3     # acc AFTER the tick (the committed result)
TR_NFIELDS = 4


class TraceRing(NamedTuple):
    """Device-resident execution history for one network instance."""

    # wr is uint32 (not int32): a long-soak traced master passes 2^31 ticks in
    # hours, and a signed wrap would make `wr % cap` negative and decode_trace
    # silently empty.  Unsigned, the counter stays index-safe and merely
    # restarts its tick labels every 2^32 ticks (int64 needs jax_enable_x64).
    buf: jnp.ndarray  # [N, CAP, TR_NFIELDS] int32
    wr: jnp.ndarray   # uint32 scalar — traced ticks so far (slot = wr % CAP)


def init_trace(num_lanes: int, cap: int = 256) -> TraceRing:
    return TraceRing(
        buf=jnp.zeros((num_lanes, cap, TR_NFIELDS), np.int32),
        wr=jnp.zeros((), np.uint32),
    )


def record_step(
    code: jnp.ndarray,
    before: NetworkState,
    after: NetworkState,
    trace: TraceRing,
) -> TraceRing:
    """Append one tick's record for every lane of ONE network instance.

    `before`/`after` are the instance's state around the tick (unbatched
    shapes); the caller owns the step itself — this lets the batched engine
    record a single instance out of a vmapped step (engine.py)."""
    n_lanes = code.shape[0]
    lane = jnp.arange(n_lanes)
    pc_before = before.pc
    op = code[lane, pc_before, isa.F_OP]
    committed = after.retired - before.retired  # [N] 0/1

    # acc column records the LOW (wire) word of the 64-bit register — one
    # int32 per entry keeps the ring dense; debug.inspect shows full width
    record = jnp.stack([pc_before, op, committed, after.acc], axis=-1)  # [N, 4]
    cap = trace.buf.shape[1]
    slot = trace.wr % cap
    new_buf = trace.buf.at[:, slot, :].set(record)
    return TraceRing(buf=new_buf, wr=trace.wr + 1)


def traced_step(
    code: jnp.ndarray,
    prog_len: jnp.ndarray,
    state: NetworkState,
    trace: TraceRing,
) -> tuple[NetworkState, TraceRing]:
    """One superstep + one trace record per lane (identical state semantics)."""
    new_state = step(code, prog_len, state)
    return new_state, record_step(code, state, new_state, trace)


def run_traced(
    code: jnp.ndarray,
    prog_len: jnp.ndarray,
    state: NetworkState,
    trace: TraceRing,
    num_steps: int,
) -> tuple[NetworkState, TraceRing]:
    """`num_steps` traced supersteps under one lax.scan (jit-friendly)."""
    import jax

    def body(carry, _):
        s, t = carry
        return traced_step(code, prog_len, s, t), None

    (state, trace), _ = jax.lax.scan(body, (state, trace), None, length=num_steps)
    return state, trace


def decode_trace(
    trace: TraceRing,
    code: np.ndarray,
    prog_len: np.ndarray,
    lane_names: Sequence[str] | None = None,
    stack_names: Sequence[str] | None = None,
    last: int | None = None,
) -> list[dict]:
    """Host-side decode: the ring as a list of per-tick dicts, oldest first.

    Each entry: {"tick", "lane", "name", "pc", "op", "committed", "acc",
    "text"} where `text` is the disassembled instruction the lane executed
    (or retried, if parked).
    """
    buf = np.asarray(trace.buf)
    wr = int(trace.wr)
    n_lanes, cap, _ = buf.shape
    code = np.asarray(code)
    lane_names = list(lane_names) if lane_names else [f"node{i}" for i in range(n_lanes)]
    if not stack_names:
        max_tgt = int(code[..., isa.F_TGT].max(initial=0))
        stack_names = [f"stack{i}" for i in range(max_tgt + 1)]
    else:
        stack_names = list(stack_names)

    n_avail = min(wr, cap)
    if last is not None:
        n_avail = min(n_avail, last)
    first_tick = wr - n_avail

    out = []
    for tick in range(first_tick, wr):
        slot = tick % cap
        for lane in range(n_lanes):
            pc, op, committed, acc = (int(v) for v in buf[lane, slot])
            pc_clipped = min(pc, code.shape[1] - 1)
            try:
                text = disassemble_line(code[lane, pc_clipped], lane_names, stack_names)
            except Exception:  # malformed row (e.g. trace older than a /load)
                text = f"<op {op}>"
            out.append(
                {
                    "tick": tick,
                    "lane": lane,
                    "name": lane_names[lane],
                    "pc": pc,
                    "op": isa.OP_NAMES.get(op, str(op)),
                    "committed": bool(committed),
                    "acc": acc,
                    "text": text,
                }
            )
    return out


def format_trace(entries: list[dict]) -> str:
    """Render decoded entries as an aligned text listing (debugger output)."""
    lines = []
    for e in entries:
        mark = " " if e["committed"] else "*"  # * = parked/retry
        lines.append(
            f"t={e['tick']:>6} {e['name']:>10} pc={e['pc']:>3}{mark} "
            f"acc={e['acc']:>11} | {e['text']}"
        )
    return "\n".join(lines)
