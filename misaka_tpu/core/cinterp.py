"""ctypes bridge to the native C++ superstep interpreter (native/interpreter.cpp).

A zero-JAX host executor with the exact tick discipline of the kernels
(core/step.py docstring): useful as a third independent implementation for
differential testing, and as a microsecond-latency single-instance engine for
control-plane-sized runs where a device round-trip isn't worth it.

Build with `make native` (repo root) or let this module build it on first
use (g++, ~1s).  `available()` reports whether the backend can load.
"""

from __future__ import annotations

import ctypes
import os
import sys
import threading

import numpy as np

from misaka_tpu.tis import isa
from misaka_tpu.utils import metrics
from misaka_tpu.utils.nativelib import NativeLib

# Lifecycle counters for the C++ handles (GET /metrics): a leak shows as
# created climbing without closed following — the native pool owns real OS
# threads, so this pair is the observable for the _close_runner discipline
# (runtime/master.py replaces engines on load/restore/autogrow).
_C_CREATED = metrics.counter(
    "misaka_native_engines_created_total",
    "Native C++ engine handles created, by kind", ("kind",),
)
_C_CLOSED = metrics.counter(
    "misaka_native_engines_closed_total",
    "Native C++ engine handles explicitly closed or GC-finalized, by kind",
    ("kind",),
)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_I32P = ctypes.POINTER(ctypes.c_int32)
_U8P = ctypes.POINTER(ctypes.c_uint8)


def _configure(lib: ctypes.CDLL) -> None:
    lib.misaka_interp_create.restype = ctypes.c_void_p
    lib.misaka_interp_create.argtypes = [_I32P, _I32P] + [ctypes.c_int] * 6
    lib.misaka_interp_destroy.restype = None
    lib.misaka_interp_destroy.argtypes = [ctypes.c_void_p]
    lib.misaka_interp_feed.restype = ctypes.c_int
    lib.misaka_interp_feed.argtypes = [ctypes.c_void_p, _I32P, ctypes.c_int]
    lib.misaka_interp_run.restype = None
    lib.misaka_interp_run.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.misaka_interp_drain.restype = ctypes.c_int
    lib.misaka_interp_drain.argtypes = [ctypes.c_void_p, _I32P, ctypes.c_int]
    lib.misaka_interp_seed_counters.restype = ctypes.c_int
    lib.misaka_interp_seed_counters.argtypes = [ctypes.c_void_p] + [ctypes.c_int32] * 4
    lib.misaka_interp_read.restype = None
    lib.misaka_interp_read.argtypes = [ctypes.c_void_p] + [
        _I32P, _I32P, _I32P, _I32P, _U8P, _I32P, _U8P,
        _I32P, _I32P, _I32P, _I32P, _I32P, _I32P, _I32P,
    ]
    lib.misaka_interp_read_in.restype = None
    lib.misaka_interp_read_in.argtypes = [ctypes.c_void_p, _I32P]
    lib.misaka_interp_write.restype = ctypes.c_int
    lib.misaka_interp_write.argtypes = [ctypes.c_void_p] + [
        _I32P, _I32P, _I32P, _I32P, _U8P, _I32P, _U8P,
        _I32P, _I32P, _I32P, _I32P, _I32P, _I32P, _I32P, _I32P,
    ]
    lib.misaka_pool_create.restype = ctypes.c_void_p
    lib.misaka_pool_create.argtypes = [_I32P, _I32P] + [ctypes.c_int] * 8
    lib.misaka_pool_destroy.restype = None
    lib.misaka_pool_destroy.argtypes = [ctypes.c_void_p]
    lib.misaka_pool_threads.restype = ctypes.c_int
    lib.misaka_pool_threads.argtypes = [ctypes.c_void_p]
    _I64P = ctypes.POINTER(ctypes.c_int64)
    lib.misaka_pool_counters.restype = None
    lib.misaka_pool_counters.argtypes = [ctypes.c_void_p, _I64P]
    lib.misaka_pool_thread_counters.restype = ctypes.c_int
    lib.misaka_pool_thread_counters.argtypes = [
        ctypes.c_void_p, _I64P, _I64P, ctypes.c_int,
    ]
    lib.misaka_pool_serve.restype = ctypes.c_int
    lib.misaka_pool_serve.argtypes = [ctypes.c_void_p] + [
        _I32P, _I32P, _I32P, _I32P, _U8P, _I32P, _U8P,
        _I32P, _I32P, _I32P, _I32P, _I32P, _I32P, _I32P, _I32P,
        _I32P, _I32P, ctypes.c_int, _I32P, ctypes.c_int, _I32P,
    ]
    lib.misaka_pool_simd_info.restype = None
    lib.misaka_pool_simd_info.argtypes = [ctypes.c_void_p, _I32P]
    lib.misaka_spec_key.restype = ctypes.c_char_p
    lib.misaka_spec_key.argtypes = []
    # resident-state serving (r17)
    lib.misaka_interp_pack.restype = None
    lib.misaka_interp_pack.argtypes = [ctypes.c_void_p, _I32P, ctypes.c_int]
    _STATE15 = [
        _I32P, _I32P, _I32P, _I32P, _U8P, _I32P, _U8P,
        _I32P, _I32P, _I32P, _I32P, _I32P, _I32P, _I32P, _I32P,
    ]
    lib.misaka_pool_import.restype = ctypes.c_int
    lib.misaka_pool_import.argtypes = [ctypes.c_void_p] + _STATE15
    lib.misaka_pool_export.restype = ctypes.c_int
    lib.misaka_pool_export.argtypes = [ctypes.c_void_p] + _STATE15
    lib.misaka_pool_discard.restype = None
    lib.misaka_pool_discard.argtypes = [ctypes.c_void_p]
    lib.misaka_pool_is_resident.restype = ctypes.c_int
    lib.misaka_pool_is_resident.argtypes = [ctypes.c_void_p]
    lib.misaka_pool_serve_resident.restype = ctypes.c_int
    lib.misaka_pool_serve_resident.argtypes = [
        ctypes.c_void_p, _I32P, _I32P, ctypes.c_int, _I32P, ctypes.c_int,
        _I32P, _U8P, ctypes.c_int,
    ]
    # copy-and-patch JIT rung (r21).  Absent from pre-r21 builds loaded
    # via MISAKA_INTERP_SO (sanitizer lanes): the ladder then tops out at
    # switch-threaded — jit_arm() reports rc -9 and the caller falls back.
    try:
        _VPP = ctypes.POINTER(ctypes.c_void_p)
        lib.misaka_pool_jit_arm.restype = ctypes.c_int
        lib.misaka_pool_jit_arm.argtypes = [
            ctypes.c_void_p, _VPP, _VPP, ctypes.c_int, ctypes.c_int,
            ctypes.c_int,
        ]
        lib.misaka_pool_jit_disarm.restype = None
        lib.misaka_pool_jit_disarm.argtypes = [ctypes.c_void_p]
    except AttributeError:
        pass
    # flight recorder (r18)
    lib.misaka_pool_trace_info.restype = None
    lib.misaka_pool_trace_info.argtypes = [ctypes.c_void_p, _I64P]
    lib.misaka_pool_trace_read.restype = ctypes.c_int
    lib.misaka_pool_trace_read.argtypes = [
        ctypes.c_void_p, ctypes.c_int, _I64P, ctypes.c_int, _I64P,
    ]
    lib.misaka_pool_trace_stats.restype = None
    lib.misaka_pool_trace_stats.argtypes = [ctypes.c_void_p, _I64P]
    lib.misaka_pool_trace_set.restype = ctypes.c_int
    lib.misaka_pool_trace_set.argtypes = [ctypes.c_void_p, ctypes.c_int]


_NATIVE = NativeLib(
    os.path.join(_REPO_ROOT, "native", "interpreter.cpp"),
    os.path.join(_REPO_ROOT, "native", "libmisaka_interp.so"),
    _configure,
    so_env="MISAKA_INTERP_SO",  # sanitizer lanes load instrumented builds
)


def _load() -> ctypes.CDLL | None:
    return _NATIVE.load()


def available() -> bool:
    return _NATIVE.available()


# Per-program specialized builds (core/specialize.py): each cached .so is
# the full interpreter ABI compiled with one network's tables baked in.
# dlopen caches by path, but ctypes.CDLL re-runs symbol setup per call —
# keep one configured handle per path (never evicted: a handle must
# outlive every pool created from it, and the set is bounded by the
# registry's activation cache).
_SPEC_LIBS: dict[str, ctypes.CDLL] = {}
_SPEC_LIBS_LOCK = threading.Lock()


def load_specialized(path: str) -> ctypes.CDLL:
    """Load + configure a specialized interpreter .so.  Raises on any
    load/symbol failure — callers fall back to the generic library."""
    with _SPEC_LIBS_LOCK:
        lib = _SPEC_LIBS.get(path)
        if lib is None:
            lib = ctypes.CDLL(path)
            _configure(lib)
            if not lib.misaka_spec_key():  # a generic build is NOT a spec
                raise ValueError(f"{path} carries no specialization key")
            _SPEC_LIBS[path] = lib
        return lib


def _as_i32p(arr: np.ndarray):
    return arr.ctypes.data_as(_I32P)


_I32_INFO = np.iinfo(np.int32)


def _checked_i32(key: str, value, shape: tuple | None = None) -> np.ndarray:
    """Contiguous int32 array of `value`, REJECTING lossy casts: a wider
    integer (hand-edited/corrupt checkpoint) whose values exceed the int32
    range raises ValueError instead of silently wrapping — wrapped values
    can pass the C-side range validation while meaning something else."""
    a = np.asarray(value)
    if shape is not None and a.shape != shape:
        raise ValueError(f"{key}: expected shape {shape}, got {a.shape}")
    if a.dtype != np.int32:
        if a.dtype.kind not in "iub":
            raise ValueError(
                f"{key}: dtype {a.dtype} cannot carry int32 state exactly"
            )
        if a.dtype.kind in "iu" and a.size and not np.can_cast(a.dtype, np.int32):
            mn, mx = int(a.min()), int(a.max())
            if mn < _I32_INFO.min or mx > _I32_INFO.max:
                raise ValueError(
                    f"{key}: values [{mn}, {mx}] out of int32 range "
                    f"(corrupt or hand-edited state?)"
                )
    return np.ascontiguousarray(a, dtype=np.int32)


def _checked_i32_int(key: str, v) -> int:
    iv = int(v)
    if not (_I32_INFO.min <= iv <= _I32_INFO.max):
        raise ValueError(f"{key}: value {iv} out of int32 range")
    return iv


def _checked_u8(key: str, value, shape: tuple) -> np.ndarray:
    """Contiguous uint8 FLAG plane: truthiness-preserving conversion.

    astype(uint8) would wrap wide values (256 -> 0), flipping a truthy
    flag to False with no error — the same lossy-cast class _checked_i32
    rejects.  Flags are booleans, so convert by `!= 0` (any nonzero stays
    1) and reject non-integer dtypes outright."""
    a = np.asarray(value)
    if a.shape != shape:
        raise ValueError(f"{key}: expected shape {shape}, got {a.shape}")
    if a.dtype.kind not in "iub":
        raise ValueError(f"{key}: dtype {a.dtype} is not a valid flag plane")
    return np.ascontiguousarray(a != 0).astype(np.uint8)


class NativeInterpreter:
    """One network instance stepped by the C++ engine (Oracle-compatible API)."""

    def __init__(self, code, prog_len, num_stacks, stack_cap, in_cap, out_cap):
        lib = _load()
        if lib is None:
            raise RuntimeError("native interpreter unavailable (no g++?)")
        self._lib = lib
        code = np.ascontiguousarray(code, dtype=np.int32)
        prog_len = np.ascontiguousarray(prog_len, dtype=np.int32)
        if code.ndim != 3 or code.shape[2] != isa.NFIELDS:
            raise ValueError(
                f"code must be [n_lanes, max_len, {isa.NFIELDS}], got {code.shape}"
            )
        if prog_len.shape != (code.shape[0],):
            raise ValueError(
                f"prog_len must have shape ({code.shape[0]},), got {prog_len.shape}"
            )
        self.n_lanes, self.max_len, _ = code.shape
        self.num_stacks = max(1, num_stacks)
        self.stack_cap = stack_cap
        self.in_cap = in_cap
        self.out_cap = out_cap
        self._h = lib.misaka_interp_create(
            _as_i32p(code),
            _as_i32p(prog_len),
            self.n_lanes,
            self.max_len,
            self.num_stacks,
            stack_cap,
            in_cap,
            out_cap,
        )
        if not self._h:
            raise ValueError("invalid network tables")
        _C_CREATED.labels(kind="interp").inc()

    def close(self) -> None:
        if self._h:
            self._lib.misaka_interp_destroy(self._h)
            self._h = None
            _C_CLOSED.labels(kind="interp").inc()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _handle(self):
        if not self._h:
            raise RuntimeError("interpreter is closed")
        return self._h

    def feed(self, values) -> int:
        vals = np.ascontiguousarray(values, dtype=np.int32)
        return self._lib.misaka_interp_feed(self._handle(), _as_i32p(vals), len(vals))

    def run(self, ticks: int) -> None:
        self._lib.misaka_interp_run(self._handle(), int(ticks))

    def drain(self) -> list[int]:
        out = np.zeros((self.out_cap,), np.int32)
        got = self._lib.misaka_interp_drain(self._handle(), _as_i32p(out), self.out_cap)
        return out[:got].tolist()

    def seed_counters(self, in_rd: int, in_wr: int, out_rd: int, out_wr: int) -> None:
        """Set the ring counters directly (checkpoint restore / soak tests).

        Raises ValueError when the counters violate the ring invariants
        (0 <= rd <= wr, wr - rd <= cap) — the C side rejects them with the
        interpreter state unchanged."""
        rc = self._lib.misaka_interp_seed_counters(
            self._handle(), int(in_rd), int(in_wr), int(out_rd), int(out_wr)
        )
        if rc != 0:
            raise ValueError(
                f"invalid ring counters: in {in_rd}/{in_wr} (cap {self.in_cap}), "
                f"out {out_rd}/{out_wr} (cap {self.out_cap})"
            )

    def _read_raw(self) -> dict:
        """One misaka_interp_read into fresh buffers: the shared read path
        of state_arrays (differential view) and export_arrays (serving
        view) — a signature change lands in exactly one place."""
        n, s, cap = self.n_lanes, self.num_stacks, self.stack_cap
        d = {
            "acc": np.zeros(n, np.int32), "bak": np.zeros(n, np.int32),
            "acc_hi": np.zeros(n, np.int32), "bak_hi": np.zeros(n, np.int32),
            "pc": np.zeros(n, np.int32),
            "port_val": np.zeros((n, isa.NUM_PORTS), np.int32),
            "port_full": np.zeros((n, isa.NUM_PORTS), np.uint8),
            "hold_val": np.zeros(n, np.int32),
            "holding": np.zeros(n, np.uint8),
            "stack_mem": np.zeros((s, cap), np.int32),
            "stack_top": np.zeros(s, np.int32),
            "out_buf": np.zeros(self.out_cap, np.int32),
            "counters": np.zeros(5, np.int32),
            "retired": np.zeros(n, np.int32),
        }
        self._lib.misaka_interp_read(
            self._handle(),
            _as_i32p(d["acc"]), _as_i32p(d["bak"]), _as_i32p(d["pc"]),
            _as_i32p(d["port_val"]), d["port_full"].ctypes.data_as(_U8P),
            _as_i32p(d["hold_val"]), d["holding"].ctypes.data_as(_U8P),
            _as_i32p(d["stack_mem"]), _as_i32p(d["stack_top"]),
            _as_i32p(d["out_buf"]), _as_i32p(d["counters"]),
            _as_i32p(d["retired"]), _as_i32p(d["acc_hi"]),
            _as_i32p(d["bak_hi"]),
        )
        return d

    def pack(self, drain: bool = True) -> np.ndarray:
        """The serve_chunk packed row [in_rd, in_wr, out_rd, out_wr,
        out_buf...] straight off the interpreter, draining the output ring
        AFTER the snapshot when `drain` (the resident-state serve path:
        the counters + ring are the only per-chunk reads, so the full
        state export stays on the lifecycle paths).  With drain=False only
        the four counters are filled."""
        row = np.empty((4 + self.out_cap,), np.int32)
        self._lib.misaka_interp_pack(
            self._handle(), _as_i32p(row), 1 if drain else 0
        )
        return row

    def state_arrays(self) -> dict:
        """Mirror tests/oracle.py state_arrays for differential comparison."""
        d = self._read_raw()
        counters = d.pop("counters")
        d["port_full"] = d["port_full"].astype(bool)
        d["holding"] = d["holding"].astype(bool)
        d["stack_mem_used"] = d.pop("stack_mem")
        d["in_rd"] = counters[0]
        d["out_wr"] = counters[3]
        d["tick"] = counters[4]
        return d

    def export_arrays(self) -> dict:
        """COMPLETE state export for the serving engine: every NetworkState
        field (core/state.py), stack_mem zero-padded above each top.  The
        superset of state_arrays (which keeps its differential-comparison
        key set and naming)."""
        d = self._read_raw()
        counters = d.pop("counters")
        d["port_full"] = d["port_full"].astype(bool)
        d["holding"] = d["holding"].astype(bool)
        in_buf = np.zeros(self.in_cap, np.int32)
        self._lib.misaka_interp_read_in(self._handle(), _as_i32p(in_buf))
        d["in_buf"] = in_buf
        d["in_rd"], d["in_wr"] = counters[0], counters[1]
        d["out_rd"], d["out_wr"] = counters[2], counters[3]
        d["tick"] = counters[4]
        return d

    def import_arrays(self, d: dict) -> None:
        """Bulk state write — the inverse of export_arrays.  Raises
        ValueError (interpreter unchanged) on out-of-range pc/top/counters
        AND on wider-integer inputs whose values do not fit int32 (an unsafe
        cast would wrap them into the valid range — see _checked_i32)."""
        n, s = self.n_lanes, self.num_stacks

        def i32arr(key, shape):
            return _checked_i32(key, d[key], shape)

        def u8arr(key, shape):
            return _checked_u8(key, d[key], shape)

        acc = i32arr("acc", (n,)); bak = i32arr("bak", (n,))
        acc_hi = i32arr("acc_hi", (n,)); bak_hi = i32arr("bak_hi", (n,))
        pc = i32arr("pc", (n,))
        port_val = i32arr("port_val", (n, isa.NUM_PORTS))
        port_full = u8arr("port_full", (n, isa.NUM_PORTS))
        hold_val = i32arr("hold_val", (n,))
        holding = u8arr("holding", (n,))
        stack_mem = i32arr("stack_mem", (s, self.stack_cap))
        stack_top = i32arr("stack_top", (s,))
        in_buf = i32arr("in_buf", (self.in_cap,))
        out_buf = i32arr("out_buf", (self.out_cap,))
        retired = i32arr("retired", (n,))
        counters = np.ascontiguousarray(
            [_checked_i32_int(k, d[k])
             for k in ("in_rd", "in_wr", "out_rd", "out_wr", "tick")],
            dtype=np.int32,
        )
        rc = self._lib.misaka_interp_write(
            self._handle(),
            _as_i32p(acc), _as_i32p(bak), _as_i32p(pc),
            _as_i32p(port_val), port_full.ctypes.data_as(_U8P),
            _as_i32p(hold_val), holding.ctypes.data_as(_U8P),
            _as_i32p(stack_mem), _as_i32p(stack_top),
            _as_i32p(in_buf), _as_i32p(out_buf), _as_i32p(counters),
            _as_i32p(retired), _as_i32p(acc_hi), _as_i32p(bak_hi),
        )
        if rc != 0:
            raise ValueError(
                "invalid state import (pc/stack_top/ring counters out of range)"
            )


class NativePool:
    """B replica interpreters served by a persistent C++ OS-thread pool.

    The multi-threaded host serving tier: one `serve`/`idle` call runs a
    whole batched chunk iteration — per replica: import its state slice,
    feed, run `ticks`, snapshot a packed row, export — with the replica
    range sharded across threads inside ONE ctypes call (which releases the
    GIL, so C++ workers saturate cores while Python serves HTTP).  State
    lives in the caller's batch-major arrays between calls, exactly like
    the stateless single-instance NativeInterpreter serve path.
    """

    def __init__(self, code, prog_len, num_stacks, stack_cap, in_cap, out_cap,
                 replicas, threads: int | None = None,
                 lib: ctypes.CDLL | None = None):
        # `lib` overrides the shared generic library with a per-program
        # specialized build (load_specialized) — same ABI, baked tables
        if lib is None:
            lib = _load()
        if lib is None:
            raise RuntimeError("native interpreter unavailable (no g++?)")
        self._lib = lib
        code = np.ascontiguousarray(code, dtype=np.int32)
        prog_len = np.ascontiguousarray(prog_len, dtype=np.int32)
        if code.ndim != 3 or code.shape[2] != isa.NFIELDS:
            raise ValueError(
                f"code must be [n_lanes, max_len, {isa.NFIELDS}], got {code.shape}"
            )
        if prog_len.shape != (code.shape[0],):
            raise ValueError(
                f"prog_len must have shape ({code.shape[0]},), got {prog_len.shape}"
            )
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.n_lanes, self.max_len, _ = code.shape
        self.num_stacks = max(1, num_stacks)
        self.stack_cap = stack_cap
        self.in_cap = in_cap
        self.out_cap = out_cap
        self.replicas = int(replicas)
        if threads is None:
            threads = int(os.environ.get("MISAKA_NATIVE_THREADS", "0") or 0) \
                or (os.cpu_count() or 1)
        self._h = lib.misaka_pool_create(
            _as_i32p(code), _as_i32p(prog_len),
            self.n_lanes, self.max_len, self.num_stacks,
            stack_cap, in_cap, out_cap, self.replicas, int(threads),
        )
        if not self._h:
            raise ValueError("invalid network tables")
        self.threads = int(lib.misaka_pool_threads(self._h))
        # Serializes counter READS against destroy: the r12 debug surfaces
        # (/metrics, /debug/usage, /debug/flamegraph) read counters() from
        # scrape threads while a registry eviction/hot-swap may close()
        # this pool — the _h None-check alone is TOCTOU-racy (a reader
        # past the check would dereference a freed C++ Pool).  serve/idle
        # stay outside the lock: only the device loop calls them, and the
        # engine quiesces before close by construction.
        self._ctr_lock = threading.Lock()
        # pack-row elision buffers (serve_resident(reuse_out=True)) and
        # the armed JIT program (kept alive: the C++ side holds raw
        # pointers into its executable buffer until disarm/close)
        self._packed_serve = None
        self._packed_idle = None
        self._progress_buf = None
        self._jit_prog = None
        _C_CREATED.labels(kind="pool").inc()

    def close(self) -> None:
        with self._ctr_lock:
            if self._h:
                self._lib.misaka_pool_destroy(self._h)
                self._h = None
                self._jit_prog = None  # exec buffer may now be unmapped
                _C_CLOSED.labels(kind="pool").inc()

    def __del__(self):
        try:
            # NEVER destroy the C++ pool during interpreter finalization:
            # a daemon device-loop thread may be frozen inside a
            # GIL-released serve call (CPython parks daemon threads at
            # their next GIL acquisition, so the C++ side keeps waiting on
            # cv_done) — destroying the condition variable under that
            # waiter is UB and aborts the whole process ("terminate called
            # without an active exception").  The OS reclaims the threads
            # and memory at exit anyway; explicit close() keeps the
            # quiesced-by-construction contract for normal lifecycles.
            if sys.is_finalizing():
                return
            self.close()
        except Exception:
            pass

    def _handle(self):
        if not self._h:
            raise RuntimeError("pool is closed")
        return self._h

    def simd_info(self) -> dict:
        """The pool's execution mode: {"width": replicas per SIMD group
        (0 = scalar per-replica path), "avx2": AVX2 instantiation selected,
        "specialized": per-program baked tick functions engaged, "jit":
        copy-and-patch fragment tables armed (r21)}."""
        out = np.zeros((4,), np.int32)
        with self._ctr_lock:
            self._lib.misaka_pool_simd_info(self._handle(), _as_i32p(out))
        return {
            "width": int(out[0]),
            "avx2": bool(out[1]),
            "specialized": bool(out[2]),
            "jit": bool(out[3]),
        }

    def jit_arm(self, prog) -> int:
        """Arm the copy-and-patch JIT rung (r21) with a core/jit.py
        JitProgram.  Returns the C rc: 0 armed (the pool now dispatches
        group ticks through the spliced fragments and this pool keeps the
        program's executable buffer alive), nonzero = pool unchanged, the
        caller serves one rung down (-1 ABI drift, -2 scalar pool, -3
        shape mismatch, -4 bad tables, -9 pre-r21 native library)."""
        fn = getattr(self._lib, "misaka_pool_jit_arm", None)
        if fn is None:
            return -9
        with self._ctr_lock:
            rc = int(fn(self._handle(), prog.tab1, prog.tab2,
                        int(prog.n_lanes), int(prog.max_len),
                        int(prog.abi)))
        if rc == 0:
            self._jit_prog = prog
        return rc

    def jit_disarm(self) -> None:
        """Drop back to the switch-threaded / generic tick and release
        the pool's hold on the JIT program's executable buffer."""
        fn = getattr(self._lib, "misaka_pool_jit_disarm", None)
        with self._ctr_lock:
            if fn is not None and self._h:
                fn(self._h)
            self._jit_prog = None

    def counters(self) -> dict:
        """Pool busy/idle nanosecond counters (the usage-accounting plane):
        `busy_ns` is worker-thread time spent executing replica supersteps,
        `idle_ns` time parked awaiting work, `serial_ns` the small-pass
        fast path run on the calling thread.  Lock-free on the C++ side
        (safe concurrently with serve/idle); _ctr_lock only fences the
        read against a concurrent close() freeing the Pool."""
        out = np.zeros((5,), np.int64)
        with self._ctr_lock:
            self._lib.misaka_pool_counters(
                self._handle(),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            )
        return {
            "threads": self.threads,
            "busy_ns": int(out[0]),
            "idle_ns": int(out[1]),
            "serial_ns": int(out[2]),
            # the caller-inline lane, first-class (r18): serial_ns IS
            # work booked on the calling thread (zero-handoff inline,
            # caller help, the small-pass fast path) — surfaced under
            # its own name, with work_ns the one total conservation
            # checks read instead of re-deriving busy + serial
            "caller_inline_ns": int(out[2]),
            "work_ns": int(out[0]) + int(out[2]),
            # pack-row elision (r21): quiescent rows whose write into a
            # REUSED packed buffer was skipped vs actually written
            "elided_rows": int(out[3]),
            "skip_packed_rows": int(out[4]),
        }

    def thread_counters(self) -> tuple[np.ndarray, np.ndarray]:
        """Per-thread (busy_ns, idle_ns) arrays — the skew diagnostic
        behind the aggregate counters()."""
        i64p = ctypes.POINTER(ctypes.c_int64)
        busy = np.zeros((self.threads,), np.int64)
        idle = np.zeros((self.threads,), np.int64)
        with self._ctr_lock:
            self._lib.misaka_pool_thread_counters(
                self._handle(), busy.ctypes.data_as(i64p),
                idle.ctypes.data_as(i64p), self.threads,
            )
        return busy, idle

    # --- flight recorder (r18) -----------------------------------------

    # Event kinds (native/interpreter.cpp TraceEv) and the rung/shape tag
    # decode for TEV_UNIT args — shared by native_serve's exporters.
    TRACE_EVENTS = {
        1: "serve", 2: "unit", 3: "spin", 4: "yield", 5: "park",
        6: "import", 7: "export", 8: "discard",
    }
    TRACE_RUNGS = {
        0: "scalar", 1: "generic", 2: "avx2",
        5: "spec-generic", 6: "spec-avx2",
        # bit 3 = copy-and-patch JIT armed (r21); in practice the JIT
        # rides the generic lib (the spec switch tick outranks it inside
        # a specialized .so), so 9/10 are the live values
        9: "jit", 10: "jit-avx2", 13: "spec-jit", 14: "spec-avx2-jit",
    }
    TRACE_SHAPES = {0: "group", 1: "scalar", 2: "masked"}
    _TRACE_STAT_KEYS = (
        "spin_ns", "yield_ns", "park_ns", "wakes",
        "dispatch_calls", "dispatch_wait_ns", "last_dispatch_wait_ns",
        "last_unit_imbalance", "caller_units", "serve_calls",
        "inline_calls", "dropped",
    )

    def trace_info(self) -> dict:
        """Recorder shape: ring count (0 = MISAKA_NATIVE_TRACE=0 skipped
        the build), records per ring, armed flag, and the cumulative
        oldest-dropped (overwritten) record count across rings."""
        out = np.zeros((4,), np.int64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        with self._ctr_lock:
            self._lib.misaka_pool_trace_info(
                self._handle(), out.ctypes.data_as(i64p)
            )
        return {
            "rings": int(out[0]), "capacity": int(out[1]),
            "armed": bool(out[2]), "dropped": int(out[3]),
        }

    def trace_read(self, ring: int, max_records: int | None = None):
        """Snapshot one per-thread event ring without stopping the pool:
        (records [n, 4] int64 rows of [t0_ns, dur_ns, kind, arg] oldest
        first, cursor, dropped).  Ring `threads` is the calling thread's
        (serve lifecycle + caller-inline units + residency events).
        Raises ValueError on a bad ring index or an unbuilt recorder."""
        i64p = ctypes.POINTER(ctypes.c_int64)
        meta = np.zeros((2,), np.int64)
        with self._ctr_lock:
            info = np.zeros((4,), np.int64)
            self._lib.misaka_pool_trace_info(
                self._handle(), info.ctypes.data_as(i64p)
            )
            cap = int(info[1])
            want = cap if max_records is None else min(cap, int(max_records))
            buf = np.zeros((max(1, want), 4), np.int64)
            n = self._lib.misaka_pool_trace_read(
                self._handle(), int(ring), buf.ctypes.data_as(i64p),
                want, meta.ctypes.data_as(i64p),
            )
        if n < 0:
            raise ValueError(
                f"bad trace ring {ring} (recorder built: {bool(info[0])})"
            )
        return buf[:n], int(meta[0]), int(meta[1])

    def trace_stats(self) -> dict:
        """Cumulative recorder aggregates (lock-free relaxed reads on the
        C++ side): dispenser wait ns by phase, wake/dispatch/serve call
        counters, last dispatch wait + unit imbalance, caller-inline
        units, dropped records, and replicas ticked per (rung, shape)."""
        out = np.zeros((12 + 64,), np.int64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        with self._ctr_lock:
            self._lib.misaka_pool_trace_stats(
                self._handle(), out.ctypes.data_as(i64p)
            )
        d = {k: int(out[i]) for i, k in enumerate(self._TRACE_STAT_KEYS)}
        reps = {}
        for rung in range(16):
            for shape in range(4):
                v = int(out[12 + rung * 4 + shape])
                if v:
                    reps[(
                        self.TRACE_RUNGS.get(rung, f"rung{rung}"),
                        self.TRACE_SHAPES.get(shape, f"shape{shape}"),
                    )] = v
        d["reps"] = reps
        return d

    def trace_set(self, on: bool) -> bool:
        """Arm/disarm a built recorder at runtime (the overhead A/B's
        toggle).  False when MISAKA_NATIVE_TRACE=0 skipped the ring
        allocation at pool creation — there is nothing to arm."""
        with self._ctr_lock:
            if not self._h:
                return False
            return self._lib.misaka_pool_trace_set(
                self._h, 1 if on else 0
            ) >= 0

    # --- resident-state serving (r17) ----------------------------------

    def _state_ptrs(self, d: dict):
        """The 15 state-array pointers for the import/export ABI (import
        passes _checked arrays — the C++ side copies, no donation; export
        passes freshly-allocated buffers)."""
        return [
            _as_i32p(d["acc"]), _as_i32p(d["bak"]), _as_i32p(d["pc"]),
            _as_i32p(d["port_val"]), d["port_full"].ctypes.data_as(_U8P),
            _as_i32p(d["hold_val"]), d["holding"].ctypes.data_as(_U8P),
            _as_i32p(d["stack_mem"]), _as_i32p(d["stack_top"]),
            _as_i32p(d["in_buf"]), _as_i32p(d["out_buf"]),
            _as_i32p(d["_counters5"]), _as_i32p(d["retired"]),
            _as_i32p(d["acc_hi"]), _as_i32p(d["bak_hi"]),
        ]

    def import_state(self, d: dict) -> bool:
        """Arm C++ residency from a state dict (export_arrays keys, each
        with a leading [B] axis).  The arrays are validated exactly like a
        stateless serve (lossy casts raise ValueError) and COPIED into the
        resident store — no donation.  False when the C side rejects the
        state (pc/stack_top/ring violations): residency stays disarmed and
        the caller's arrays stay authoritative."""
        B, n, s = self.replicas, self.n_lanes, self.num_stacks
        c = {
            "acc": _checked_i32("acc", d["acc"], (B, n)),
            "bak": _checked_i32("bak", d["bak"], (B, n)),
            "acc_hi": _checked_i32("acc_hi", d["acc_hi"], (B, n)),
            "bak_hi": _checked_i32("bak_hi", d["bak_hi"], (B, n)),
            "pc": _checked_i32("pc", d["pc"], (B, n)),
            "port_val": _checked_i32(
                "port_val", d["port_val"], (B, n, isa.NUM_PORTS)
            ),
            "port_full": _checked_u8(
                "port_full", d["port_full"], (B, n, isa.NUM_PORTS)
            ),
            "hold_val": _checked_i32("hold_val", d["hold_val"], (B, n)),
            "holding": _checked_u8("holding", d["holding"], (B, n)),
            "stack_mem": _checked_i32(
                "stack_mem", d["stack_mem"], (B, s, self.stack_cap)
            ),
            "stack_top": _checked_i32("stack_top", d["stack_top"], (B, s)),
            "in_buf": _checked_i32("in_buf", d["in_buf"], (B, self.in_cap)),
            "out_buf": _checked_i32(
                "out_buf", d["out_buf"], (B, self.out_cap)
            ),
            "retired": _checked_i32("retired", d["retired"], (B, n)),
        }
        counters = np.empty((B, 5), np.int32)
        for i, k in enumerate(("in_rd", "in_wr", "out_rd", "out_wr", "tick")):
            counters[:, i] = _checked_i32(k, d[k], (B,))
        c["_counters5"] = counters
        rc = self._lib.misaka_pool_import(
            self._handle(), *self._state_ptrs(c)
        )
        return rc == 0

    def export_state(self) -> dict | None:
        """Non-destructive export of the resident state into fresh
        batch-major arrays (residency stays armed; None when it is not).
        The returned dict has the same key set serve() returns, so the
        caller can feed it straight back through the trusted stateless
        path or build a NetworkState from it."""
        B, n, s = self.replicas, self.n_lanes, self.num_stacks
        d = {
            "acc": np.empty((B, n), np.int32),
            "bak": np.empty((B, n), np.int32),
            "acc_hi": np.empty((B, n), np.int32),
            "bak_hi": np.empty((B, n), np.int32),
            "pc": np.empty((B, n), np.int32),
            "port_val": np.empty((B, n, isa.NUM_PORTS), np.int32),
            "port_full": np.empty((B, n, isa.NUM_PORTS), np.uint8),
            "hold_val": np.empty((B, n), np.int32),
            "holding": np.empty((B, n), np.uint8),
            "stack_mem": np.empty((B, s, self.stack_cap), np.int32),
            "stack_top": np.empty((B, s), np.int32),
            "in_buf": np.empty((B, self.in_cap), np.int32),
            "out_buf": np.empty((B, self.out_cap), np.int32),
            "retired": np.empty((B, n), np.int32),
            "_counters5": np.empty((B, 5), np.int32),
        }
        rc = self._lib.misaka_pool_export(
            self._handle(), *self._state_ptrs(d)
        )
        if rc != 0:
            return None
        counters = d["_counters5"]
        d["in_rd"] = counters[:, 0].copy()
        d["in_wr"] = counters[:, 1].copy()
        d["out_rd"] = counters[:, 2].copy()
        d["out_wr"] = counters[:, 3].copy()
        d["tick"] = counters[:, 4].copy()
        return d

    def discard_resident(self) -> None:
        """Disarm residency WITHOUT exporting — the caller replaced the
        state wholesale (load/restore) and the resident copy is
        superseded."""
        with self._ctr_lock:
            if self._h:
                self._lib.misaka_pool_discard(self._h)

    def is_resident(self) -> bool:
        with self._ctr_lock:
            if not self._h:
                return False
            return bool(self._lib.misaka_pool_is_resident(self._h))

    def serve_resident(self, values, counts, ticks: int, active=None,
                       reuse_out: bool = False):
        """One serve (counts given) or idle (counts None) pass on the
        RESIDENT state: no import, no export, no Python-side state dict at
        all.  Returns (packed, progress) — packed has EVERY row filled
        (skipped rows carry their current counters plus the
        drained-on-serve contract), progress[b]=1 when replica b retired
        an instruction this call (the device loop's hot-set signal).

        `reuse_out=True` enables pack-row elision (r21): the pool keeps
        one packed/progress buffer pair per pass kind and hands the SAME
        arrays back every call, telling the C++ side their contents are
        its own previous output — quiescent replicas' rows are then
        skipped entirely instead of re-filled, removing the B-proportional
        light-fill cost on sparse batches.  The caller must treat the
        returned arrays as read-only and must not hold a row across the
        next call (copy what survives the iteration)."""
        B = self.replicas
        feeding = counts is not None
        reuse = 0
        if feeding:
            values = _checked_i32("values", values, (B, self.in_cap))
            counts = _checked_i32("counts", counts, (B,))
            if reuse_out:
                packed = self._packed_serve
                if packed is None:
                    packed = np.empty((B, 4 + self.out_cap), np.int32)
                    self._packed_serve = packed
                else:
                    reuse = 1
            else:
                packed = np.empty((B, 4 + self.out_cap), np.int32)
            vp, cp = _as_i32p(values), _as_i32p(counts)
        else:
            if reuse_out:
                packed = self._packed_idle
                if packed is None:
                    packed = np.empty((B, 4), np.int32)
                    self._packed_idle = packed
                else:
                    reuse = 1
            else:
                packed = np.empty((B, 4), np.int32)
            vp = cp = None
        ap, n_active = None, 0
        if active is not None:
            active = np.ascontiguousarray(active, dtype=np.int32)
            if active.ndim != 1:
                raise ValueError("active must be a flat replica index list")
            if active.size and (
                int(active[0]) < 0 or int(active[-1]) >= B
                or (np.diff(active) <= 0).any()
            ):
                raise ValueError(
                    "active must be strictly increasing replica indices "
                    f"in [0, {B})"
                )
            if feeding:
                skip = np.ones((B,), bool)
                skip[active] = False
                if (counts[skip] > 0).any():
                    raise ValueError(
                        "active must cover every replica with counts > 0 "
                        "(a skipped feed would silently drop values)"
                    )
            ap, n_active = _as_i32p(active), int(active.size)
        if reuse_out:
            progress = self._progress_buf
            if progress is None:
                progress = np.empty((B,), np.uint8)
                self._progress_buf = progress
        else:
            progress = np.empty((B,), np.uint8)
        rc = self._lib.misaka_pool_serve_resident(
            self._handle(), vp, cp, int(ticks), ap, n_active,
            _as_i32p(packed), progress.ctypes.data_as(_U8P), reuse,
        )
        if rc == -2:
            raise RuntimeError("native pool feed exceeded ring free space")
        if rc == -3:  # pragma: no cover — Python validated above
            raise ValueError("invalid active replica list")
        if rc == -4:
            raise RuntimeError("pool residency is not armed")
        return packed, progress

    def serve(self, d: dict, values, counts, ticks: int, active=None,
              trusted: bool = False):
        """One batched serve iteration.  `d` holds batch-major state arrays
        (export_arrays keys, each with a leading [B] axis); returns
        (new_d, packed [B, 4+out_cap]) with new_d the post-chunk state —
        output rings drained (the packed rows carry the pre-drain
        snapshot, device-twin parity).

        `active` (optional, strictly increasing replica indices) is the
        partial-fill fast path: only those replicas are imported, fed,
        run, and exported; skipped replicas' state is untouched — except
        that a skipped replica with an undrained output ring is drained
        here (its outputs land in its packed row), keeping the
        drained-on-serve contract uniform — and their packed rows carry
        their current counters.  Skipped replicas' ticks do NOT advance
        (instances stop being tick-lockstep under partial fill)."""
        if values is None or counts is None:
            raise ValueError("serve requires values and counts (use idle)")
        return self._call(d, values, counts, int(ticks), active, trusted)

    def idle(self, d: dict, ticks: int, active=None,
             trusted: bool = False):
        """One batched idle iteration: advance `ticks` with no feed; returns
        (new_d, ctrs [B, 4]) with the output rings NOT drained.  `active`
        restricts the pass like serve's (skipped rows: state untouched,
        ctrs row = current counters)."""
        return self._call(d, None, None, int(ticks), active, trusted)

    def _call(self, d, values, counts, ticks, active=None, trusted=False):
        B, n, s = self.replicas, self.n_lanes, self.num_stacks

        if trusted:
            # Identity fast path: `d` is EXACTLY the dict this pool produced
            # last call (NativeServePool round-trips it and asserts identity
            # before setting `trusted`) — every array is already contiguous,
            # writeable int32/uint8 state the C++ side itself exported, and
            # _counters5 is the live [B, 5] buffer whose public columns went
            # out as copies.  Re-validating it every iteration was ~40% of
            # the device-loop's serve-path Python under multi-tenant load.
            acc, bak = d["acc"], d["bak"]
            acc_hi, bak_hi = d["acc_hi"], d["bak_hi"]
            pc = d["pc"]
            port_val, port_full = d["port_val"], d["port_full"]
            hold_val, holding = d["hold_val"], d["holding"]
            stack_mem, stack_top = d["stack_mem"], d["stack_top"]
            in_buf, out_buf = d["in_buf"], d["out_buf"]
            retired = d["retired"]
            counters = d["_counters5"]
        else:
            # The C++ workers write the post-chunk state back INTO these
            # arrays (input state is donated, like the jitted twins'
            # donate_argnums).  np.asarray of a jax array can be a read-only
            # view of the XLA buffer, which must never be mutated — take
            # ownership unless the array already owns writeable memory.
            def own(key, shape):
                a = _checked_i32(key, d[key], shape)
                if a.base is not None or not a.flags.writeable:
                    a = np.array(a)
                return a

            def u8arr(key, shape):
                return _checked_u8(key, d[key], shape)

            acc = own("acc", (B, n))
            bak = own("bak", (B, n))
            acc_hi = own("acc_hi", (B, n))
            bak_hi = own("bak_hi", (B, n))
            pc = own("pc", (B, n))
            port_val = own("port_val", (B, n, isa.NUM_PORTS))
            port_full = u8arr("port_full", (B, n, isa.NUM_PORTS))
            hold_val = own("hold_val", (B, n))
            holding = u8arr("holding", (B, n))
            stack_mem = own("stack_mem", (B, s, self.stack_cap))
            stack_top = own("stack_top", (B, s))
            in_buf = own("in_buf", (B, self.in_cap))
            out_buf = own("out_buf", (B, self.out_cap))
            retired = own("retired", (B, n))
            counters = np.empty((B, 5), np.int32)
            for i, k in enumerate(
                ("in_rd", "in_wr", "out_rd", "out_wr", "tick")
            ):
                counters[:, i] = _checked_i32(k, d[k], (B,))
        feeding = counts is not None
        if feeding:
            values = _checked_i32("values", values, (B, self.in_cap))
            counts = _checked_i32("counts", counts, (B,))
            packed = np.empty((B, 4 + self.out_cap), np.int32)
            vp, cp = _as_i32p(values), _as_i32p(counts)
        else:
            packed = np.empty((B, 4), np.int32)
            vp = cp = None
        ap, n_active = None, 0
        if active is not None:
            active = np.ascontiguousarray(active, dtype=np.int32)
            if active.ndim != 1:
                raise ValueError("active must be a flat replica index list")
            if active.size and (
                int(active[0]) < 0 or int(active[-1]) >= B
                or (np.diff(active) <= 0).any()
            ):
                raise ValueError(
                    "active must be strictly increasing replica indices "
                    f"in [0, {B})"
                )
            # skipped replicas never reach the C++ side: their packed rows
            # carry their current counters here
            packed[:, :4] = counters[:, :4]
            skip = np.ones((B,), bool)
            skip[active] = False
            if feeding:
                if (counts[skip] > 0).any():
                    raise ValueError(
                        "active must cover every replica with counts > 0 "
                        "(a skipped feed would silently drop values)"
                    )
                # an undrained output ring on a skipped row (possible after
                # an idle chunk) is snapshotted + drained exactly like a
                # served row — the drained-on-serve contract stays uniform
                undrained = skip & (counters[:, 3] > counters[:, 2])
                if undrained.any():
                    packed[undrained, 4:] = out_buf[undrained]
                    counters[undrained, 2] = counters[undrained, 3]
            ap, n_active = _as_i32p(active), int(active.size)
        rc = self._lib.misaka_pool_serve(
            self._handle(),
            _as_i32p(acc), _as_i32p(bak), _as_i32p(pc),
            _as_i32p(port_val), port_full.ctypes.data_as(_U8P),
            _as_i32p(hold_val), holding.ctypes.data_as(_U8P),
            _as_i32p(stack_mem), _as_i32p(stack_top),
            _as_i32p(in_buf), _as_i32p(out_buf), _as_i32p(counters),
            _as_i32p(retired), _as_i32p(acc_hi), _as_i32p(bak_hi),
            vp, cp, ticks, ap, n_active, _as_i32p(packed),
        )
        if rc == -2:
            raise RuntimeError("native pool feed exceeded ring free space")
        if rc == -3:  # pragma: no cover — Python validated above
            raise ValueError("invalid active replica list")
        if rc != 0:
            raise ValueError(
                "invalid state import (pc/stack_top/ring counters out of range)"
            )
        out = {
            "acc": acc, "bak": bak, "acc_hi": acc_hi, "bak_hi": bak_hi,
            "pc": pc, "port_val": port_val, "port_full": port_full,
            "hold_val": hold_val, "holding": holding,
            "stack_mem": stack_mem, "stack_top": stack_top,
            "in_buf": in_buf, "out_buf": out_buf, "retired": retired,
            "in_rd": counters[:, 0].copy(), "in_wr": counters[:, 1].copy(),
            "out_rd": counters[:, 2].copy(), "out_wr": counters[:, 3].copy(),
            "tick": counters[:, 4].copy(),
            # the live counters buffer, for the trusted round-trip fast
            # path (consumers key NetworkState fields explicitly, so the
            # private entry never leaks into state construction)
            "_counters5": counters,
        }
        return out, packed
