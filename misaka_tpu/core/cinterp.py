"""ctypes bridge to the native C++ superstep interpreter (native/interpreter.cpp).

A zero-JAX host executor with the exact tick discipline of the kernels
(core/step.py docstring): useful as a third independent implementation for
differential testing, and as a microsecond-latency single-instance engine for
control-plane-sized runs where a device round-trip isn't worth it.

Build with `make native` (repo root) or let this module build it on first
use (g++, ~1s).  `available()` reports whether the backend can load.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from misaka_tpu.tis import isa
from misaka_tpu.utils.nativelib import NativeLib

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_I32P = ctypes.POINTER(ctypes.c_int32)
_U8P = ctypes.POINTER(ctypes.c_uint8)


def _configure(lib: ctypes.CDLL) -> None:
    lib.misaka_interp_create.restype = ctypes.c_void_p
    lib.misaka_interp_create.argtypes = [_I32P, _I32P] + [ctypes.c_int] * 6
    lib.misaka_interp_destroy.restype = None
    lib.misaka_interp_destroy.argtypes = [ctypes.c_void_p]
    lib.misaka_interp_feed.restype = ctypes.c_int
    lib.misaka_interp_feed.argtypes = [ctypes.c_void_p, _I32P, ctypes.c_int]
    lib.misaka_interp_run.restype = None
    lib.misaka_interp_run.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.misaka_interp_drain.restype = ctypes.c_int
    lib.misaka_interp_drain.argtypes = [ctypes.c_void_p, _I32P, ctypes.c_int]
    lib.misaka_interp_seed_counters.restype = ctypes.c_int
    lib.misaka_interp_seed_counters.argtypes = [ctypes.c_void_p] + [ctypes.c_int32] * 4
    lib.misaka_interp_read.restype = None
    lib.misaka_interp_read.argtypes = [ctypes.c_void_p] + [
        _I32P, _I32P, _I32P, _I32P, _U8P, _I32P, _U8P,
        _I32P, _I32P, _I32P, _I32P, _I32P, _I32P, _I32P,
    ]


_NATIVE = NativeLib(
    os.path.join(_REPO_ROOT, "native", "interpreter.cpp"),
    os.path.join(_REPO_ROOT, "native", "libmisaka_interp.so"),
    _configure,
)


def _load() -> ctypes.CDLL | None:
    return _NATIVE.load()


def available() -> bool:
    return _NATIVE.available()


def _as_i32p(arr: np.ndarray):
    return arr.ctypes.data_as(_I32P)


class NativeInterpreter:
    """One network instance stepped by the C++ engine (Oracle-compatible API)."""

    def __init__(self, code, prog_len, num_stacks, stack_cap, in_cap, out_cap):
        lib = _load()
        if lib is None:
            raise RuntimeError("native interpreter unavailable (no g++?)")
        self._lib = lib
        code = np.ascontiguousarray(code, dtype=np.int32)
        prog_len = np.ascontiguousarray(prog_len, dtype=np.int32)
        if code.ndim != 3 or code.shape[2] != isa.NFIELDS:
            raise ValueError(
                f"code must be [n_lanes, max_len, {isa.NFIELDS}], got {code.shape}"
            )
        if prog_len.shape != (code.shape[0],):
            raise ValueError(
                f"prog_len must have shape ({code.shape[0]},), got {prog_len.shape}"
            )
        self.n_lanes, self.max_len, _ = code.shape
        self.num_stacks = max(1, num_stacks)
        self.stack_cap = stack_cap
        self.in_cap = in_cap
        self.out_cap = out_cap
        self._h = lib.misaka_interp_create(
            _as_i32p(code),
            _as_i32p(prog_len),
            self.n_lanes,
            self.max_len,
            self.num_stacks,
            stack_cap,
            in_cap,
            out_cap,
        )
        if not self._h:
            raise ValueError("invalid network tables")

    def close(self) -> None:
        if self._h:
            self._lib.misaka_interp_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _handle(self):
        if not self._h:
            raise RuntimeError("interpreter is closed")
        return self._h

    def feed(self, values) -> int:
        vals = np.ascontiguousarray(values, dtype=np.int32)
        return self._lib.misaka_interp_feed(self._handle(), _as_i32p(vals), len(vals))

    def run(self, ticks: int) -> None:
        self._lib.misaka_interp_run(self._handle(), int(ticks))

    def drain(self) -> list[int]:
        out = np.zeros((self.out_cap,), np.int32)
        got = self._lib.misaka_interp_drain(self._handle(), _as_i32p(out), self.out_cap)
        return out[:got].tolist()

    def seed_counters(self, in_rd: int, in_wr: int, out_rd: int, out_wr: int) -> None:
        """Set the ring counters directly (checkpoint restore / soak tests).

        Raises ValueError when the counters violate the ring invariants
        (0 <= rd <= wr, wr - rd <= cap) — the C side rejects them with the
        interpreter state unchanged."""
        rc = self._lib.misaka_interp_seed_counters(
            self._handle(), int(in_rd), int(in_wr), int(out_rd), int(out_wr)
        )
        if rc != 0:
            raise ValueError(
                f"invalid ring counters: in {in_rd}/{in_wr} (cap {self.in_cap}), "
                f"out {out_rd}/{out_wr} (cap {self.out_cap})"
            )

    def state_arrays(self) -> dict:
        """Mirror tests/oracle.py state_arrays for differential comparison."""
        self._handle()
        n, s, cap = self.n_lanes, self.num_stacks, self.stack_cap
        acc = np.zeros(n, np.int32)
        bak = np.zeros(n, np.int32)
        pc = np.zeros(n, np.int32)
        port_val = np.zeros((n, isa.NUM_PORTS), np.int32)
        port_full = np.zeros((n, isa.NUM_PORTS), np.uint8)
        hold_val = np.zeros(n, np.int32)
        holding = np.zeros(n, np.uint8)
        stack_mem = np.zeros((s, cap), np.int32)
        stack_top = np.zeros(s, np.int32)
        out_buf = np.zeros(self.out_cap, np.int32)
        counters = np.zeros(5, np.int32)
        retired = np.zeros(n, np.int32)
        acc_hi = np.zeros(n, np.int32)
        bak_hi = np.zeros(n, np.int32)
        self._lib.misaka_interp_read(
            self._h,
            _as_i32p(acc), _as_i32p(bak), _as_i32p(pc),
            _as_i32p(port_val), port_full.ctypes.data_as(_U8P),
            _as_i32p(hold_val), holding.ctypes.data_as(_U8P),
            _as_i32p(stack_mem), _as_i32p(stack_top),
            _as_i32p(out_buf), _as_i32p(counters), _as_i32p(retired),
            _as_i32p(acc_hi), _as_i32p(bak_hi),
        )
        return {
            "acc": acc,
            "bak": bak,
            "acc_hi": acc_hi,
            "bak_hi": bak_hi,
            "pc": pc,
            "port_val": port_val,
            "port_full": port_full.astype(bool),
            "hold_val": hold_val,
            "holding": holding.astype(bool),
            "stack_top": stack_top,
            "stack_mem_used": stack_mem,
            "in_rd": counters[0],
            "out_wr": counters[3],
            "out_buf": out_buf,
            "tick": counters[4],
            "retired": retired,
        }
