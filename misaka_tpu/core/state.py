"""Network state: the whole distributed system as one pytree of arrays.

The reference scatters this state across OS processes: per-node registers
(program.go:27-35), per-node cap-1 port channels (program.go:29-32,:60-63),
per-stack-node mutex-guarded slices (intStack.go:9-45), and the master's cap-1
I/O channels (master.go:31-32,:58-59).  Here it is one NamedTuple of int32
arrays; a whole-network snapshot is therefore a checkpoint for free
(SURVEY.md §5), and reset (program.go:207-216) is just `init_state`.

Shapes below are for ONE network instance; the engine vmaps a leading batch
axis over independent instances for throughput.

Ring-buffer convention: `rd`/`wr` are monotonically increasing int32 counters;
the slot index is `counter % capacity`; occupancy is `wr - rd`.  The device
consumes inputs (IN) and produces outputs (OUT); the host refills `in_buf` /
advances `out_rd` between jitted chunks.

Counter lifetime: a long-soak master moves >2^31 values within hours, and a
wrapped-negative int32 counter breaks `% capacity` indexing.  Every chunk
runner therefore calls `rebase_rings` after its scan: once a ring's `rd`
passes 2^30, a multiple of the ring's capacity is subtracted from both its
counters — slot indices and occupancy are unchanged, and the headroom
(2^31 - 2^30 ≈ 1e9 values) can never be consumed within one chunk.  The
`tick`/`retired` metrics counters, by contrast, are allowed to wrap: nothing
indexes off them.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from misaka_tpu.tis import isa


class NetworkState(NamedTuple):
    """All mutable state of one Misaka network instance."""

    # program-node lanes.  acc/bak are the reference's 64-bit Go ints
    # (program.go:27-28) carried as int32 (hi, lo) planes — `acc`/`bak`
    # hold the LOW word (which is also the wire value; the wire truncates
    # to sint32, messenger.proto:34-41), `acc_hi`/`bak_hi` bits 32-63.
    # See core/regs64.py.
    acc: jnp.ndarray        # [N] int32 — low word of the 64-bit acc
    bak: jnp.ndarray        # [N] int32 — low word of the 64-bit bak
    acc_hi: jnp.ndarray     # [N] int32 — high word of acc
    bak_hi: jnp.ndarray     # [N] int32 — high word of bak
    pc: jnp.ndarray         # [N] int32   (program.go:34)
    port_val: jnp.ndarray   # [N, 4] int32 — inbound ports r0..r3 (program.go:29-32)
    port_full: jnp.ndarray  # [N, 4] bool — cap-1 occupancy (bufferSize=1, program.go:21)
    # The hold latch models the reference's two-phase blocking ops: getFromSrc
    # CONSUMES the port (program.go:441-468) and only then the delivery RPC
    # blocks (sendValue/outputValue, :475-506/:554-566).  A lane whose port
    # source is ready therefore consumes it into the latch immediately and
    # parks with `holding` set until its delivery commits.
    hold_val: jnp.ndarray   # [N] int32 — consumed-but-undelivered source value
    holding: jnp.ndarray    # [N] bool

    # stack nodes
    stack_mem: jnp.ndarray  # [S, CAP] int32 (intStack.go:9; bounded here, see engine)
    stack_top: jnp.ndarray  # [S] int32

    # master I/O rings (inChan/outChan, master.go:31-32)
    in_buf: jnp.ndarray     # [QI] int32
    in_rd: jnp.ndarray      # int32 scalar — device-advanced
    in_wr: jnp.ndarray      # int32 scalar — host-advanced
    out_buf: jnp.ndarray    # [QO] int32
    out_rd: jnp.ndarray     # int32 scalar — host-advanced
    out_wr: jnp.ndarray     # int32 scalar — device-advanced

    # metrics
    tick: jnp.ndarray       # int32 scalar — supersteps executed
    retired: jnp.ndarray    # [N] int32 — committed instructions per lane


REBASE_THRESHOLD = 1 << 30


def rebase_rings(state: NetworkState) -> NetworkState:
    """Rebase I/O ring counters below the int32 wrap (see module docstring).

    Elementwise, so it works for unbatched scalars and batched [B] counters
    alike; a no-op until a counter passes REBASE_THRESHOLD.
    """

    def rb(rd, wr, cap):
        base = jnp.where(
            rd > REBASE_THRESHOLD, (rd // cap) * cap, jnp.zeros_like(rd)
        )
        return rd - base, wr - base

    in_rd, in_wr = rb(state.in_rd, state.in_wr, state.in_buf.shape[-1])
    out_rd, out_wr = rb(state.out_rd, state.out_wr, state.out_buf.shape[-1])
    return state._replace(in_rd=in_rd, in_wr=in_wr, out_rd=out_rd, out_wr=out_wr)


def init_state(
    num_lanes: int,
    num_stacks: int,
    stack_cap: int,
    in_cap: int,
    out_cap: int,
) -> NetworkState:
    """Fresh all-zeros state (the reference's resetNode, program.go:207-216)."""
    i32 = np.int32
    return NetworkState(
        acc=jnp.zeros((num_lanes,), i32),
        bak=jnp.zeros((num_lanes,), i32),
        acc_hi=jnp.zeros((num_lanes,), i32),
        bak_hi=jnp.zeros((num_lanes,), i32),
        pc=jnp.zeros((num_lanes,), i32),
        port_val=jnp.zeros((num_lanes, isa.NUM_PORTS), i32),
        port_full=jnp.zeros((num_lanes, isa.NUM_PORTS), bool),
        hold_val=jnp.zeros((num_lanes,), i32),
        holding=jnp.zeros((num_lanes,), bool),
        stack_mem=jnp.zeros((num_stacks, stack_cap), i32),
        stack_top=jnp.zeros((num_stacks,), i32),
        in_buf=jnp.zeros((in_cap,), i32),
        in_rd=jnp.zeros((), i32),
        in_wr=jnp.zeros((), i32),
        out_buf=jnp.zeros((out_cap,), i32),
        out_rd=jnp.zeros((), i32),
        out_wr=jnp.zeros((), i32),
        tick=jnp.zeros((), i32),
        retired=jnp.zeros((num_lanes,), i32),
    )
