"""Network state: the whole distributed system as one pytree of arrays.

The reference scatters this state across OS processes: per-node registers
(program.go:27-35), per-node cap-1 port channels (program.go:29-32,:60-63),
per-stack-node mutex-guarded slices (intStack.go:9-45), and the master's cap-1
I/O channels (master.go:31-32,:58-59).  Here it is one NamedTuple of int32
arrays; a whole-network snapshot is therefore a checkpoint for free
(SURVEY.md §5), and reset (program.go:207-216) is just `init_state`.

Shapes below are for ONE network instance; the engine vmaps a leading batch
axis over independent instances for throughput.

Ring-buffer convention: `rd`/`wr` are monotonically increasing int32 counters;
the slot index is `counter % capacity`; occupancy is `wr - rd`.  The device
consumes inputs (IN) and produces outputs (OUT); the host refills `in_buf` /
advances `out_rd` between jitted chunks.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from misaka_tpu.tis import isa


class NetworkState(NamedTuple):
    """All mutable state of one Misaka network instance."""

    # program-node lanes
    acc: jnp.ndarray        # [N] int32   (program.go:27)
    bak: jnp.ndarray        # [N] int32   (program.go:28)
    pc: jnp.ndarray         # [N] int32   (program.go:34)
    port_val: jnp.ndarray   # [N, 4] int32 — inbound ports r0..r3 (program.go:29-32)
    port_full: jnp.ndarray  # [N, 4] bool — cap-1 occupancy (bufferSize=1, program.go:21)
    # The hold latch models the reference's two-phase blocking ops: getFromSrc
    # CONSUMES the port (program.go:441-468) and only then the delivery RPC
    # blocks (sendValue/outputValue, :475-506/:554-566).  A lane whose port
    # source is ready therefore consumes it into the latch immediately and
    # parks with `holding` set until its delivery commits.
    hold_val: jnp.ndarray   # [N] int32 — consumed-but-undelivered source value
    holding: jnp.ndarray    # [N] bool

    # stack nodes
    stack_mem: jnp.ndarray  # [S, CAP] int32 (intStack.go:9; bounded here, see engine)
    stack_top: jnp.ndarray  # [S] int32

    # master I/O rings (inChan/outChan, master.go:31-32)
    in_buf: jnp.ndarray     # [QI] int32
    in_rd: jnp.ndarray      # int32 scalar — device-advanced
    in_wr: jnp.ndarray      # int32 scalar — host-advanced
    out_buf: jnp.ndarray    # [QO] int32
    out_rd: jnp.ndarray     # int32 scalar — host-advanced
    out_wr: jnp.ndarray     # int32 scalar — device-advanced

    # metrics
    tick: jnp.ndarray       # int32 scalar — supersteps executed
    retired: jnp.ndarray    # [N] int32 — committed instructions per lane


def init_state(
    num_lanes: int,
    num_stacks: int,
    stack_cap: int,
    in_cap: int,
    out_cap: int,
) -> NetworkState:
    """Fresh all-zeros state (the reference's resetNode, program.go:207-216)."""
    i32 = np.int32
    return NetworkState(
        acc=jnp.zeros((num_lanes,), i32),
        bak=jnp.zeros((num_lanes,), i32),
        pc=jnp.zeros((num_lanes,), i32),
        port_val=jnp.zeros((num_lanes, isa.NUM_PORTS), i32),
        port_full=jnp.zeros((num_lanes, isa.NUM_PORTS), bool),
        hold_val=jnp.zeros((num_lanes,), i32),
        holding=jnp.zeros((num_lanes,), bool),
        stack_mem=jnp.zeros((num_stacks, stack_cap), i32),
        stack_top=jnp.zeros((num_stacks,), i32),
        in_buf=jnp.zeros((in_cap,), i32),
        in_rd=jnp.zeros((), i32),
        in_wr=jnp.zeros((), i32),
        out_buf=jnp.zeros((out_cap,), i32),
        out_rd=jnp.zeros((), i32),
        out_wr=jnp.zeros((), i32),
        tick=jnp.zeros((), i32),
        retired=jnp.zeros((num_lanes,), i32),
    )
