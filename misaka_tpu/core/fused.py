"""Fused-network Pallas kernel: the whole network, VMEM-resident, one launch.

The XLA-scan engine (core/engine.py) pays ~30 kernel launches and HBM
round-trips of the full state per superstep.  This module instead *compiles
each network into its own TPU kernel*: the lowered program tables are static
Python data at build time, so every program line emits only the handful of
masked vector ops its semantics need — a specialized dataflow machine, not an
interpreter.  All state stays resident in VMEM for the entire chunk of
`num_steps` ticks (one `pallas_call`), with a grid over batch blocks.

Layout: batch-last.  Every per-instance quantity is a row of shape
[B/128, 128] (VPU-tile aligned); lanes/ports/stack slots/ring slots are
leading row indices.  The wrapper transposes the public batched NetworkState
([B, ...]-major) in and out around the kernel — O(state) once per chunk,
amortized over hundreds of ticks.

Semantics are bit-identical to core/step.py (same pass order: consume ->
send-arbitrate -> stack/IN/OUT elect -> commit; same lowest-lane priority,
realized as static priority chains).  tests/test_fused.py proves it.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from misaka_tpu.core import regs64
from misaka_tpu.core.state import NetworkState, rebase_rings
from misaka_tpu.tis import isa

LANE = 128  # VPU lane width; batch blocks are multiples of this

# Capacity threshold between the two storage modes for stacks/rings:
#   cap <= UNROLL_CAP — slots live in the fori_loop carry (registers) and
#     every access is an unrolled select chain: fastest, but O(cap) unrolled
#     ops and carry rows (the round-1 capacity cliff).
#   cap >  UNROLL_CAP — slots stay in the VMEM ref; accesses are chunked
#     dynamic-slice scans (pl.ds, 8 rows at a time) inside lax.fori_loop:
#     program size O(1), per-tick cost O(cap/8) vector ops, no carry rows.
# Engine-default 1024-deep stacks/rings (intStack.go:9 is unbounded) now
# compile and run; the five bench configs (small caps) keep the fast path.
UNROLL_CAP = 64
_CHUNK = 8  # rows per dynamic slice (sublane multiple)

_I32 = jnp.int32


@dataclass(frozen=True)
class _Instr:
    op: int
    src: int
    imm: int
    dst: int
    tgt: int
    port: int
    jmp: int

    @property
    def reads_port(self) -> bool:
        return self.op in isa.READS_SRC and self.src >= isa.SRC_R0

    @property
    def port_idx(self) -> int:
        return self.src - isa.SRC_R0


def _decode(code_np: np.ndarray, prog_len_np: np.ndarray) -> list[list[_Instr]]:
    return [
        [_Instr(*map(int, code_np[n, l])) for l in range(int(prog_len_np[n]))]
        for n in range(code_np.shape[0])
    ]


def make_fused_runner(
    code_np: np.ndarray,
    prog_len_np: np.ndarray,
    *,
    num_stacks: int,
    stack_cap: int,
    in_cap: int,
    out_cap: int,
    batch: int,
    num_steps: int,
    block_batch: int | None = None,
    interpret: bool = False,
    unroll_cap: int | None = None,
    elide_dead_hi: bool | None = None,
):
    """Build `fn(state) -> state` advancing `num_steps` ticks in one kernel.

    Operates on the standard batched NetworkState.  `block_batch` (multiple of
    128, divides batch) bounds VMEM residency per grid block.

    elide_dead_hi: opt-in (default off; env MISAKA_FUSED_ELIDE_HI=1): skip
    all hi-plane arithmetic on lanes that never read the 64-bit high word
    (see hi_live below).  Wire/output behavior is bit-identical; the
    CONTRACT CHANGE is that the returned state's acc_hi/bak_hi planes are
    unspecified on those lanes (they stay at their entry values instead of
    tracking overflow no reader would ever see).
    """
    n_lanes = code_np.shape[0]
    n_dests = n_lanes * isa.NUM_PORTS
    n_stacks = max(1, num_stacks)
    progs = _decode(code_np, prog_len_np)

    # Static hi-plane liveness, per lane (the r5 cut at the perf model's
    # named masked-lane waste, ARCHITECTURE.md "Headroom, named").  The
    # 64-bit high word of ACC/BAK is READ only by conditional jumps
    # (JEZ/JNZ/JGZ/JLZ see the full 64-bit value) and by JRO-from-ACC; the
    # wire (ports, stacks, OUT) truncates to int32 = the lo plane, and
    # add64/sub64/neg64 compute lo from lo alone.  A lane with none of
    # those readers can skip ALL hi-plane arithmetic bit-identically —
    # add2/acc_loop/ring lanes are straight-line or JMP-only, so the
    # headline kernel drops its hi-plane ops entirely.  JRO from imm/port
    # reads src_hi derived from the STATIC immediate or the int32 port
    # latch (not the acc plane), so those lines keep their val_hi fold.
    _COND_JUMPS = (isa.OP_JEZ, isa.OP_JNZ, isa.OP_JGZ, isa.OP_JLZ)
    if elide_dead_hi is None:
        elide_dead_hi = os.environ.get("MISAKA_FUSED_ELIDE_HI") == "1"
    hi_live = [
        not elide_dead_hi
        or any(
            ins.op in _COND_JUMPS
            or (ins.op == isa.OP_JRO and ins.src == isa.SRC_ACC)
            for ins in prog
        )
        for prog in progs
    ]

    if block_batch is None:
        block_batch = min(batch, 1024)
    if batch % block_batch or block_batch % LANE:
        raise ValueError(
            f"batch {batch} must be a multiple of block_batch {block_batch}, "
            f"itself a multiple of {LANE}"
        )
    # Mosaic tiling: state arrays are (rows, batch//LANE, LANE) and the
    # grid blocks the middle axis at block_batch//LANE sublane-rows.  The
    # TPU lowering requires the -2 block dim to be a multiple of 8 (the
    # int32 sublane tile) unless the block spans the whole axis — raised
    # EAGERLY here (the lowering only raises at compile) so
    # fused_runner_walk can skip past an untileable candidate the same way
    # it skips past a budget-rejected one.
    if (
        not interpret
        and jax.default_backend() == "tpu"
        and block_batch != batch
        and block_batch % (8 * LANE)
    ):
        raise ValueError(
            f"block_batch={block_batch} is not Mosaic-tileable: partial "
            f"batch blocks must be multiples of {8 * LANE} (8 sublanes x "
            f"{LANE} lanes, int32 tile) unless block_batch == batch"
        )
    # Storage-mode split (see UNROLL_CAP above): small caps live in the
    # fori_loop carry and pay unrolled select chains; big caps stay in VMEM
    # refs and pay chunked dynamic-slice scans.
    ucap = UNROLL_CAP if unroll_cap is None else unroll_cap
    sm_in_regs = stack_cap <= ucap
    inb_in_regs = in_cap <= ucap
    ob_in_regs = out_cap <= ucap
    for name, cap, in_regs in (
        ("stack_cap", stack_cap, sm_in_regs),
        ("in_cap", in_cap, inb_in_regs),
        ("out_cap", out_cap, ob_in_regs),
    ):
        if not in_regs and cap % _CHUNK:
            raise ValueError(
                f"{name}={cap} above the unroll threshold must be a "
                f"multiple of {_CHUNK} (chunked dynamic-slice access)"
            )
    # Budget arithmetic (acc/bak carry TWO rows each — 64-bit hi/lo planes,
    # core/regs64.py).  Carry-resident rows are the scarce resource:
    # Mosaic's scoped-vmem stack peaks at ~4x the carry rows (input+output
    # aliasing plus transients) against the 16MB hardware scoped limit —
    # measured on a v5e, block_batch=4096 on the add-2 net (5MB carry)
    # compiles to a 22MB scoped allocation and is rejected.  Ref-resident
    # rows (the chunked big-cap mode) are plain VMEM arrays without that
    # multiplier; bound the total at a conservative 8MB.
    carry_rows = 8 * n_lanes + 2 * n_dests + n_stacks + 5
    if sm_in_regs:
        carry_rows += n_stacks * stack_cap
    if inb_in_regs:
        carry_rows += in_cap
    if ob_in_regs:
        carry_rows += out_cap
    total_rows = (
        8 * n_lanes + 2 * n_dests + n_stacks * stack_cap + n_stacks
        + in_cap + out_cap + 5
    )
    carry_bytes = carry_rows * block_batch * 4
    total_bytes = total_rows * block_batch * 4
    if carry_rows > 2048 or carry_bytes > 4 * 1024 * 1024 \
            or total_bytes > 8 * 1024 * 1024:
        raise ValueError(
            f"fused kernel budget exceeded: {carry_rows} carry rows "
            f"({carry_bytes / 1e6:.1f} MB) / {total_rows} total rows "
            f"({total_bytes / 1e6:.1f} MB) at block_batch={block_batch} — "
            "reduce stack_cap/in_cap/out_cap or shrink block_batch"
        )
    bsr = block_batch // LANE  # sublane-rows per block
    n_blocks = batch // block_batch

    # Static routing tables: which (lane, line) contend for each resource.
    sends_by_dest: dict[int, list[tuple[int, int]]] = {}
    stack_ops: dict[int, list[tuple[int, int, bool]]] = {}  # (lane, line, is_push)
    in_entries: list[tuple[int, int]] = []
    out_entries: list[tuple[int, int]] = []
    for n, prog in enumerate(progs):
        for l, ins in enumerate(prog):
            if ins.op == isa.OP_MOV_NET:
                d = ins.tgt * isa.NUM_PORTS + ins.port
                sends_by_dest.setdefault(d, []).append((n, l))
            elif ins.op == isa.OP_PUSH:
                stack_ops.setdefault(ins.tgt, []).append((n, l, True))
            elif ins.op == isa.OP_POP:
                stack_ops.setdefault(ins.tgt, []).append((n, l, False))
            elif ins.op == isa.OP_IN:
                in_entries.append((n, l))
            elif ins.op == isa.OP_OUT:
                out_entries.append((n, l))
    # Priority = lowest lane index (core/step.py discipline); line order within
    # a lane is irrelevant (at most one line active per lane per tick).
    for entries in sends_by_dest.values():
        entries.sort()
    for entries in stack_ops.values():
        entries.sort()
    in_entries.sort()
    out_entries.sort()

    # --- chunked dynamic-slice access for ref-resident big caps ------------
    # The target slot differs per batch element ([bsr, LANE] indices), so a
    # scalar dynamic index cannot address it; instead scan the slot axis in
    # _CHUNK-row slices and mask — O(cap/_CHUNK) vector ops, O(1) program.

    def _slot_ids(i):
        return i * _CHUNK + jax.lax.broadcasted_iota(_I32, (_CHUNK, 1, 1), 0)

    def ref_gather(ref, base, cap, idx):
        """ref[base + idx[b], b] per batch element (0 where idx misses)."""

        def body(i, acc_v):
            blk = ref[pl.ds(base + i * _CHUNK, _CHUNK)]
            m = _slot_ids(i) == idx[None, :, :]
            return acc_v + jnp.where(m, blk, 0).sum(axis=0)

        return jax.lax.fori_loop(0, cap // _CHUNK, body, jnp.zeros_like(idx))

    def ref_scatter(ref, base, cap, idx, mask, val):
        """ref[base + idx[b], b] = val[b] where mask[b] (read-modify-write)."""

        def body(i, _):
            blk = ref[pl.ds(base + i * _CHUNK, _CHUNK)]
            m = (_slot_ids(i) == idx[None, :, :]) & mask[None, :, :]
            ref[pl.ds(base + i * _CHUNK, _CHUNK)] = jnp.where(m, val[None], blk)
            return 0

        jax.lax.fori_loop(0, cap // _CHUNK, body, 0)

    def ref_copy(src, dst, rows_count):
        def body(i, _):
            dst[pl.ds(i * _CHUNK, _CHUNK)] = src[pl.ds(i * _CHUNK, _CHUNK)]
            return 0

        jax.lax.fori_loop(0, rows_count // _CHUNK, body, 0)

    def tick_body(carry, inb, sm_ref, ob_ref):
        """One superstep.  inb: list of rows (regs mode) or a ref; sm_ref /
        ob_ref: the writable stack/out-ring refs (None in regs mode, where
        the corresponding carry entries hold the rows).  acc/bak are 64-bit
        (hi, lo) row pairs (core/regs64.py); ports/stacks/rings stay int32
        (the wire truncates, messenger.proto:34-41)."""
        (acc, bak, acc_hi, bak_hi, pc, pv, pf, hv, ho, sm, st, ob, sc, ret) = carry
        in_rd, in_wr, out_rd, out_wr, tick = sc
        i32 = lambda b: b.astype(_I32)

        act = [
            [pc[n] == l for l in range(len(progs[n]))] for n in range(n_lanes)
        ]
        ho_b = [ho[n] != 0 for n in range(n_lanes)]
        pf_b = [pf[d] != 0 for d in range(n_dests)]

        # --- pass 1: consume ready port sources into hold latches ----------
        new_hv = list(hv)
        new_ho = list(ho_b)
        new_pf = list(pf_b)
        for n, prog in enumerate(progs):
            for l, ins in enumerate(prog):
                if ins.reads_port:
                    row = n * isa.NUM_PORTS + ins.port_idx
                    consume = act[n][l] & ~new_ho[n] & new_pf[row]
                    new_hv[n] = jnp.where(consume, pv[row], new_hv[n])
                    new_ho[n] = new_ho[n] | consume
                    new_pf[row] = new_pf[row] & ~consume

        # --- pass 2: source resolution -------------------------------------
        # src_val is the low/wire word; src_hi the 64-bit high word (only
        # ACC sources carry a live one — imm is static, ports are int32)
        true_mask = pc[0] == pc[0]  # all-True [bsr, LANE]
        src_ok: list = []
        src_val: list = []
        src_hi: list = []
        for n, prog in enumerate(progs):
            ok = true_mask
            val = jnp.zeros_like(acc[n])
            val_hi = jnp.zeros_like(acc[n])
            for l, ins in enumerate(prog):
                if ins.op not in isa.READS_SRC:
                    continue
                a = act[n][l]
                if ins.src == isa.SRC_IMM:
                    v = jnp.int32(ins.imm)
                    vh = jnp.int32(-1 if ins.imm < 0 else 0)  # static sext
                elif ins.src == isa.SRC_ACC:
                    v = acc[n]
                    vh = acc_hi[n]
                elif ins.src == isa.SRC_NIL:
                    v = jnp.int32(0)
                    vh = jnp.int32(0)
                else:
                    v = new_hv[n]
                    vh = new_hv[n] >> 31  # port values are int32: sext
                    ok = ok & (~a | new_ho[n])
                val = jnp.where(a, v, val)
                # hi-dead lanes skip the val_hi fold except for JRO lines,
                # whose src_hi is live even there (see hi_live above)
                if hi_live[n] or ins.op == isa.OP_JRO:
                    val_hi = jnp.where(a, vh, val_hi)
            src_ok.append(ok)
            src_val.append(val)
            src_hi.append(val_hi)

        # --- pass 3a: network sends (static priority chain per dest) -------
        send_ok: dict[tuple[int, int], jnp.ndarray] = {}
        new_pv = list(pv)
        for d, entries in sends_by_dest.items():
            avail = ~new_pf[d]
            delivered = jnp.zeros_like(avail)
            val_d = new_pv[d]
            for (n, l) in entries:
                want = act[n][l] & src_ok[n]
                win = want & avail
                avail = avail & ~win
                delivered = delivered | win
                send_ok[(n, l)] = win
                val_d = jnp.where(win, src_val[n], val_d)
            new_pf[d] = new_pf[d] | delivered
            new_pv[d] = val_d

        # --- pass 3b: stacks (one op per stack per tick) --------------------
        stack_ok: dict[tuple[int, int], jnp.ndarray] = {}
        pop_val: dict[int, jnp.ndarray] = {}
        new_sm = list(sm)
        new_st = list(st)
        for s, entries in stack_ops.items():
            can_push = st[s] < stack_cap
            can_pop = st[s] > 0
            if sm_in_regs:
                pv_s = jnp.zeros_like(st[s])
                for c in range(stack_cap):
                    pv_s = jnp.where(st[s] - 1 == c, sm[s * stack_cap + c], pv_s)
            else:
                pv_s = ref_gather(sm_ref, s * stack_cap, stack_cap, st[s] - 1)
            pop_val[s] = pv_s
            granted = jnp.zeros_like(can_push)
            push_m = jnp.zeros_like(can_push)
            pop_m = jnp.zeros_like(can_push)
            push_v = jnp.zeros_like(st[s])
            for (n, l, is_push) in entries:
                if is_push:
                    okm = act[n][l] & src_ok[n] & can_push & ~granted
                    push_m = push_m | okm
                    push_v = jnp.where(okm, src_val[n], push_v)
                else:
                    okm = act[n][l] & can_pop & ~granted
                    pop_m = pop_m | okm
                granted = granted | okm
                stack_ok[(n, l)] = okm
            if sm_in_regs:
                for c in range(stack_cap):
                    slot = s * stack_cap + c
                    new_sm[slot] = jnp.where(
                        push_m & (st[s] == c), push_v, new_sm[slot]
                    )
            else:
                ref_scatter(sm_ref, s * stack_cap, stack_cap, st[s], push_m, push_v)
            new_st[s] = st[s] + i32(push_m) - i32(pop_m)

        # --- pass 3c: master input (single grant per tick) ------------------
        in_ok: dict[tuple[int, int], jnp.ndarray] = {}
        in_any = jnp.zeros_like(true_mask)
        if in_entries:
            in_avail = (in_wr - in_rd) > 0
            for (n, l) in in_entries:
                okm = act[n][l] & in_avail & ~in_any
                in_any = in_any | okm
                in_ok[(n, l)] = okm
        rd_mod = jax.lax.rem(in_rd, jnp.int32(in_cap))
        in_val = jnp.zeros_like(in_rd)
        if in_entries:
            if inb_in_regs:
                for q in range(in_cap):
                    in_val = jnp.where(rd_mod == q, inb[q], in_val)
            else:
                in_val = ref_gather(inb, 0, in_cap, rd_mod)
        new_in_rd = in_rd + i32(in_any)

        # --- pass 3d: master output (single grant per tick) -----------------
        out_ok: dict[tuple[int, int], jnp.ndarray] = {}
        out_any = jnp.zeros_like(true_mask)
        out_val = jnp.zeros_like(out_rd)
        new_ob = list(ob)
        if out_entries:
            out_free = (out_wr - out_rd) < out_cap
            for (n, l) in out_entries:
                okm = act[n][l] & src_ok[n] & out_free & ~out_any
                out_any = out_any | okm
                out_val = jnp.where(okm, src_val[n], out_val)
                out_ok[(n, l)] = okm
            wr_mod = jax.lax.rem(out_wr, jnp.int32(out_cap))
            if ob_in_regs:
                for q in range(out_cap):
                    new_ob[q] = jnp.where(out_any & (wr_mod == q), out_val, ob[q])
            else:
                ref_scatter(ob_ref, 0, out_cap, wr_mod, out_any, out_val)
        new_out_wr = out_wr + i32(out_any)

        # --- pass 4: commit + register/pc effects ---------------------------
        new_acc = list(acc)
        new_bak = list(bak)
        new_acc_hi = list(acc_hi)
        new_bak_hi = list(bak_hi)
        new_pc = list(pc)
        new_ret = list(ret)
        for n, prog in enumerate(progs):
            ln = len(prog)
            commit_n = jnp.zeros_like(true_mask)
            for l, ins in enumerate(prog):
                op = ins.op
                if op == isa.OP_MOV_NET:
                    c = send_ok[(n, l)]
                elif op in (isa.OP_PUSH, isa.OP_POP):
                    c = stack_ok[(n, l)]
                elif op == isa.OP_IN:
                    c = in_ok[(n, l)]
                elif op == isa.OP_OUT:
                    c = out_ok[(n, l)]
                else:
                    c = act[n][l] & src_ok[n]
                commit_n = commit_n | c

                # register effects (reading begin-of-tick acc/bak; 64-bit
                # hi/lo arithmetic per core/regs64.py).  `hl` gates the hi
                # plane: on hi-dead lanes (no 64-bit readers, see hi_live)
                # every hi op is statically elided — lo arithmetic wraps
                # exactly like the truncating wire, so this is bit-identical.
                hl = hi_live[n]
                if op == isa.OP_MOV_LOCAL and ins.dst == isa.DST_ACC:
                    new_acc[n] = jnp.where(c, src_val[n], new_acc[n])
                    if hl:
                        new_acc_hi[n] = jnp.where(c, src_hi[n], new_acc_hi[n])
                elif op == isa.OP_ADD:
                    if hl:
                        r_hi, r_lo = regs64.add64(
                            acc_hi[n], acc[n], src_hi[n], src_val[n]
                        )
                        new_acc_hi[n] = jnp.where(c, r_hi, new_acc_hi[n])
                    else:
                        r_lo = acc[n] + src_val[n]
                    new_acc[n] = jnp.where(c, r_lo, new_acc[n])
                elif op == isa.OP_SUB:
                    if hl:
                        r_hi, r_lo = regs64.sub64(
                            acc_hi[n], acc[n], src_hi[n], src_val[n]
                        )
                        new_acc_hi[n] = jnp.where(c, r_hi, new_acc_hi[n])
                    else:
                        r_lo = acc[n] - src_val[n]
                    new_acc[n] = jnp.where(c, r_lo, new_acc[n])
                elif op == isa.OP_NEG:
                    if hl:
                        r_hi, r_lo = regs64.neg64(acc_hi[n], acc[n])
                        new_acc_hi[n] = jnp.where(c, r_hi, new_acc_hi[n])
                    else:
                        r_lo = -acc[n]
                    new_acc[n] = jnp.where(c, r_lo, new_acc[n])
                elif op == isa.OP_SWP:
                    new_acc[n] = jnp.where(c, bak[n], new_acc[n])
                    new_bak[n] = jnp.where(c, acc[n], new_bak[n])
                    if hl:
                        new_acc_hi[n] = jnp.where(c, bak_hi[n], new_acc_hi[n])
                        new_bak_hi[n] = jnp.where(c, acc_hi[n], new_bak_hi[n])
                elif op == isa.OP_SAV:
                    new_bak[n] = jnp.where(c, acc[n], new_bak[n])
                    if hl:
                        new_bak_hi[n] = jnp.where(c, acc_hi[n], new_bak_hi[n])
                elif op == isa.OP_POP and ins.dst == isa.DST_ACC:
                    new_acc[n] = jnp.where(c, pop_val[ins.tgt], new_acc[n])
                    if hl:
                        new_acc_hi[n] = jnp.where(
                            c, pop_val[ins.tgt] >> 31, new_acc_hi[n]
                        )
                elif op == isa.OP_IN and ins.dst == isa.DST_ACC:
                    new_acc[n] = jnp.where(c, in_val, new_acc[n])
                    if hl:
                        new_acc_hi[n] = jnp.where(c, in_val >> 31, new_acc_hi[n])

                # pc effect (conditions see the full 64-bit acc)
                nxt = jnp.int32((l + 1) % ln)
                if op == isa.OP_JMP:
                    target = jnp.int32(ins.jmp)
                elif op == isa.OP_JEZ:
                    target = jnp.where(
                        regs64.is_zero(acc_hi[n], acc[n]), jnp.int32(ins.jmp), nxt
                    )
                elif op == isa.OP_JNZ:
                    target = jnp.where(
                        ~regs64.is_zero(acc_hi[n], acc[n]), jnp.int32(ins.jmp), nxt
                    )
                elif op == isa.OP_JGZ:
                    target = jnp.where(
                        regs64.is_pos(acc_hi[n], acc[n]), jnp.int32(ins.jmp), nxt
                    )
                elif op == isa.OP_JLZ:
                    target = jnp.where(
                        regs64.is_neg(acc_hi[n], acc[n]), jnp.int32(ins.jmp), nxt
                    )
                elif op == isa.OP_JRO:
                    target = regs64.jro_target(
                        jnp.int32(l), src_hi[n], src_val[n], jnp.int32(ln)
                    )
                else:
                    target = nxt
                new_pc[n] = jnp.where(c, target, new_pc[n])

            new_ho[n] = new_ho[n] & ~commit_n
            new_ret[n] = ret[n] + i32(commit_n)

        new_sc = (new_in_rd, in_wr, out_rd, new_out_wr, tick + 1)
        return (
            new_acc,
            new_bak,
            new_acc_hi,
            new_bak_hi,
            new_pc,
            new_pv,
            [i32(x) for x in new_pf],
            new_hv,
            [i32(x) for x in new_ho],
            new_sm,
            new_st,
            new_ob,
            new_sc,
            new_ret,
        )

    def kernel(*refs):
        (acc_r, bak_r, acc_hi_r, bak_hi_r, pc_r, pv_r, pf_r, hv_r, ho_r,
         sm_r, st_r, ob_r, sc_r, ret_r, inb_r) = refs[:15]
        outs = refs[15:]
        sm_out, ob_out = outs[9], outs[11]

        # Ref-resident big caps: seed the writable OUTPUT ref from the input
        # (input refs are aliased but only read; all tick-time access goes to
        # the output ref), then ticks mutate it in place.
        if not sm_in_regs:
            ref_copy(sm_r, sm_out, n_stacks * stack_cap)
        if not ob_in_regs:
            ref_copy(ob_r, ob_out, out_cap)

        rows = lambda ref, k: [ref[i] for i in range(k)]
        carry = (
            rows(acc_r, n_lanes),
            rows(bak_r, n_lanes),
            rows(acc_hi_r, n_lanes),
            rows(bak_hi_r, n_lanes),
            rows(pc_r, n_lanes),
            rows(pv_r, n_dests),
            rows(pf_r, n_dests),
            rows(hv_r, n_lanes),
            rows(ho_r, n_lanes),
            rows(sm_r, n_stacks * stack_cap) if sm_in_regs else [],
            rows(st_r, n_stacks),
            rows(ob_r, out_cap) if ob_in_regs else [],
            tuple(rows(sc_r, 5)),
            rows(ret_r, n_lanes),
        )
        inb = rows(inb_r, in_cap) if inb_in_regs else inb_r

        carry = jax.lax.fori_loop(
            0, num_steps,
            lambda t, c: tick_body(
                c, inb,
                None if sm_in_regs else sm_out,
                None if ob_in_regs else ob_out,
            ),
            carry,
        )

        # Carry-resident entries write back here; ref-resident ones ([] in
        # the carry) were mutated in place during the ticks.
        for out_ref, vals in zip(outs, carry):
            for i, v in enumerate(vals):
                out_ref[i] = v

    # --- pallas_call plumbing ----------------------------------------------

    def spec(rows_count):
        return pl.BlockSpec(
            (rows_count, bsr, LANE),
            lambda i: (0, i, 0),
            memory_space=pltpu.VMEM,
        )

    row_counts = [
        n_lanes, n_lanes, n_lanes, n_lanes, n_lanes, n_dests, n_dests,
        n_lanes, n_lanes, n_stacks * stack_cap, n_stacks, out_cap, 5, n_lanes,
    ]
    in_specs = [spec(k) for k in row_counts] + [spec(in_cap)]
    out_specs = [spec(k) for k in row_counts]
    out_shapes = [
        jax.ShapeDtypeStruct((k, batch // LANE, LANE), np.int32)
        for k in row_counts
    ]

    call = pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        input_output_aliases={i: i for i in range(14)},
        interpret=interpret,
    )

    # --- layout transforms ---------------------------------------------------

    def to_rows(x, rows_count):
        """[B, ...rest] -> [rows, B//LANE, LANE] (rest flattened to rows)."""
        flat = x.reshape(batch, rows_count)
        return jnp.transpose(flat, (1, 0)).reshape(rows_count, batch // LANE, LANE)

    def from_rows(y, rows_count, shape, dtype):
        flat = jnp.transpose(y.reshape(rows_count, batch), (1, 0))
        return flat.reshape(shape).astype(dtype)

    @jax.jit
    def run(state: NetworkState) -> NetworkState:
        sc = jnp.stack(
            [state.in_rd, state.in_wr, state.out_rd, state.out_wr, state.tick],
            axis=1,
        )  # [B, 5]
        args = [
            to_rows(state.acc, n_lanes),
            to_rows(state.bak, n_lanes),
            to_rows(state.acc_hi, n_lanes),
            to_rows(state.bak_hi, n_lanes),
            to_rows(state.pc, n_lanes),
            to_rows(state.port_val, n_dests),
            to_rows(state.port_full.astype(_I32), n_dests),
            to_rows(state.hold_val, n_lanes),
            to_rows(state.holding.astype(_I32), n_lanes),
            to_rows(state.stack_mem, n_stacks * stack_cap),
            to_rows(state.stack_top, n_stacks),
            to_rows(state.out_buf, out_cap),
            to_rows(sc, 5),
            to_rows(state.retired, n_lanes),
            to_rows(state.in_buf, in_cap),
        ]
        (acc, bak, acc_hi, bak_hi, pc, pv, pf, hv, ho, sm, st, ob, sc_o,
         ret) = call(*args)
        b = batch
        sc_flat = from_rows(sc_o, 5, (b, 5), _I32)
        return rebase_rings(NetworkState(
            acc=from_rows(acc, n_lanes, (b, n_lanes), _I32),
            bak=from_rows(bak, n_lanes, (b, n_lanes), _I32),
            acc_hi=from_rows(acc_hi, n_lanes, (b, n_lanes), _I32),
            bak_hi=from_rows(bak_hi, n_lanes, (b, n_lanes), _I32),
            pc=from_rows(pc, n_lanes, (b, n_lanes), _I32),
            port_val=from_rows(pv, n_dests, (b, n_lanes, isa.NUM_PORTS), _I32),
            port_full=from_rows(pf, n_dests, (b, n_lanes, isa.NUM_PORTS), _I32).astype(bool),
            hold_val=from_rows(hv, n_lanes, (b, n_lanes), _I32),
            holding=from_rows(ho, n_lanes, (b, n_lanes), _I32).astype(bool),
            stack_mem=from_rows(sm, n_stacks * stack_cap, (b, n_stacks, stack_cap), _I32),
            stack_top=from_rows(st, n_stacks, (b, n_stacks), _I32),
            in_buf=state.in_buf,
            in_rd=sc_flat[:, 0],
            in_wr=state.in_wr,
            out_buf=from_rows(ob, out_cap, (b, out_cap), _I32),
            out_rd=state.out_rd,
            out_wr=sc_flat[:, 3],
            tick=sc_flat[:, 4],
            retired=from_rows(ret, n_lanes, (b, n_lanes), _I32),
        ))

    return run
