"""Shared lane-local phases of the superstep kernel.

Three kernels execute the same TIS semantics over different agreement
fabrics: core/step.py (single chip, dense one-hot arbitration),
parallel/sharded.py (multi-chip, occupancy all_gather) and
parallel/routed.py (multi-chip, compact-slot pmin/psum).  What differs
between them is ONLY how same-tick conflicts are agreed; everything a lane
does locally — fetch/decode, the phase-A hold-latch consume, source
resolution, and the commit-time register/PC update — is identical, and any
ISA change must hit all three identically (the bit-identical invariant
tests/test_parallel.py and tests/test_differential.py pin).  Those shared
phases live here, once.

Semantics documentation lives with the canonical kernel (core/step.py's
module docstring, mapping each rule to program.go / stack.go / master.go);
this module is the mechanism.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from misaka_tpu.core import regs64
from misaka_tpu.core.state import NetworkState
from misaka_tpu.tis import isa

_I32 = jnp.int32


class Decoded(NamedTuple):
    """Per-lane decode + phase-A results (all arrays [N_lanes_local])."""

    op: jnp.ndarray
    src: jnp.ndarray
    imm: jnp.ndarray
    dst: jnp.ndarray
    tgt: jnp.ndarray
    tport: jnp.ndarray
    jmp: jnp.ndarray
    src_val: jnp.ndarray   # resolved low (wire) word of the source operand
    src_hi: jnp.ndarray    # 64-bit high word of the source (regs64.py)
    src_ok: jnp.ndarray    # source available (port sources: latched)
    holding: jnp.ndarray   # hold latch AFTER this tick's consumes
    hold_val: jnp.ndarray
    port_full_after_reads: jnp.ndarray  # [N, 4] occupancy after phase A


def decode_and_consume(code: jnp.ndarray, state: NetworkState) -> Decoded:
    """Fetch/decode at each lane's PC + phase A (consume ready port sources
    into the hold latch, resolve the source operand).

    See core/step.py's docstring for the two-phase hold-latch rationale
    (getFromSrc drains before delivery blocks, program.go:441-468).
    """
    n_lanes = code.shape[0]
    n_ports = isa.NUM_PORTS
    lane = jnp.arange(n_lanes)

    fields = code[lane, state.pc]
    op = fields[:, isa.F_OP]
    src = fields[:, isa.F_SRC]
    imm = fields[:, isa.F_IMM]

    is_port_src = src >= isa.SRC_R0
    pidx = jnp.clip(src - isa.SRC_R0, 0, n_ports - 1)
    port_v = state.port_val[lane, pidx]
    port_f = state.port_full[lane, pidx]
    reads_src = jnp.isin(op, jnp.asarray(isa.READS_SRC, dtype=_I32))
    reads_port = reads_src & is_port_src
    consume_now = reads_port & ~state.holding & port_f
    holding = state.holding | consume_now
    hold_val = jnp.where(consume_now, port_v, state.hold_val)
    src_val = jnp.where(
        src == isa.SRC_IMM,
        imm,
        jnp.where(
            src == isa.SRC_ACC,
            state.acc,
            jnp.where(src == isa.SRC_NIL, jnp.zeros_like(imm), hold_val),
        ),
    )
    # 64-bit source view: ACC carries its real high word; every other source
    # (imm, NIL, port values) is an int32 that sign-extends (regs64.py).
    # src_val (the low word) remains THE wire value for sends/stack/OUT.
    src_hi = jnp.where(src == isa.SRC_ACC, state.acc_hi, regs64.sext(src_val))
    src_ok = ~reads_port | holding

    # Ports cleared by this tick's consumes are visible to this tick's sends
    # (consume-then-send interleaving, one tick per pipeline hop).
    consume_onehot = consume_now[:, None] & (
        pidx[:, None] == jnp.arange(n_ports)[None, :]
    )
    port_full_after_reads = state.port_full & ~consume_onehot

    return Decoded(
        op=op, src=src, imm=imm,
        dst=fields[:, isa.F_DST], tgt=fields[:, isa.F_TGT],
        tport=fields[:, isa.F_PORT], jmp=fields[:, isa.F_JMP],
        src_val=src_val, src_hi=src_hi, src_ok=src_ok,
        holding=holding, hold_val=hold_val,
        port_full_after_reads=port_full_after_reads,
    )


def commit_lane_state(
    d: Decoded,
    prog_len: jnp.ndarray,
    state: NetworkState,
    commit: jnp.ndarray,
    pop_val_lane: jnp.ndarray,
    in_val: jnp.ndarray,
) -> dict:
    """The commit-time register file + PC update (begin-of-tick reads).

    Returns the new values of acc/bak (hi+lo), pc, holding as a dict of
    NetworkState field updates.  64-bit (hi, lo) arithmetic per regs64.py:
    ADD/SUB/NEG wrap at 64 bits like Go's int; values arriving from the
    network/stack/IN are int32 and sign-extend; local MOV ACC keeps width.
    Jump conditions evaluate the FULL 64-bit acc (program.go:300-340).
    """
    op, dst = d.op, d.dst
    is_pop = op == isa.OP_POP
    incoming = jnp.where(
        is_pop, pop_val_lane, jnp.where(op == isa.OP_IN, in_val, d.src_val)
    )
    incoming_hi = jnp.where(op == isa.OP_MOV_LOCAL, d.src_hi, regs64.sext(incoming))
    writes_acc = ((op == isa.OP_MOV_LOCAL) | is_pop | (op == isa.OP_IN)) & (
        dst == isa.DST_ACC
    )
    acc = state.acc
    acc_hi = state.acc_hi
    add_hi, add_lo = regs64.add64(acc_hi, acc, d.src_hi, d.src_val)
    sub_hi, sub_lo = regs64.sub64(acc_hi, acc, d.src_hi, d.src_val)
    neg_hi, neg_lo = regs64.neg64(acc_hi, acc)
    new_acc = jnp.where(commit & writes_acc, incoming, acc)
    new_acc_hi = jnp.where(commit & writes_acc, incoming_hi, acc_hi)
    new_acc = jnp.where(commit & (op == isa.OP_ADD), add_lo, new_acc)
    new_acc_hi = jnp.where(commit & (op == isa.OP_ADD), add_hi, new_acc_hi)
    new_acc = jnp.where(commit & (op == isa.OP_SUB), sub_lo, new_acc)
    new_acc_hi = jnp.where(commit & (op == isa.OP_SUB), sub_hi, new_acc_hi)
    new_acc = jnp.where(commit & (op == isa.OP_NEG), neg_lo, new_acc)
    new_acc_hi = jnp.where(commit & (op == isa.OP_NEG), neg_hi, new_acc_hi)
    new_acc = jnp.where(commit & (op == isa.OP_SWP), state.bak, new_acc)
    new_acc_hi = jnp.where(commit & (op == isa.OP_SWP), state.bak_hi, new_acc_hi)
    saves_bak = commit & ((op == isa.OP_SWP) | (op == isa.OP_SAV))
    new_bak = jnp.where(saves_bak, acc, state.bak)
    new_bak_hi = jnp.where(saves_bak, acc_hi, state.bak_hi)

    jump_taken = (
        (op == isa.OP_JMP)
        | ((op == isa.OP_JEZ) & regs64.is_zero(acc_hi, acc))
        | ((op == isa.OP_JNZ) & ~regs64.is_zero(acc_hi, acc))
        | ((op == isa.OP_JGZ) & regs64.is_pos(acc_hi, acc))
        | ((op == isa.OP_JLZ) & regs64.is_neg(acc_hi, acc))
    )
    pc_inc = (state.pc + 1) % prog_len                          # program.go:429
    pc_jro = regs64.jro_target(state.pc, d.src_hi, d.src_val, prog_len)  # :354
    new_pc = jnp.where(jump_taken, d.jmp, jnp.where(op == isa.OP_JRO, pc_jro, pc_inc))
    new_pc = jnp.where(commit, new_pc, state.pc)

    return dict(
        acc=new_acc, bak=new_bak, acc_hi=new_acc_hi, bak_hi=new_bak_hi,
        pc=new_pc, hold_val=d.hold_val, holding=d.holding & ~commit,
    )


def apply_stack_ring_updates(
    state: NetworkState,
    push_per_stack: jnp.ndarray,
    pop_per_stack: jnp.ndarray,
    push_val: jnp.ndarray,
    in_any: jnp.ndarray,
    out_any: jnp.ndarray,
    out_val: jnp.ndarray,
) -> dict:
    """Stack memory + master I/O ring updates from agreed per-tick winners.

    At most one push OR pop per stack, one IN, one OUT per tick (the
    lowest-lane arbitration discipline); all reads are begin-of-tick.
    Returns NetworkState field updates.
    """
    n_stacks, stack_cap = state.stack_mem.shape
    out_cap = state.out_buf.shape[0]

    stack_ids = jnp.arange(n_stacks)
    push_slot = jnp.clip(state.stack_top, 0, stack_cap - 1)
    cur_slot_val = state.stack_mem[stack_ids, push_slot]
    new_stack_mem = state.stack_mem.at[stack_ids, push_slot].set(
        jnp.where(push_per_stack, push_val, cur_slot_val)
    )
    new_stack_top = (
        state.stack_top + push_per_stack.astype(_I32) - pop_per_stack.astype(_I32)
    )

    new_in_rd = state.in_rd + in_any.astype(_I32)
    out_slot = state.out_wr % out_cap
    new_out_buf = state.out_buf.at[out_slot].set(
        jnp.where(out_any, out_val, state.out_buf[out_slot])
    )
    new_out_wr = state.out_wr + out_any.astype(_I32)

    return dict(
        stack_mem=new_stack_mem, stack_top=new_stack_top,
        in_rd=new_in_rd, out_buf=new_out_buf, out_wr=new_out_wr,
    )
