"""Execution engine: jitted chunk runner + host-side I/O for a compiled network.

The reference's execution model is one free-running goroutine per node
(program.go:78-92) with the master's HTTP thread feeding cap-1 channels
(master.go:216-219).  Here the whole network advances in jitted chunks of K
supersteps (lax.scan), with the host touching device state only at chunk
boundaries: refill the input ring, drain the output ring.  A leading batch
axis runs B independent network instances in lockstep (vmap) — the data
parallelism the reference lacks entirely (SURVEY.md §2 taxonomy).
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from misaka_tpu.core.state import NetworkState, init_state, rebase_rings
from misaka_tpu.core.step import step

_I32 = jnp.int32

# Lane count at/above which the compact scatter-election kernel
# (core/routing.py) replaces the dense one-hot kernel (core/step.py) as the
# auto-selected scan engine.  The dense kernel's election matrices are
# O(N·4N) per tick; the compact kernel is O(N + active-dests).  The
# crossover is PLATFORM-dependent (VERDICT r4 weak #2, measured r5):
#
#   cpu: compact wins at EVERY width — 2 lanes 71k vs 46k ticks/s, 3 lanes
#        (add2 shape, batch 512) 9.6k vs 4.0k, 16 lanes 15.1k vs 6.1k,
#        64 lanes 5.1k vs 0.16k (bench.py lane_scaling + r5 session
#        measurements) — threshold 0, always compact.
#   tpu: measured r5 (BENCH_tpu_r05.json lane_scaling + artifacts/r05):
#        the dense one-hot rides the VPU and beats compact at every
#        measured small width — 16 lanes 176k vs 106k values/s, 32 lanes
#        524k vs 332k lane-batch-normalized inst-ticks/s — but its
#        election matrix is O(N^2 x batch) bytes: 64 lanes x 4096 batch
#        (67 MiB/tick) reproducibly crashed/wedged the worker in r4 and
#        both r5 captures.  At PRODUCTION batches that memory wall sits
#        below 32 lanes, so 32 stays the auto threshold on safety — the
#        measured 1.6x dense win at 32 lanes only exists at bench-sized
#        batches the footprint cap admits.
#
# COMPACT_AUTO_LANES is the TPU/default constant; decision sites go through
# compact_auto_lanes(), which reads the live backend (and the
# MISAKA_COMPACT_AUTO_LANES override).
COMPACT_AUTO_LANES = 32
_COMPACT_AUTO_BY_PLATFORM = {"cpu": 0, "tpu": COMPACT_AUTO_LANES}

# Which kernel serves networks at/above the threshold.  Measured r5 on
# hardware (artifacts/r05/lane_followup.json): the CHAINED election —
# scatter-free, statically-unrolled min/sum chains (core/routing.py
# ChainTable) — beats the scatter kernel 1.40x at 64 lanes and 1.44x at
# 256 lanes on TPU (56 vs 40 and 59 vs 41 ticks/s, same batch), exactly
# the scatter-serialization ceiling it was built to dodge.  On CPU, XLA
# lowers scatters well and chained measures ~0.7x compact, so compact
# stays the CPU wide kernel.
_WIDE_ENGINE_BY_PLATFORM = {"cpu": "compact", "tpu": "chained"}


def compact_auto_lanes() -> int:
    """Platform-dependent dense->wide-kernel auto-switch threshold."""
    env = os.environ.get("MISAKA_COMPACT_AUTO_LANES")
    if env:
        return int(env)
    return _COMPACT_AUTO_BY_PLATFORM.get(
        jax.default_backend(), COMPACT_AUTO_LANES
    )


def wide_engine() -> str:
    """Platform-dependent wide-network kernel: "chained" on TPU (1.4x the
    scatter kernel at 64/256 lanes, measured r5), "compact" on CPU.
    Override with MISAKA_WIDE_ENGINE=compact|chained."""
    env = os.environ.get("MISAKA_WIDE_ENGINE")
    if env:
        if env not in ("compact", "chained"):
            raise ValueError(
                f"MISAKA_WIDE_ENGINE must be compact|chained, got {env!r}"
            )
        return env
    return _WIDE_ENGINE_BY_PLATFORM.get(jax.default_backend(), "compact")


def _chunk_body(step_fn, tables, state: NetworkState, num_steps: int,
                batched: bool) -> NetworkState:
    """`num_steps` ticks of `step_fn` under lax.scan (+ vmap when batched).

    The one copy of the chunk contract, shared by the dense/compact jits
    below and the per-network compact closures."""
    fn = step_fn if not batched else jax.vmap(step_fn, in_axes=(None, None, 0))

    def body(s, _):
        return fn(tables[0], tables[1], s), None

    out, _ = jax.lax.scan(body, state, None, length=num_steps)
    return rebase_rings(out)


def _serve_body(step_fn, tables, state: NetworkState, values, count,
                num_steps: int):
    """Feed + run + counter/output snapshot + drain: the ONE copy of the
    one-dispatch serve contract (see serve_chunk).  `packed` layout
    [in_rd, in_wr, out_rd, out_wr, out_buf...] is parsed by the device
    loop's p[:4]/p[4:]; keep them in lockstep."""
    in_cap = state.in_buf.shape[0]
    k = values.shape[0]
    idx = (state.in_wr + jnp.arange(k, dtype=_I32)) % in_cap
    mask = jnp.arange(k) < count
    new_buf = state.in_buf.at[idx].set(jnp.where(mask, values, state.in_buf[idx]))
    state = state._replace(in_buf=new_buf, in_wr=state.in_wr + count.astype(_I32))
    state = _chunk_body(step_fn, tables, state, num_steps, batched=False)
    packed = jnp.concatenate([
        jnp.stack([state.in_rd, state.in_wr, state.out_rd, state.out_wr]),
        state.out_buf,
    ])
    return state._replace(out_rd=state.out_wr), packed


@functools.partial(jax.jit, static_argnums=(2,), donate_argnums=(1,))
def _run_chunk(tables, state: NetworkState, num_steps: int) -> NetworkState:
    return _chunk_body(step, tables, state, num_steps, batched=False)


@functools.partial(jax.jit, static_argnums=(2,), donate_argnums=(1,))
def _run_chunk_batched(tables, state: NetworkState, num_steps: int) -> NetworkState:
    return _chunk_body(step, tables, state, num_steps, batched=True)


@functools.partial(jax.jit, static_argnums=(3,), donate_argnums=(1, 2))
def _run_chunk_traced(tables, state: NetworkState, trace, num_steps: int):
    from misaka_tpu.core.trace import run_traced

    code, prog_len = tables
    state, trace = run_traced(code, prog_len, state, trace, num_steps)
    return rebase_rings(state), trace


@functools.partial(jax.jit, static_argnums=(3, 4), donate_argnums=(1, 2))
def _run_chunk_traced_batched(tables, state: NetworkState, trace, num_steps: int,
                              instance: int):
    """Batched chunk with instruction tracing of ONE instance (default 0).

    Instances are independent, so recording instance `instance` while all B
    advance in lockstep costs one sliced trace store per tick — the batched
    production configuration stays debuggable (the reference's only substitute
    is a per-instruction stdout log, program.go:222-223)."""
    from misaka_tpu.core.trace import record_step

    code, prog_len = tables
    step_b = jax.vmap(step, in_axes=(None, None, 0))

    def body(carry, _):
        s, t = carry
        s2 = step_b(code, prog_len, s)
        one = lambda st: jax.tree.map(lambda x: x[instance], st)
        t2 = record_step(code, one(s), one(s2), t)
        return (s2, t2), None

    (state, trace), _ = jax.lax.scan(body, (state, trace), None, length=num_steps)
    return rebase_rings(state), trace


@functools.partial(jax.jit, static_argnums=(4,), donate_argnums=(1,))
def _serve_chunk(tables, state: NetworkState, values, count, num_steps: int):
    """Feed + run + counter/output snapshot + drain in ONE dispatch.

    The round-2 unbatched device loop paid up to four device interactions
    per iteration (feed, run, counters, drain) — ~6 round trips per quiet
    /compute on a relayed device vs the one-dispatch kernel floor
    (VERDICT r2 weak #3).  This fuses the whole serve iteration: the host
    enqueues (values, count), gets back the advanced state plus ONE packed
    int32 array [in_rd, in_wr, out_rd, out_wr, out_buf...], and extracts
    outputs from the snapshot while the device ring is already drained
    (out_rd := out_wr happens on-device, after the snapshot).
    """
    return _serve_body(step, tables, state, values, count, num_steps)


@jax.jit
def _read_counters(state: NetworkState) -> jnp.ndarray:
    """All four ring counters as ONE device array: [4] (or [4, B] batched).

    The serving loop reads these every iteration; packing them into a single
    transfer matters when the device link is a relay (one round trip instead
    of four).
    """
    return jnp.stack([state.in_rd, state.in_wr, state.out_rd, state.out_wr])


@jax.jit
def _feed(state: NetworkState, values: jnp.ndarray, count: jnp.ndarray) -> NetworkState:
    """Append `count` leading entries of `values` to the input ring.

    Caller guarantees count <= free space and len(values) <= in_cap, so the
    scatter indices are distinct.
    """
    in_cap = state.in_buf.shape[0]
    k = values.shape[0]
    idx = (state.in_wr + jnp.arange(k, dtype=_I32)) % in_cap
    mask = jnp.arange(k) < count
    new_buf = state.in_buf.at[idx].set(jnp.where(mask, values, state.in_buf[idx]))
    return state._replace(in_buf=new_buf, in_wr=state.in_wr + count.astype(_I32))


@jax.jit
def _feed_batched(state: NetworkState, values: jnp.ndarray, counts: jnp.ndarray) -> NetworkState:
    """Per-instance ring append: values [B, K], counts [B] (counts <= free).

    K is fixed (the ring capacity) so this compiles once; masked slots keep
    their old contents.
    """
    in_cap = state.in_buf.shape[-1]
    b, k = values.shape
    rows = jnp.arange(b)[:, None]
    idx = (state.in_wr[:, None] + jnp.arange(k, dtype=_I32)[None, :]) % in_cap
    mask = jnp.arange(k)[None, :] < counts[:, None]
    cur = state.in_buf[rows, idx]
    new_buf = state.in_buf.at[rows, idx].set(jnp.where(mask, values, cur))
    return state._replace(in_buf=new_buf, in_wr=state.in_wr + counts.astype(_I32))


@dataclass
class CompiledNetwork:
    """A lowered network bound to the jitted superstep engine.

    code/prog_len come from tis.lower.pad_programs.  `batch=None` runs one
    network instance; an integer B runs B independent instances in lockstep
    (state arrays gain a leading batch axis).
    """

    code: np.ndarray          # [N, L, NFIELDS] int32
    prog_len: np.ndarray      # [N] int32
    num_stacks: int = 1
    stack_cap: int = 1024     # reference stacks are unbounded (intStack.go:9);
                              # bounded here — a full stack parks the pusher.
                              # Documented divergence, config knob.
    in_cap: int = 1024
    out_cap: int = 1024
    batch: int | None = None
    _tables: tuple = field(init=False, repr=False)
    _compact_fn: object = field(init=False, repr=False, default=None)
    _compact_chunk: object = field(init=False, repr=False, default=None)
    _chained_fn: object = field(init=False, repr=False, default=None)
    _chained_chunk: object = field(init=False, repr=False, default=None)
    _compact_serve: object = field(init=False, repr=False, default=None)

    def __post_init__(self):
        # At least one (possibly phantom) stack keeps kernel shapes nonempty.
        self.num_stacks = max(1, self.num_stacks)
        self._tables = (
            jnp.asarray(self.code, dtype=_I32),
            jnp.asarray(self.prog_len, dtype=_I32),
        )

    @property
    def num_lanes(self) -> int:
        return self.code.shape[0]

    def init_state(self) -> NetworkState:
        s = init_state(
            self.num_lanes, self.num_stacks, self.stack_cap, self.in_cap, self.out_cap
        )
        if self.batch is not None:
            s = jax.tree.map(
                lambda x: jnp.broadcast_to(x, (self.batch,) + x.shape).copy(), s
            )
        return s

    def step_fn(self):
        """The auto-selected per-tick step function (single instance):
        dense one-hot below compact_auto_lanes() lanes (platform-dependent:
        0 on CPU, so CPU never runs dense), the platform wide kernel
        (wide_engine(): scatter elections on CPU, chained elections on
        TPU — core/routing.py) at/above.  All are bit-identical; only the
        arbitration data structure differs."""
        if self.num_lanes < compact_auto_lanes():
            return step
        if wide_engine() == "chained":
            return self._chained_step()
        return self._compact_step()

    def _compact_step(self):
        if self._compact_fn is None:
            from misaka_tpu.core.routing import build_route_table, step_slots

            route = build_route_table(self.code, self.prog_len)
            self._compact_fn = functools.partial(step_slots, route)
        return self._compact_fn

    def _chained_step(self):
        """The scatter-free compact variant: elections as statically
        unrolled min/sum chains (core/routing.py ChainTable) — the r5
        probe at the TPU wide-lane scatter ceiling."""
        if self._chained_fn is None:
            from misaka_tpu.core.routing import (
                build_chain_table,
                build_route_table,
                step_slots,
            )

            route = build_route_table(self.code, self.prog_len)
            chain = build_chain_table(
                self.code, self.prog_len, route, self.num_stacks
            )
            self._chained_fn = functools.partial(step_slots, route, chain=chain)
        return self._chained_fn

    def run(
        self, state: NetworkState, num_steps: int, engine: str | None = None
    ) -> NetworkState:
        """Advance `num_steps` supersteps in one jitted scan (donated state).

        engine: None auto-selects by lane count (see step_fn); "dense" /
        "compact" force a kernel (the bench's lane-ceiling probe).
        """
        if engine is None:
            engine = (
                wide_engine()
                if self.num_lanes >= compact_auto_lanes()
                else "dense"
            )
        if engine in ("compact", "chained"):
            cache_attr = "_compact_chunk" if engine == "compact" else "_chained_chunk"
            if getattr(self, cache_attr) is None:
                step1 = (
                    self._compact_step()
                    if engine == "compact"
                    else self._chained_step()
                )
                tables = self._tables
                batched = self.batch is not None

                @functools.partial(jax.jit, static_argnums=(1,), donate_argnums=(0,))
                def chunk(s, n):
                    return _chunk_body(step1, tables, s, n, batched)

                setattr(self, cache_attr, chunk)
            return getattr(self, cache_attr)(state, num_steps)
        if engine != "dense":
            raise ValueError(
                f"engine must be dense|compact|chained|None, got {engine!r}"
            )
        runner = _run_chunk if self.batch is None else _run_chunk_batched
        return runner(self._tables, state, num_steps)

    def init_trace(self, cap: int = 256):
        """Fresh per-lane trace ring (unbatched networks; the debug path)."""
        from misaka_tpu.core.trace import init_trace

        return init_trace(self.num_lanes, cap)

    def run_traced(self, state: NetworkState, trace, num_steps: int,
                   instance: int = 0):
        """Like `run`, but records fetch/commit/acc into `trace` (core/trace.py).

        Unbatched networks record every lane; batched networks record the
        lanes of one selectable instance (instances are independent, so the
        traced instance's history is exact while all B advance together)."""
        if self.batch is None:
            return _run_chunk_traced(self._tables, state, trace, num_steps)
        if not (0 <= instance < self.batch):
            raise ValueError(f"instance {instance} out of range [0, {self.batch})")
        return _run_chunk_traced_batched(
            self._tables, state, trace, num_steps, instance
        )

    def fused_runner(
        self,
        num_steps: int,
        block_batch: int | None = None,
        interpret: bool = False,
        unroll_cap: int | None = None,
        elide_dead_hi: bool | None = None,
    ):
        """The Pallas fast path: fn(state) -> state, `num_steps` ticks in ONE
        kernel launch with all state VMEM-resident (batched networks only).
        ~36x faster per tick than `run` on TPU at B=8192; bit-identical
        semantics (tests/test_fused.py).

        `unroll_cap` overrides the register/VMEM storage-mode threshold
        (fused.UNROLL_CAP); tests pass a tiny value to force the chunked
        dynamic-slice path on small caps.
        """
        if self.batch is None:
            raise ValueError("fused_runner requires a batched network")
        from misaka_tpu.core.fused import make_fused_runner

        return make_fused_runner(
            self.code,
            self.prog_len,
            num_stacks=self.num_stacks,
            stack_cap=self.stack_cap,
            in_cap=self.in_cap,
            out_cap=self.out_cap,
            batch=self.batch,
            num_steps=num_steps,
            block_batch=block_batch,
            interpret=interpret,
            unroll_cap=unroll_cap,
            elide_dead_hi=elide_dead_hi,
        )

    def fused_runner_walk(
        self,
        num_steps: int,
        candidates=(None, 512, 256, 128),
        interpret: bool = False,
    ):
        """fused_runner, walking `candidates` block sizes down until one
        fits the VMEM carry budget (big caps / wide lanes reject large
        blocks — e.g. 64 lanes is 1,102 carry rows, 9 MB at block 2048).

        Returns (runner, block_batch_used); raises the last budget
        ValueError when nothing fits.  The ONE copy of the walk, shared by
        the serving path and the bench lane matrix.
        """
        if self.batch is None:
            raise ValueError("fused_runner requires a batched network")
        err: ValueError | None = None
        for bb in candidates:
            if bb is not None and (self.batch % bb or bb > self.batch):
                continue
            try:
                return (
                    self.fused_runner(
                        num_steps, block_batch=bb, interpret=interpret
                    ),
                    bb,
                )
            except ValueError as e:
                err = e
        raise err if err is not None else ValueError(
            f"no block-size candidate applies to batch={self.batch}"
        )

    def make_batched_serve(self, runner, num_steps: int):
        """Build the one-dispatch BATCHED serve iteration: returns
        (serve_fn, idle_fn) where

          serve_fn(state, values [B, in_cap], counts [B]) -> (state, packed)
          idle_fn(state)                                  -> (state, ctrs)

        serve_fn's `packed` is ONE [B, 4 + out_cap] device array holding
        each instance's [in_rd, in_wr, out_rd, out_wr, out_buf...] snapshot
        with the output ring already drained on-device (out_rd := out_wr).
        The piecewise loop paid four device interactions per iteration
        (feed, run, counters, drain) — four round trips on a relayed
        device; this pays one dispatch + one read.

        idle_fn (quiet iterations) skips BOTH the [B, in_cap] feed upload
        and the [B, out_cap] ring download: it returns only the [B, 4]
        counters and leaves the ring undrained, so the caller fetches
        outputs with drain_batched only on the rare idle iteration that
        actually produced some.

        `runner` is the engine chunk fn (the fused Pallas runner) or None
        for the XLA scan engine; it is inlined into the combined jit.
        """
        if self.batch is None:
            raise ValueError("make_batched_serve requires a batched network")
        tables = self._tables

        scan_step = None if runner is not None else self.step_fn()

        def advance(state):
            if runner is not None:
                return runner(state)
            return _chunk_body(scan_step, tables, state, num_steps, batched=True)

        def ctrs_of(state):
            return jnp.stack(
                [state.in_rd, state.in_wr, state.out_rd, state.out_wr], axis=1
            )

        def serve(state, values, counts):
            state = advance(_feed_batched(state, values, counts))
            packed = jnp.concatenate([ctrs_of(state), state.out_buf], axis=1)
            return state._replace(out_rd=state.out_wr), packed

        def idle(state):
            state = advance(state)
            return state, ctrs_of(state)  # ring untouched: counters only

        return (
            jax.jit(serve, donate_argnums=(0,)),
            jax.jit(idle, donate_argnums=(0,)),
        )

    @staticmethod
    def drain_from_snapshot(buf, rd, wr, out_cap):
        """Ragged per-instance gather of pending outputs from a ring
        snapshot: returns [(slot, values)] like drain_batched, with one
        vectorized gather for all active instances."""
        active = np.nonzero(wr > rd)[0]
        if active.size == 0:
            return []
        counts = (wr - rd)[active]
        bounds = np.cumsum(counts)
        seq = np.arange(bounds[-1]) - np.repeat(bounds - counts, counts)
        idx = (np.repeat(rd[active], counts) + seq) % out_cap
        flat = buf[np.repeat(active, counts), idx]
        return list(zip(active.tolist(), np.split(flat, bounds[:-1])))

    def serve_chunk(self, state: NetworkState, values, count, num_steps: int):
        """One-dispatch serve iteration (unbatched device loop): feed the
        `count` leading entries of `values` ([in_cap] int32), advance
        `num_steps` ticks, and return (state, packed) where `packed` is ONE
        device array [in_rd, in_wr, out_rd, out_wr, out_buf...] and the
        returned state's output ring is already drained (out_rd = out_wr).

        The host extracts outputs from the packed snapshot — a full serve
        iteration costs one dispatch + one device read instead of the four
        interactions (feed/run/counters/drain) of the piecewise path.
        """
        if self.batch is not None:
            raise ValueError("serve_chunk drives a single network instance")
        if self.num_lanes < compact_auto_lanes():
            return _serve_chunk(
                self._tables, state, jnp.asarray(values),
                jnp.asarray(count, _I32), num_steps,
            )
        # Wide networks serve through the platform wide kernel (scatter
        # elections on CPU, chained on TPU — bit-identical, so a cached
        # closure surviving an env flip is a perf nuance, not a wrong
        # answer); the route table is baked into a per-network jitted
        # closure (it is not hashable, so it cannot ride as a static arg
        # of the module-level jit).
        if self._compact_serve is None:
            step1 = (
                self._chained_step()
                if wide_engine() == "chained"
                else self._compact_step()
            )
            tables = self._tables

            @functools.partial(jax.jit, static_argnums=(3,), donate_argnums=(0,))
            def serve(state, values, count, num_steps):
                return _serve_body(step1, tables, state, values, count, num_steps)

            self._compact_serve = serve
        return self._compact_serve(
            state, jnp.asarray(values), jnp.asarray(count, _I32), num_steps
        )

    # --- host-side I/O (chunk-boundary only) -------------------------------

    def feed(self, state: NetworkState, values) -> tuple[NetworkState, int]:
        """Enqueue up to len(values) inputs; returns (state, accepted_count).

        Unbatched networks only — batched I/O is driven by the bench/runtime
        with its own jitted feeders.
        """
        if self.batch is not None:
            raise ValueError(
                "feed/drain/compute_stream drive a single network instance; "
                "for batch mode write the I/O rings directly (see bench.py)"
            )
        values = np.asarray(values, dtype=np.int32)
        free = self.in_cap - int(state.in_wr - state.in_rd)
        k = min(len(values), free)
        if k == 0:
            return state, 0
        buf = np.zeros((self.in_cap,), np.int32)
        buf[:k] = values[:k]
        return _feed(state, jnp.asarray(buf), jnp.asarray(k, _I32)), k

    def feed_batched(self, state: NetworkState, values, counts) -> NetworkState:
        """Append per-instance inputs: values [B, in_cap] int32, counts [B].

        Caller guarantees counts[b] <= free space of instance b (the batched
        master computes free from the same state it passes in).
        """
        if self.batch is None:
            raise ValueError("feed_batched requires a batched network")
        values = np.ascontiguousarray(values, dtype=np.int32)
        counts = np.ascontiguousarray(counts, dtype=np.int32)
        if values.shape != (self.batch, self.in_cap) or counts.shape != (self.batch,):
            raise ValueError(
                f"need values [{self.batch}, {self.in_cap}] and counts "
                f"[{self.batch}], got {values.shape} / {counts.shape}"
            )
        return _feed_batched(state, jnp.asarray(values), jnp.asarray(counts))

    def counters(self, state: NetworkState) -> np.ndarray:
        """[in_rd, in_wr, out_rd, out_wr] in ONE device read ([4] or [4, B])."""
        return np.asarray(_read_counters(state))

    def drain_batched(
        self,
        state: NetworkState,
        rd: np.ndarray | None = None,
        wr: np.ndarray | None = None,
    ) -> tuple[NetworkState, list[tuple[int, np.ndarray]]]:
        """Collect pending outputs per instance, in order; advances out_rd.

        Returns (slot, values) pairs for instances that produced anything —
        host cost is O(active + values), with exactly one device read (the
        output ring) when rd/wr are passed in from a prior counters() call.
        """
        if self.batch is None:
            raise ValueError("drain_batched requires a batched network")
        if rd is None or wr is None:
            c = self.counters(state)
            rd, wr = c[2], c[3]
        if (wr == rd).all():
            return state, []
        # one ragged gather for ALL active instances (the per-instance
        # fancy-index loop cost O(active) numpy calls per drain — at B=8192
        # that loop, not the engine, was the serve path's floor)
        buf = np.asarray(state.out_buf)
        outs = self.drain_from_snapshot(buf, rd, wr, self.out_cap)
        return state._replace(out_rd=jnp.asarray(wr)), outs

    def drain(self, state: NetworkState) -> tuple[NetworkState, list[int]]:
        """Collect all pending outputs in order; advances out_rd."""
        if self.batch is not None:
            raise ValueError(
                "feed/drain/compute_stream drive a single network instance; "
                "for batch mode write the I/O rings directly (see bench.py)"
            )
        rd = int(state.out_rd)
        wr = int(state.out_wr)
        if wr == rd:
            return state, []
        buf = np.asarray(state.out_buf)
        vals = [int(buf[i % self.out_cap]) for i in range(rd, wr)]
        return state._replace(out_rd=jnp.asarray(wr, _I32)), vals

    def compute_stream(
        self,
        state: NetworkState,
        values,
        chunk: int = 64,
        max_steps: int = 1_000_000,
        expected: int | None = None,
    ) -> tuple[NetworkState, list[int]]:
        """Feed a value stream and run until `expected` outputs arrive
        (default: one output per input).

        The serialized-workload oracle mode: equivalent to the reference's
        /compute called sequentially (master.go:197-224), where pairing is
        unambiguous.  Pass `expected` for networks whose output count differs
        from the input count (e.g. examples/multiply.json: 2 inputs -> 1).
        """
        pending = list(values)
        outputs: list[int] = []
        if expected is None:
            expected = len(pending)
        steps = 0
        while len(outputs) < expected:
            if steps >= max_steps:
                raise RuntimeError(
                    f"network made no full progress after {steps} supersteps "
                    f"({len(outputs)}/{expected} outputs) — deadlock or starvation"
                )
            if pending:
                state, took = self.feed(state, pending)
                pending = pending[took:]
            state = self.run(state, chunk)
            steps += chunk
            state, got = self.drain(state)
            outputs.extend(got)
        return state, outputs
