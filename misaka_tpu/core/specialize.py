"""Per-program specialized native tick functions (ISSUE 12 layer 2).

The registry compiles each program version exactly once (content-addressed,
PR 6), which makes activation the perfect hook for native specialization:
instead of the generic interpreter dispatching opcodes through runtime
tables, we re-compile ``native/interpreter.cpp`` with the program's tables
and every dimension baked in as ``constexpr`` data (a generated header,
``-DMISAKA_SPEC_HEADER``).  The group SIMD tick template then instantiates
against compile-time constants: lane loops unroll, dimension arithmetic
folds, and the program reads from ``.rodata`` — the C++ equivalent of what
XLA does when it burns the code table into the compiled kernel.

Contracts:

* **Cache**: one ``.so`` per (source hash, program tables, dims, flags)
  key, built atomically (tmp + ``os.replace``) so concurrent activations
  of the same version race benignly.  The registry passes a cache dir next
  to the version store; everything else shares a per-user tmp cache.
* **Graceful fallback**: ANY failure (no toolchain, compile error, the
  ``specialize_fail`` chaos fault) logs, counts on
  ``misaka_native_specialize_total{status=error}``, and returns ``None`` —
  the caller serves on the generic interpreter.  A specialized build whose
  baked tables do not match the runtime network degrades inside the C++
  side too (``spec_matches``), so a mis-keyed cache entry can never
  execute another program's code.
* **Kill switch**: ``MISAKA_SPECIALIZE=0`` disables the whole layer.

Bit-identity: the specialized paths are instantiations of the SAME
templates as the generic group engine (native/interpreter.cpp), pinned by
tests/test_simd.py's differential corpus.
"""

from __future__ import annotations

import hashlib
import logging
import os
import subprocess
import tempfile
import threading

import numpy as np

from misaka_tpu.tis import isa
from misaka_tpu.utils import faults
from misaka_tpu.utils import metrics

log = logging.getLogger("misaka.specialize")

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SRC = os.path.join(_REPO_ROOT, "native", "interpreter.cpp")

SPEC_VERSION = 2  # bump to invalidate every cached specialization
# v2 (r17): the generated header grew a second section — a per-(lane, pc)
# SWITCH-THREADED tick (misaka_spec_tick) whose cases carry the
# instruction fields and pc successors as literals, replacing the
# gather-driven fetch entirely on the specialized path.

M_SPECIALIZE = metrics.counter(
    "misaka_native_specialize_total",
    "Per-program native specialization outcomes (hit = cache reuse, "
    "built = fresh compile, error = compile failure -> generic fallback, "
    "fallback = load/engage failure after a successful build, "
    "disabled = kill switch)",
    ("status",),
)
M_CACHE_EVICT = metrics.counter(
    "misaka_specialize_cache_evictions_total",
    "Specialized-build cache entries evicted by the size/entry LRU bound",
)
G_CACHE_ENTRIES = metrics.gauge(
    "misaka_specialize_cache_entries",
    "Specialized .so entries in the on-disk cache after the last prune",
)
G_CACHE_BYTES = metrics.gauge(
    "misaka_specialize_cache_bytes",
    "Bytes held by the specialized-build cache after the last prune",
)


def enabled() -> bool:
    """MISAKA_SPECIALIZE kill switch (default on: activation compiles a
    specialized tick, any failure falls back to the generic engine)."""
    return os.environ.get("MISAKA_SPECIALIZE", "1") not in ("0", "off")


def _extra_flags() -> list[str]:
    """MISAKA_SPEC_CXXFLAGS: extra compile flags (the sanitizer stress
    lane instruments specialized builds with these)."""
    raw = os.environ.get("MISAKA_SPEC_CXXFLAGS", "")
    return [f for f in raw.split() if f]


def default_cache_dir() -> str:
    """The shared on-disk cache for non-registry callers: per-user so a
    multi-user box never trips over permissions."""
    explicit = os.environ.get("MISAKA_SPEC_CACHE")
    if explicit:
        return explicit
    uid = os.getuid() if hasattr(os, "getuid") else 0
    return os.path.join(tempfile.gettempdir(), f"misaka-spec-{uid}")


_src_hash_cache: str | None = None
_src_hash_lock = threading.Lock()


def _src_hash() -> str:
    global _src_hash_cache
    with _src_hash_lock:
        if _src_hash_cache is None:
            with open(_SRC, "rb") as f:
                _src_hash_cache = hashlib.sha256(f.read()).hexdigest()[:16]
        return _src_hash_cache


def _switch_cap() -> int:
    """MISAKA_SPEC_SWITCH_MAX: total-instruction ceiling for the generated
    switch-threaded tick (code size is proportional to it); 0 disables the
    layer, falling back to the table-baked generic template tick."""
    return int(os.environ.get("MISAKA_SPEC_SWITCH_MAX", "") or 4096)


def spec_key(code: np.ndarray, prog_len: np.ndarray, num_stacks: int,
             stack_cap: int, in_cap: int, out_cap: int) -> str:
    """Content key: interpreter source hash (the build id — a source change
    invalidates every cached .so) + program tables + dimensions + flags."""
    h = hashlib.sha256()
    h.update(f"v{SPEC_VERSION}:{_src_hash()}".encode())
    h.update(
        f":{num_stacks}:{stack_cap}:{in_cap}:{out_cap}"
        f":{code.shape}:{' '.join(_extra_flags())}:sw{_switch_cap()}:".encode()
    )
    h.update(np.ascontiguousarray(code, np.int32).tobytes())
    h.update(np.ascontiguousarray(prog_len, np.int32).tobytes())
    return h.hexdigest()[:16]


# instruction-word fields (mirrors native/interpreter.cpp enum Field)
_F_OP, _F_SRC, _F_IMM, _F_DST, _F_TGT, _F_PORT, _F_JMP = range(7)
_READS = {isa.OP_MOV_LOCAL, isa.OP_MOV_NET, isa.OP_ADD, isa.OP_SUB,
          isa.OP_JRO, isa.OP_PUSH, isa.OP_OUT}
_K_GROUP_W = 8  # native/interpreter.cpp kGroupW
_K_PORTS = 4


def _tick_case1(lane: int, p: int, f) -> list[str]:
    """Pass-1 case (fetch + phase A + source resolution) for one baked
    instruction — mirrors group_tick pass 1 with every field a literal."""
    op, src = int(f[_F_OP]), int(f[_F_SRC])
    reads = op in _READS
    out = [f"        case {p}: {{"]
    if reads and src >= isa.SRC_R0:
        base = (lane * _K_PORTS + (src - isa.SRC_R0)) * _K_GROUP_W
        out += [
            "          if ((!kMasked || mask[r]) && !g.holding[i]) {",
            f"            const size_t pi = {base}u + r;",
            "            if (g.port_full[pi]) {",
            "              g.hold_val[i] = g.port_val[pi];",
            "              g.holding[i] = 1;",
            "              g.port_full[pi] = 0;",
            "              moved[r] = 1;",
            "            }",
            "          }",
        ]
    if not reads:
        val = "0"
    elif src == isa.SRC_IMM:
        val = f"(int64_t){int(f[_F_IMM])}LL"
    elif src == isa.SRC_ACC:
        val = "g.acc[i]"
    elif src == isa.SRC_NIL:
        val = "(int64_t)0"
    else:
        val = "(int64_t)g.hold_val[i]"
    ok = ("1" if (not reads or src < isa.SRC_R0)
          else "(uint8_t)(g.holding[i] != 0)")
    out += [
        f"          g.s_src_val[i] = {val};",
        f"          g.s_src_ok[i] = {ok};",
        "        } break;",
    ]
    return out


def _tick_case2(lane: int, p: int, f, ln: int, num_stacks: int,
                stack_cap: int, in_cap: int) -> list[str]:
    """Pass-2 case (arbitration + commit) for one baked instruction —
    mirrors group_tick pass 2; the pc successors are literals, so the
    modulo advance and the jump targets fold away entirely."""
    op, src = int(f[_F_OP]), int(f[_F_SRC])
    dst, tgt = int(f[_F_DST]), int(f[_F_TGT])
    nxt = (p + 1) % ln
    guarded = op in _READS and src >= isa.SRC_R0  # commit needs src_ok

    def tail(effects: list[str], pc: list[str] | None = None) -> list[str]:
        return [
            "moved[r] = 1;",
            *effects,
            *(pc if pc is not None else [f"g.pc[i] = {nxt};"]),
            "g.holding[i] = 0;",
            "g.retired[i] = i32((int64_t)g.retired[i] + 1);",
        ]

    if op == isa.OP_MOV_NET:
        pi = (tgt * _K_PORTS + int(f[_F_PORT])) * _K_GROUP_W
        body = [
            f"const size_t pi = {pi}u + r;",
            "if (!g.port_full[pi] && !g.s_deliv_full[pi]) {",
            "  g.s_deliv_full[pi] = 1;",
            "  g.s_deliv_val[pi] = i32(g.s_src_val[i]);",
            *("  " + s for s in tail([])),
            "}",
        ]
    elif op == isa.OP_PUSH:
        body = [
            f"const size_t si = {tgt * _K_GROUP_W}u + r;",
            f"if (!g.s_stack_taken[si] && g.s_begin_top[si] < {stack_cap}) {{",
            "  g.s_stack_taken[si] = 1;",
            "  g.s_pushed[si] = 1;",
            "  g.s_push_val[si] = i32(g.s_src_val[i]);",
            *("  " + s for s in tail([])),
            "}",
        ]
    elif op == isa.OP_POP:
        eff = []
        if dst == isa.DST_ACC:
            eff = [f"g.acc[i] = g.stack_mem[((size_t)r * {num_stacks} + "
                   f"{tgt}) * {stack_cap} + g.s_begin_top[si] - 1];"]
        body = [
            f"const size_t si = {tgt * _K_GROUP_W}u + r;",
            "if (!g.s_stack_taken[si] && g.s_begin_top[si] > 0) {",
            "  g.s_stack_taken[si] = 1;",
            *("  " + s for s in tail(eff)),
            "}",
        ]
    elif op == isa.OP_IN:
        eff = []
        if dst == isa.DST_ACC:
            eff = [f"g.acc[i] = g.in_buf[(size_t)r * {in_cap} + "
                   f"g.in_rd[r] % {in_cap}];"]
        body = [
            "if (io.in_avail[r] && !io.in_taken[r]) {",
            "  io.in_taken[r] = 1;",
            f"  io.in_win[r] = {lane};",
            *("  " + s for s in tail(eff)),
            "}",
        ]
    elif op == isa.OP_OUT:
        ok = "g.s_src_ok[i] && " if guarded else ""
        body = [
            f"if ({ok}io.out_free[r] && !io.out_taken[r]) {{",
            "  io.out_taken[r] = 1;",
            "  io.out_value[r] = i32(g.s_src_val[i]);",
            *("  " + s for s in tail([])),
            "}",
        ]
        guarded = False  # the guard is folded into the condition above
    elif op == isa.OP_JRO:
        mx = ln - 1
        body = tail(
            ["const int64_t v = g.s_src_val[i];",
             "const int64_t t = (v >= INT32_MIN && v <= INT32_MAX)",
             f"    ? (int64_t){p} + v : (v < 0 ? 0 : (int64_t){mx});"],
            [f"g.pc[i] = (int32_t)(t < 0 ? 0 : (t > {mx} ? {mx} : t));"],
        )
    elif op == isa.OP_JMP:
        body = tail([], [f"g.pc[i] = {int(f[_F_JMP])};"])
    elif op in (isa.OP_JEZ, isa.OP_JNZ, isa.OP_JGZ, isa.OP_JLZ):
        cond = {isa.OP_JEZ: "== 0", isa.OP_JNZ: "!= 0",
                isa.OP_JGZ: "> 0", isa.OP_JLZ: "< 0"}[op]
        body = tail(
            [], [f"g.pc[i] = g.acc[i] {cond} ? {int(f[_F_JMP])} : {nxt};"]
        )
    else:
        effects = {
            isa.OP_NOP: [],
            isa.OP_SWP: ["const int64_t oa = g.acc[i];",
                         "g.acc[i] = g.bak[i];",
                         "g.bak[i] = oa;"],
            isa.OP_SAV: ["g.bak[i] = g.acc[i];"],
            isa.OP_NEG: ["g.acc[i] = (int64_t)(0 - (uint64_t)g.acc[i]);"],
            isa.OP_ADD: ["g.acc[i] = (int64_t)((uint64_t)g.acc[i] + "
                         "(uint64_t)g.s_src_val[i]);"],
            isa.OP_SUB: ["g.acc[i] = (int64_t)((uint64_t)g.acc[i] - "
                         "(uint64_t)g.s_src_val[i]);"],
            isa.OP_MOV_LOCAL: (["g.acc[i] = g.s_src_val[i];"]
                               if dst == isa.DST_ACC else []),
        }[op]
        body = tail(effects)
    if guarded:
        body = ["if (g.s_src_ok[i]) {", *("  " + s for s in body), "}"]
    return [f"        case {p}: {{",
            *("          " + s for s in body),
            "        } break;"]


def _gen_tick(code: np.ndarray, prog_len: np.ndarray, num_stacks: int,
              stack_cap: int, in_cap: int) -> str | None:
    """The switch-threaded tick (header part 2): None when over the code
    budget — the build then keeps the table-baked generic tick."""
    n_lanes = code.shape[0]
    total = int(np.sum(prog_len))
    cap = _switch_cap()
    if cap <= 0 or total > cap:
        return None
    W = _K_GROUP_W
    lines = [
        "template <bool kMasked>",
        "MISAKA_AI bool misaka_spec_tick(Group& g, const uint8_t* mask) {",
        f"  constexpr int W = {W};",
        "  (void)mask;",
        "  uint8_t moved[W];",
        "  std::memset(moved, 0, sizeof(moved));",
        "  // pass 1 - fetch + phase A + source resolution (see group_tick)",
    ]
    for lane in range(n_lanes):
        ln = int(prog_len[lane])
        lines += [
            "  for (int r = 0; r < W; ++r) {",
            f"    const int i = {lane * W} + r;",
            "    switch (g.pc[i]) {",
        ]
        for p in range(ln):
            lines += _tick_case1(lane, p, code[lane, p])
        lines += [
            "      default: g.s_src_val[i] = 0; g.s_src_ok[i] = 1; break;",
            "    }",
            "  }",
        ]
    lines += [
        "  TickIO io;",
        "  tick_prologue<SpecSpec>(g, io);",
        "  // pass 2 - arbitration + commit (lane order = priority)",
    ]
    for lane in range(n_lanes):
        ln = int(prog_len[lane])
        lines += [
            "  for (int r = 0; r < W; ++r) {",
            "    if (kMasked && !mask[r]) continue;",
            f"    const int i = {lane * W} + r;",
            "    switch (g.pc[i]) {",
        ]
        for p in range(ln):
            lines += _tick_case2(lane, p, code[lane, p], ln, num_stacks,
                                 stack_cap, in_cap)
        lines += [
            "      default: break;",
            "    }",
            "  }",
        ]
    lines += [
        "  return tick_epilogue<SpecSpec, kMasked>(g, io, moved, mask);",
        "}",
    ]
    return "\n".join(lines) + "\n"


def _gen_header(code: np.ndarray, prog_len: np.ndarray, num_stacks: int,
                stack_cap: int, in_cap: int, out_cap: int, key: str) -> str:
    """The two-part specialization header.  Part 1 (default include, top of
    interpreter.cpp): the program tables + dimensions as constexpr data.
    Part 2 (MISAKA_SPEC_PART2, included after Group/TickIO/the pass
    helpers): the generated switch-threaded tick.  A part-2-less header
    (over budget) simply never defines MISAKA_SPEC_SWITCH and the build
    keeps the generic template tick against the baked tables."""
    n_lanes, max_len, nfields = code.shape
    flat = ",".join(str(int(v)) for v in code.reshape(-1))
    plen = ",".join(str(int(v)) for v in prog_len.reshape(-1))
    tick = _gen_tick(code, prog_len, num_stacks, stack_cap, in_cap)
    part1 = (
        "// auto-generated by misaka_tpu/core/specialize.py — do not edit\n"
        "#ifndef MISAKA_SPEC_PART2\n"
        "namespace spec {\n"
        f"constexpr int n_lanes = {n_lanes};\n"
        f"constexpr int max_len = {max_len};\n"
        f"constexpr int num_stacks = {num_stacks};\n"
        f"constexpr int stack_cap = {stack_cap};\n"
        f"constexpr int in_cap = {in_cap};\n"
        f"constexpr int out_cap = {out_cap};\n"
        f'constexpr char key[] = "{key}";\n'
        f"constexpr int32_t prog_len[] = {{{plen}}};\n"
        f"alignas(64) constexpr int32_t code[] = {{{flat}}};\n"
        "}\n"
        "#define MISAKA_SPEC 1\n"
    )
    if tick is None:
        return part1 + "#endif  // MISAKA_SPEC_PART2\n"
    return (
        part1
        + "#define MISAKA_SPEC_SWITCH 1\n"
        + "#else  // MISAKA_SPEC_PART2: the switch-threaded tick\n"
        + tick
        + "#endif  // MISAKA_SPEC_PART2\n"
    )


def build(net, cache_dir: str | None = None) -> str | None:
    """Compile (or reuse) the specialized interpreter .so for one compiled
    network.  Returns the .so path, or None on any failure / kill switch —
    the caller MUST treat None as "serve generic", never as an error."""
    if not enabled():
        M_SPECIALIZE.labels(status="disabled").inc()
        return None
    code = np.ascontiguousarray(np.asarray(net.code), np.int32)
    prog_len = np.ascontiguousarray(np.asarray(net.prog_len), np.int32)
    num_stacks = max(1, int(net.num_stacks))  # the pool's clamp, baked
    stack_cap = int(net.stack_cap)
    in_cap, out_cap = int(net.in_cap), int(net.out_cap)
    try:
        key = spec_key(code, prog_len, num_stacks, stack_cap, in_cap, out_cap)
    except OSError as e:  # interpreter source unreadable
        log.warning("specialize: cannot key build (%s); serving generic", e)
        M_SPECIALIZE.labels(status="error").inc()
        return None
    cache_dir = cache_dir or default_cache_dir()
    so_path = os.path.join(cache_dir, f"interp-spec-{key}.so")
    if os.path.exists(so_path):
        M_SPECIALIZE.labels(status="hit").inc()
        try:  # refresh the LRU clock so a hot entry never ages out
            os.utime(so_path)
        except OSError:
            pass
        return so_path
    try:
        # chaos (utils/faults.py): pin the graceful-fallback contract —
        # activation must succeed on the generic interpreter with zero
        # client-visible errors when the compile site fails
        if faults.fire("specialize_fail") is not None:
            raise RuntimeError("specialize_fail fault injected")
        os.makedirs(cache_dir, exist_ok=True)
        hdr = _gen_header(code, prog_len, num_stacks, stack_cap, in_cap,
                          out_cap, key)
        hdr_path = os.path.join(cache_dir, f"interp-spec-{key}.h")
        tmp_hdr = f"{hdr_path}.tmp.{os.getpid()}"
        with open(tmp_hdr, "w") as f:
            f.write(hdr)
        os.replace(tmp_hdr, hdr_path)
        cxx = os.environ.get("CXX", "g++")
        tmp_so = f"{so_path}.tmp.{os.getpid()}"
        try:
            subprocess.run(
                [
                    cxx, "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
                    "-fopenmp-simd",
                    f'-DMISAKA_SRC_HASH="{_src_hash()}"',
                    f'-DMISAKA_SPEC_HEADER="{hdr_path}"',
                    *_extra_flags(),
                    _SRC, "-o", tmp_so,
                ],
                check=True,
                capture_output=True,
                timeout=float(os.environ.get("MISAKA_SPEC_TIMEOUT_S", "") or 120),
            )
            os.replace(tmp_so, so_path)  # atomic: concurrent builds race benignly
        finally:
            if os.path.exists(tmp_so):
                os.unlink(tmp_so)
    except Exception as e:
        detail = ""
        if isinstance(e, subprocess.CalledProcessError) and e.stderr:
            detail = ": " + e.stderr.decode(errors="replace")[-400:]
        log.warning(
            "specialize: build failed (%s%s); serving generic", e, detail
        )
        M_SPECIALIZE.labels(status="error").inc()
        return None
    M_SPECIALIZE.labels(status="built").inc()
    log.info("specialize: built %s", so_path)
    _prune_cache(cache_dir, keep=so_path)
    return so_path


def _cache_bounds() -> tuple[int, int]:
    """(max_entries, max_bytes) for the on-disk cache.  The cache is keyed
    on content hashes, so without a bound it grows one .so (~100-300 KB)
    per distinct program version FOREVER across uploads."""
    entries = int(os.environ.get("MISAKA_SPEC_CACHE_MAX_ENTRIES", "") or 64)
    mb = float(os.environ.get("MISAKA_SPEC_CACHE_MAX_MB", "") or 256)
    return entries, int(mb * 1024 * 1024)


def _prune_cache(cache_dir: str, keep: str | None = None) -> None:
    """LRU-evict interp-spec-* entries beyond the size/entry bounds.  Best
    effort and crash-safe: eviction is an unlink (dlopen'd files survive
    it on Linux, and a concurrent loader that loses the race falls down
    the total graceful-fallback ladder).  The just-built `keep` entry is
    never evicted.  Hits refresh mtime, so mtime order IS the LRU order."""
    max_entries, max_bytes = _cache_bounds()
    entries = []
    try:
        for name in os.listdir(cache_dir):
            if not (name.startswith("interp-spec-") and name.endswith(".so")):
                continue
            path = os.path.join(cache_dir, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            entries.append((st.st_mtime, st.st_size, path))
    except OSError:
        return
    entries.sort()  # oldest first
    total = sum(e[1] for e in entries)
    count = len(entries)
    for mtime, size, path in entries:
        if count <= max_entries and total <= max_bytes:
            break
        if path == keep:
            continue
        try:
            os.unlink(path)
        except OSError:
            continue
        try:  # the generated header rides along with its .so
            os.unlink(path[:-3] + ".h")
        except OSError:
            pass
        M_CACHE_EVICT.inc()
        count -= 1
        total -= size
    G_CACHE_ENTRIES.set(count)
    G_CACHE_BYTES.set(total)
