"""Copy-and-patch JIT: the top rung of the native tick ladder (r21).

``native/stencils.cpp`` holds one parameterized machine-code fragment per
(instruction kind, pass) — semantically identical to the matching arm of
``group_tick`` in ``native/interpreter.cpp``.  This module compiles that
library ONCE per toolchain/source version (content-keyed ``.o`` in the
same on-disk cache ``core/specialize.py`` uses), parses the fragments and
their relocation tables straight out of the object file, and then — per
activated program — splices fragments per (lane, pc) into an executable
buffer, patching the parameter holes (plane bases, immediates, pc
successors, jump targets) as 64-bit immediates.  Activation cost is a few
dict lookups and ``memmove``s, not a C++ compile; steady-state ticks beat
the switch-threaded tier because dispatch, field reads, and pc advances
are all baked into straight-line code.

Ladder discipline (same contract as specialize.py):

* **Kill switch**: ``MISAKA_JIT=0`` disables the layer entirely.
* **Graceful fallback**: ANY failure — no toolchain, a relocation the
  splicer does not recognize (the self-containment check), mmap/mprotect
  (W^X) failure, ABI drift between interpreter.cpp and stencils.cpp —
  logs, counts on ``misaka_native_jit_total{status=...}``, and returns
  ``None``: the caller falls back ONE rung (switch-threaded / generic),
  never errors a serve.
* **Bit-identity**: fragments mirror ``group_tick`` arm-for-arm, pinned
  by tests/test_jit.py's differential corpus against the scalar, generic,
  avx2, and switch-threaded rungs.

W^X: the buffer is populated while PROT_READ|PROT_WRITE and flipped to
PROT_READ|PROT_EXEC before any pointer escapes — it is never writable and
executable at once.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import platform
import shutil
import struct
import subprocess
import threading

import numpy as np

from misaka_tpu.core import specialize
from misaka_tpu.tis import isa
from misaka_tpu.utils import faults
from misaka_tpu.utils import metrics

log = logging.getLogger("misaka.jit")

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SRC = os.path.join(_REPO_ROOT, "native", "stencils.cpp")

JIT_VERSION = 1  # bump to invalidate every cached stencil library

# Must match native/interpreter.cpp + native/stencils.cpp; the pool's arm
# call rejects a mismatch (rc -1) and the ladder falls back one rung.
MISAKA_JIT_ABI = 1

# The stencil compile contract: no PIC/GOT (holes become movabs imm64
# with R_X86_64_64 relocations), no jump tables / stack protector /
# unwind tables (nothing outside the fragment's own section), one section
# per fragment so splicing is a byte-range copy.
_CXXFLAGS = [
    "-O2", "-std=c++17", "-c", "-fno-pic", "-mcmodel=large",
    "-fno-jump-tables", "-fno-stack-protector", "-fno-exceptions",
    "-fno-rtti", "-fomit-frame-pointer", "-fno-asynchronous-unwind-tables",
    "-ffunction-sections", "-Wall", "-Wextra", "-Werror",
]

M_JIT = metrics.counter(
    "misaka_native_jit_total",
    "Copy-and-patch JIT outcomes (hit = cached stencil library reused, "
    "built = fresh stencil compile, spliced = program fragments patched "
    "into an executable buffer, armed = pool dispatching JIT ticks, "
    "error = any failure -> one rung down, disabled = kill switch or "
    "unsupported arch)",
    ("status",),
)
G_JIT_CODE_BYTES = metrics.gauge(
    "misaka_native_jit_code_bytes",
    "Executable bytes in the most recently spliced JIT program",
)
G_JIT_FRAGMENTS = metrics.gauge(
    "misaka_native_jit_fragments",
    "Distinct patched fragments in the most recently spliced JIT program "
    "(identical (stencil, params) fragments are shared across the table)",
)


def enabled() -> bool:
    """MISAKA_JIT kill switch (default on where supported)."""
    return os.environ.get("MISAKA_JIT", "1") not in ("0", "off")


def supported() -> bool:
    """Stencils are x86-64 machine code; every other arch falls back to
    the switch-threaded tier."""
    return platform.machine() in ("x86_64", "AMD64")


class JitError(RuntimeError):
    """Stencil library violates the self-containment contract."""


# --- stencil library: compile once, content-keyed ---------------------------

_src_hash_cache: str | None = None
_lib_lock = threading.Lock()
_lib_cache: dict[str, "StencilLibrary"] = {}


def _src_hash() -> str:
    global _src_hash_cache
    if _src_hash_cache is None:
        with open(_SRC, "rb") as f:
            _src_hash_cache = hashlib.sha256(f.read()).hexdigest()[:16]
    return _src_hash_cache


def stencil_key() -> str:
    """Content key for the compiled library: JIT version + stencil source
    + compile flags (a source or flag change rebuilds, old entries age out
    of the shared cache LRU)."""
    h = hashlib.sha256()
    h.update(f"jit{JIT_VERSION}:{_src_hash()}:".encode())
    h.update(" ".join(_CXXFLAGS).encode())
    return h.hexdigest()[:16]


def build_stencils(cache_dir: str | None = None) -> str | None:
    """Compile (or reuse) the stencil object file; None on any failure."""
    cache_dir = cache_dir or specialize.default_cache_dir()
    key = stencil_key()
    path = os.path.join(cache_dir, f"stencils-{key}.o")
    if os.path.exists(path):
        try:
            os.utime(path, None)  # LRU touch (shared cache prune)
        except OSError:
            pass
        M_JIT.labels(status="hit").inc()
        return path
    cxx = os.environ.get("CXX") or shutil.which("g++") or shutil.which("c++")
    if not cxx:
        log.warning("jit: no C++ toolchain; falling back one rung")
        M_JIT.labels(status="error").inc()
        return None
    try:
        os.makedirs(cache_dir, exist_ok=True)
        tmp = path + f".tmp.{os.getpid()}"
        timeout = float(os.environ.get("MISAKA_SPEC_TIMEOUT_S", "") or 120)
        proc = subprocess.run(
            [cxx, *_CXXFLAGS, _SRC, "-o", tmp],
            capture_output=True, timeout=timeout,
        )
        if proc.returncode != 0:
            log.warning("jit: stencil compile failed: %s",
                        proc.stderr.decode(errors="replace")[-500:])
            M_JIT.labels(status="error").inc()
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        os.replace(tmp, path)  # atomic: concurrent builders race benignly
    except Exception as exc:  # noqa: BLE001 - total fallback contract
        log.warning("jit: stencil build failed: %s", exc)
        M_JIT.labels(status="error").inc()
        return None
    M_JIT.labels(status="built").inc()
    return path


# --- ELF64 relocatable-object parsing ---------------------------------------

_SHT_SYMTAB = 2
_SHT_RELA = 4
_R_X86_64_64 = 1


class Stencil:
    """One fragment: its machine code and the (offset, hole, addend)
    patch sites inside it."""

    __slots__ = ("code", "holes")

    def __init__(self, code: bytes, holes: list[tuple[int, int, int]]):
        self.code = code
        self.holes = holes


def _cstr(buf: bytes, off: int) -> str:
    end = buf.index(b"\0", off)
    return buf[off:end].decode("ascii", errors="replace")


def _parse_stencils(path: str) -> dict[str, Stencil]:
    """Extract every ``misaka_st*`` fragment + its relocations from the
    object file.  Raises JitError on anything outside the contract — a
    truncated/corrupted file, a relocation that is not R_X86_64_64
    against a ``misaka_hole_K`` symbol (the fragment would reference
    memory the splicer cannot provide), or a hole outside the fragment."""
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < 64 or data[:4] != b"\x7fELF":
        raise JitError("not an ELF object")
    if data[4] != 2 or data[5] != 1:
        raise JitError("not a little-endian ELF64 object")
    (e_shoff,) = struct.unpack_from("<Q", data, 0x28)
    (e_shentsize, e_shnum, e_shstrndx) = struct.unpack_from("<HHH", data, 0x3A)
    if e_shentsize != 64 or e_shoff + e_shnum * 64 > len(data):
        raise JitError("truncated section table")

    def sh(i: int) -> tuple[int, int, int, int, int, int]:
        off = e_shoff + i * 64
        name, typ = struct.unpack_from("<II", data, off)
        s_off, size = struct.unpack_from("<QQ", data, off + 24)
        link, info = struct.unpack_from("<II", data, off + 40)
        return name, typ, s_off, size, link, info

    _, _, shstr_off, shstr_size, _, _ = sh(e_shstrndx)
    shstrtab = data[shstr_off:shstr_off + shstr_size]

    # symbol table -> (name, shndx, value, size) per symbol index
    symtab_idx = next(
        (i for i in range(e_shnum) if sh(i)[1] == _SHT_SYMTAB), None)
    if symtab_idx is None:
        raise JitError("no symbol table")
    _, _, sym_off, sym_size, sym_link, _ = sh(symtab_idx)
    _, _, str_off, str_size, _, _ = sh(sym_link)
    strtab = data[str_off:str_off + str_size]
    if sym_off + sym_size > len(data):
        raise JitError("truncated symbol table")
    syms = []
    for off in range(sym_off, sym_off + sym_size, 24):
        name_off, = struct.unpack_from("<I", data, off)
        shndx, = struct.unpack_from("<H", data, off + 6)
        value, size = struct.unpack_from("<QQ", data, off + 8)
        syms.append((_cstr(strtab, name_off) if name_off else "",
                     shndx, value, size))

    # fragment sections: one function per section (-ffunction-sections)
    frags: dict[int, tuple[str, int, int, int]] = {}  # shndx -> (name, ...)
    for name, shndx, value, size in syms:
        if not name.startswith("misaka_st") or shndx == 0:
            continue
        _, typ, s_off, s_size, _, _ = sh(shndx)
        if value + size > s_size or size == 0:
            raise JitError(f"fragment {name} outside its section")
        frags[shndx] = (name, s_off, value, size)

    out: dict[str, Stencil] = {}
    holes_by_sec: dict[int, list[tuple[int, int, int]]] = {}
    for i in range(e_shnum):
        _, typ, r_off, r_size, _, r_info = sh(i)
        if typ != _SHT_RELA or r_info not in frags:
            continue
        if r_off + r_size > len(data):
            raise JitError("truncated relocation table")
        sites = holes_by_sec.setdefault(r_info, [])
        for off in range(r_off, r_off + r_size, 24):
            rel_off, rel_info, addend = struct.unpack_from("<QQq", data, off)
            rtype = rel_info & 0xFFFFFFFF
            sym = syms[rel_info >> 32]
            if rtype != _R_X86_64_64 or not sym[0].startswith("misaka_hole_"):
                raise JitError(
                    f"{frags[r_info][0]}: unsupported relocation "
                    f"(type {rtype} against {sym[0] or '?'})")
            hole = int(sym[0][len("misaka_hole_"):])
            sites.append((rel_off, hole, addend))
    for shndx, (name, s_off, value, size) in frags.items():
        code = data[s_off + value:s_off + value + size]
        holes = []
        for rel_off, hole, addend in holes_by_sec.get(shndx, []):
            site = rel_off - value
            if site < 0 or site + 8 > size:
                raise JitError(f"{name}: relocation outside fragment")
            holes.append((site, hole, addend))
        out[name] = Stencil(code, holes)

    required = {
        "misaka_st1_port", "misaka_st1_imm", "misaka_st1_acc",
        "misaka_st1_zero", "misaka_st2_mov_net", "misaka_st2_push",
        "misaka_st2_pop_acc", "misaka_st2_pop_nil", "misaka_st2_in_acc",
        "misaka_st2_in_nil", "misaka_st2_out", "misaka_st2_jro",
        "misaka_st2_jmp", "misaka_st2_jez", "misaka_st2_jnz",
        "misaka_st2_jgz", "misaka_st2_jlz", "misaka_st2_mov_acc",
        "misaka_st2_none", "misaka_st2_add", "misaka_st2_sub",
        "misaka_st2_neg", "misaka_st2_swp", "misaka_st2_sav",
    }
    missing = required - out.keys()
    if missing:
        raise JitError(f"stencil library incomplete: missing {sorted(missing)}")
    return out


class StencilLibrary:
    def __init__(self, stencils: dict[str, Stencil]):
        self.stencils = stencils


def load_stencils(cache_dir: str | None = None) -> StencilLibrary | None:
    """Build-or-reuse + parse, with an in-process cache.  A corrupted
    cached object (truncated write, disk fault) is evicted and rebuilt
    once — robustness pinned by tests/test_jit.py."""
    key = stencil_key()
    with _lib_lock:
        lib = _lib_cache.get(key)
        if lib is not None:
            M_JIT.labels(status="hit").inc()
            return lib
        for attempt in range(2):
            path = build_stencils(cache_dir)
            if path is None:
                return None
            try:
                lib = StencilLibrary(_parse_stencils(path))
                break
            except JitError as exc:
                log.warning("jit: bad stencil library %s (%s); %s", path, exc,
                            "rebuilding" if attempt == 0 else "giving up")
                try:
                    os.unlink(path)
                except OSError:
                    pass
                if attempt == 1:
                    M_JIT.labels(status="error").inc()
                    return None
        _lib_cache[key] = lib
        return lib


# --- splice + patch ---------------------------------------------------------

_libc = ctypes.CDLL(None, use_errno=True)
_libc.mmap.restype = ctypes.c_void_p
_libc.mmap.argtypes = [ctypes.c_void_p, ctypes.c_size_t, ctypes.c_int,
                       ctypes.c_int, ctypes.c_int, ctypes.c_long]
_libc.mprotect.restype = ctypes.c_int
_libc.mprotect.argtypes = [ctypes.c_void_p, ctypes.c_size_t, ctypes.c_int]
_libc.munmap.restype = ctypes.c_int
_libc.munmap.argtypes = [ctypes.c_void_p, ctypes.c_size_t]

_PROT_READ, _PROT_WRITE, _PROT_EXEC = 1, 2, 4
_MAP_PRIVATE, _MAP_ANONYMOUS = 0x02, 0x20
_MAP_FAILED = ctypes.c_void_p(-1).value

_K_GROUP_W = 8  # native/interpreter.cpp kGroupW
_K_PORTS = 4
_F_OP, _F_SRC, _F_IMM, _F_DST, _F_TGT, _F_PORT, _F_JMP = range(7)
_READS = {isa.OP_MOV_LOCAL, isa.OP_MOV_NET, isa.OP_ADD, isa.OP_SUB,
          isa.OP_JRO, isa.OP_PUSH, isa.OP_OUT}


class JitProgram:
    """An executable buffer of patched fragments + the per-(lane, pc)
    dispatch tables the pool consumes.  Owns the mapping: keep this
    object alive while any pool is armed with it."""

    def __init__(self, addr: int, size: int, tab1, tab2, n_lanes: int,
                 max_len: int, fragments: int):
        self._addr = addr
        self._size = size
        self.tab1 = tab1  # ctypes (c_void_p * (n_lanes * max_len))
        self.tab2 = tab2
        self.n_lanes = n_lanes
        self.max_len = max_len
        self.fragments = fragments
        self.code_bytes = size
        self.abi = MISAKA_JIT_ABI

    def close(self) -> None:
        addr, self._addr = self._addr, 0
        if addr:
            _libc.munmap(ctypes.c_void_p(addr), self._size)

    def __del__(self):  # noqa: D105
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass


def _frag1(lane: int, f) -> tuple[str, tuple[int, ...]]:
    """(stencil, hole params) for one instruction's pass-1 fragment."""
    op, src = int(f[_F_OP]), int(f[_F_SRC])
    base = lane * _K_GROUP_W
    if op not in _READS or src == isa.SRC_NIL:
        return "misaka_st1_zero", (base,)
    if src >= isa.SRC_R0:
        pi = (lane * _K_PORTS + (src - isa.SRC_R0)) * _K_GROUP_W
        return "misaka_st1_port", (base, pi)
    if src == isa.SRC_IMM:
        return "misaka_st1_imm", (base, int(f[_F_IMM]))
    return "misaka_st1_acc", (base,)  # SRC_ACC


def _frag2(lane: int, p: int, f, ln: int, num_stacks: int, stack_cap: int,
           in_cap: int) -> tuple[str, tuple[int, ...]]:
    """(stencil, hole params) for one instruction's pass-2 fragment —
    parameter layout documented per stencil in native/stencils.cpp."""
    op = int(f[_F_OP])
    dst, tgt = int(f[_F_DST]), int(f[_F_TGT])
    base = lane * _K_GROUP_W
    nxt = (p + 1) % ln
    if op == isa.OP_MOV_NET:
        pi = (tgt * _K_PORTS + int(f[_F_PORT])) * _K_GROUP_W
        return "misaka_st2_mov_net", (base, pi, nxt)
    if op == isa.OP_PUSH:
        return "misaka_st2_push", (base, tgt * _K_GROUP_W, stack_cap, nxt)
    if op == isa.OP_POP:
        if dst == isa.DST_ACC:
            return "misaka_st2_pop_acc", (base, tgt * _K_GROUP_W,
                                          num_stacks * stack_cap,
                                          tgt * stack_cap, nxt)
        return "misaka_st2_pop_nil", (base, tgt * _K_GROUP_W, nxt)
    if op == isa.OP_IN:
        if dst == isa.DST_ACC:
            return "misaka_st2_in_acc", (base, lane, in_cap, nxt)
        return "misaka_st2_in_nil", (base, lane, nxt)
    if op == isa.OP_OUT:
        return "misaka_st2_out", (base, nxt)
    if op == isa.OP_JRO:
        return "misaka_st2_jro", (base, p, ln - 1)
    if op == isa.OP_JMP:
        return "misaka_st2_jmp", (base, int(f[_F_JMP]))
    cond = {isa.OP_JEZ: "misaka_st2_jez", isa.OP_JNZ: "misaka_st2_jnz",
            isa.OP_JGZ: "misaka_st2_jgz", isa.OP_JLZ: "misaka_st2_jlz"}
    if op in cond:
        return cond[op], (base, int(f[_F_JMP]), nxt)
    if op == isa.OP_MOV_LOCAL and dst == isa.DST_ACC:
        return "misaka_st2_mov_acc", (base, nxt)
    simple = {isa.OP_ADD: "misaka_st2_add", isa.OP_SUB: "misaka_st2_sub",
              isa.OP_NEG: "misaka_st2_neg", isa.OP_SWP: "misaka_st2_swp",
              isa.OP_SAV: "misaka_st2_sav"}
    return simple.get(op, "misaka_st2_none"), (base, nxt)


def _splice(lib: StencilLibrary, code: np.ndarray, prog_len: np.ndarray,
            num_stacks: int, stack_cap: int, in_cap: int) -> JitProgram:
    """Patch per-(lane, pc) fragments into one executable buffer and
    return the dispatch tables.  Identical (stencil, params) fragments
    are emitted once and shared (non-reading slots collapse hard)."""
    n_lanes, max_len = int(code.shape[0]), int(code.shape[1])
    plan1: list[tuple[str, tuple[int, ...]]] = []
    plan2: list[tuple[str, tuple[int, ...]]] = []
    for lane in range(n_lanes):
        ln = int(prog_len[lane])
        base = lane * _K_GROUP_W
        for p in range(max_len):
            if p < ln:
                plan1.append(_frag1(lane, code[lane, p]))
                plan2.append(_frag2(lane, p, code[lane, p], ln, num_stacks,
                                    stack_cap, in_cap))
            else:
                # unreachable slots (pc is validated < prog_len): benign
                # identity-adjacent fragments keep the table total
                plan1.append(("misaka_st1_zero", (base,)))
                plan2.append(("misaka_st2_none", (base, 0)))

    image = bytearray()
    offsets: dict[tuple[str, tuple[int, ...]], int] = {}
    for name, params in plan1 + plan2:
        if (name, params) in offsets:
            continue
        st = lib.stencils[name]
        pad = (-len(image)) % 16  # keep x86 fetch-friendly alignment
        image += b"\x90" * pad
        off = len(image)
        image += st.code
        for site, hole, addend in st.holes:
            if hole >= len(params):
                raise JitError(f"{name}: hole {hole} has no parameter")
            struct.pack_into("<q", image, off + site,
                             int(params[hole]) + addend)
        offsets[(name, params)] = off

    size = max(len(image), 1)
    addr = _libc.mmap(None, size, _PROT_READ | _PROT_WRITE,
                      _MAP_PRIVATE | _MAP_ANONYMOUS, -1, 0)
    if addr in (None, 0, _MAP_FAILED):
        raise JitError(f"mmap failed (errno {ctypes.get_errno()})")
    try:
        ctypes.memmove(addr, bytes(image), len(image))
        if _libc.mprotect(ctypes.c_void_p(addr), size,
                          _PROT_READ | _PROT_EXEC) != 0:
            raise JitError(f"mprotect failed (errno {ctypes.get_errno()})")
    except Exception:
        _libc.munmap(ctypes.c_void_p(addr), size)
        raise
    n = n_lanes * max_len
    tab1 = (ctypes.c_void_p * n)(*(addr + offsets[k] for k in plan1))
    tab2 = (ctypes.c_void_p * n)(*(addr + offsets[k] for k in plan2))
    return JitProgram(addr, size, tab1, tab2, n_lanes, max_len,
                      len(offsets))


def prepare(net, cache_dir: str | None = None) -> JitProgram | None:
    """Build the JIT program for one network: stencil library (cached) +
    splice/patch.  None on ANY failure — the caller serves one rung down
    (switch-threaded / generic); it never raises."""
    if not enabled() or not supported():
        M_JIT.labels(status="disabled").inc()
        return None
    try:
        if faults.fire("jit_fail") is not None:
            raise JitError("jit_fail chaos fault")
        lib = load_stencils(cache_dir)
        if lib is None:
            return None
        code = np.ascontiguousarray(net.code, np.int32)
        prog_len = np.ascontiguousarray(net.prog_len, np.int32)
        if np.any(prog_len <= 0):
            raise JitError("program with an empty lane")
        prog = _splice(lib, code, prog_len, max(1, int(net.num_stacks)),
                       int(net.stack_cap), int(net.in_cap),
                       )
        M_JIT.labels(status="spliced").inc()
        G_JIT_CODE_BYTES.set(prog.code_bytes)
        G_JIT_FRAGMENTS.set(prog.fragments)
        return prog
    except Exception as exc:  # noqa: BLE001 - total fallback contract
        log.warning("jit: prepare failed (%s); falling back one rung", exc)
        M_JIT.labels(status="error").inc()
        return None
