"""Superstep kernel and execution engine."""

from misaka_tpu.core.state import NetworkState, init_state
from misaka_tpu.core.step import step
from misaka_tpu.core.engine import CompiledNetwork
from misaka_tpu.core.trace import TraceRing, init_trace, traced_step

__all__ = [
    "NetworkState",
    "init_state",
    "step",
    "CompiledNetwork",
    "TraceRing",
    "init_trace",
    "traced_step",
]
