"""Superstep kernel and execution engine."""

from misaka_tpu.core.state import NetworkState, init_state
from misaka_tpu.core.step import step
from misaka_tpu.core.engine import CompiledNetwork

__all__ = ["NetworkState", "init_state", "step", "CompiledNetwork"]
