"""Synthetic canaries: an always-on prober through every serving tier.

The health plane so far is PASSIVE — it reports what real traffic did.
An idle (or silently broken) stack therefore reads as healthy right up
to the first customer request that fails.  This module closes that gap
the way production serving stacks do: a background prober drives a
pinned known-answer program through the FULL public path on a low cadence
(``MISAKA_CANARY_INTERVAL_S``, default 5 s), plus one shallow probe per
tier underneath, so a failure is attributed to the FIRST failing tier
instead of "the canary failed somewhere":

  edge    GET /healthz through the public HTTP listener (TLS + edge
          chain included) — the load balancer's view of the door.
  plane   one zero-value probe frame over the engine's unix-socket
          compute plane (the fleet router's own probe shape, handshake
          included) — skipped ("off") when no plane is serving.
  engine  a direct compute on the canary program's engine through a
          registry lease — the ServeBatcher + device loop with no HTTP
          or plane in front.
  full    POST /programs/_canary/compute_raw through the public
          listener: edge auth -> (frontend plane) -> ServeBatcher ->
          engine, output checked against the known answer.

Attribution: if ``full`` fails while edge/plane/engine all pass, the
fault is in the serving path between them (frontend routing or the
batcher) and is reported as tier ``serve``.

The canary program (``_canary``, a three-instruction ADD network) is
published into the registry on first use and serves from its own
per-program engine like any tenant — deliberately, because that is the
path being proven.  It is NOT pinned against LRU eviction: when capacity
pressure evicts it, the next probe reactivates it through the durable
checkpoint path, which keeps THAT machinery continuously exercised too.

Exclusion contract (test-pinned): canary traffic is tagged by its
program name ``_canary`` —

  * the usage ledger books it under the ``_canary`` account (exempt from
    the cardinality collapse; runtime/usage.py), so no real tenant is
    ever billed for probe traffic and billing exports can drop the
    account wholesale;
  * the SLO engine ignores it outright (utils/slo.py observe()): a
    deliberately slow canary drill must not burn a tenant's error
    budget, and canary failures already page through the watchdog.

Surfaces: ``misaka_canary_success{tier=...}`` (1/0 per probe),
``misaka_canary_latency_seconds{tier=...}`` histograms (the TSDB derives
p50/p99 history), a ``canary`` block on ``/healthz``, the dashboard's
canary panel, and the watchdog's default ``canary-full`` page rule.

Armed from the real serving entrypoints (runtime/app.py, the fleet
parent) — NOT from bare make_http_server, because tests build dozens of
servers per process and a process-global prober aimed at a dead port
would poison them all.  ``MISAKA_CANARY=0`` is the kill switch.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import socket
import ssl
import struct
import threading
import time
import weakref

from misaka_tpu.utils import metrics

log = logging.getLogger("misaka_tpu.canary")

PROGRAM = "_canary"
# The pinned known-answer source: out = in + 7.  Tiny on purpose — the
# canary engine must cost one small program slot, not a workload.
SOURCE = "IN ACC\nADD 7\nOUT ACC\n"
DELTA = 7
DEFAULT_INTERVAL_S = 5.0

TIERS = ("edge", "plane", "engine", "full")

M_PROBES = metrics.counter(
    "misaka_canary_probes_total", "Canary probes attempted, by tier",
    ("tier",),
)
M_FAILURES = metrics.counter(
    "misaka_canary_failures_total", "Canary probes that failed, by tier",
    ("tier",),
)
M_SUCCESS = metrics.gauge(
    "misaka_canary_success",
    "Last canary probe outcome by tier (1 ok / 0 failed; absent = tier "
    "not probed in this process)",
    ("tier",),
)
M_LATENCY = metrics.histogram(
    "misaka_canary_latency_seconds", "Canary probe latency by tier",
    ("tier",),
)


class CanaryProber:
    """The probing thread + last-cycle state."""

    def __init__(self, base_url: str, registry=None, server=None,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 token: str | None = None, program: str = PROGRAM,
                 values: int = 4, full_stack: bool | None = None,
                 probe_timeout_s: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.interval_s = max(0.05, float(interval_s))
        self.program = program
        self.token = token
        self.values = max(1, int(values))
        self.probe_timeout_s = max(0.1, float(probe_timeout_s))
        self._registry = registry
        # drive the full public stack?  Default: only when a registry is
        # in-process.  The fleet parent has none (the registries live in
        # the replicas) and passes True, registering the program over
        # HTTP instead — see _ensure_program.
        self._full_stack = (
            full_stack if full_stack is not None else registry is not None
        )
        # the serving HTTP server (weakly held: the canary must never
        # keep a dead server alive) — read each cycle for misaka_plane,
        # which app.py attaches AFTER make_http_server returns
        self._server = weakref.ref(server) if server is not None else None
        self._registered = False
        self._lock = threading.Lock()
        self._tiers: dict[str, dict] = {}
        self._failing_tier: str | None = None
        self._consecutive_full_failures = 0
        self._cycles = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        u = self.base_url
        self._tls = u.startswith("https:")
        hostport = u.split("://", 1)[-1]
        host, _, port = hostport.partition(":")
        self._host = host or "127.0.0.1"
        self._port = int(port or (443 if self._tls else 80))

    # --- lifecycle ----------------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> None:
        if self.running:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="misaka-canary"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.probe_once()
            except Exception:  # pragma: no cover — the prober must
                log.exception("canary cycle crashed")  # never take
                pass                                   # serving down

    # --- probe plumbing -----------------------------------------------------

    def _conn(self, timeout: float) -> http.client.HTTPConnection:
        if self._tls:
            # loopback self-probe: the serving cert is routinely
            # self-signed and names the public host, neither of which a
            # localhost probe can verify — transport only, no authn
            return http.client.HTTPSConnection(
                self._host, self._port, timeout=timeout,
                context=ssl._create_unverified_context(),
            )
        return http.client.HTTPConnection(
            self._host, self._port, timeout=timeout
        )

    def _headers(self) -> dict:
        return {"X-Misaka-Key": self.token} if self.token else {}

    def _record(self, tier: str, ok: bool, dur_s: float,
                error: str | None = None) -> None:
        M_PROBES.labels(tier=tier).inc()
        M_SUCCESS.labels(tier=tier).set(1.0 if ok else 0.0)
        M_LATENCY.labels(tier=tier).observe(dur_s)
        if not ok:
            M_FAILURES.labels(tier=tier).inc()
        row = {
            "ok": ok,
            "latency_ms": round(dur_s * 1e3, 3),
            "last_unix": round(time.time(), 3),
        }
        if error:
            row["error"] = error[:300]
        with self._lock:
            self._tiers[tier] = row

    def _mark_off(self, tier: str, reason: str) -> None:
        with self._lock:
            self._tiers[tier] = {"ok": None, "off": reason}

    # --- the tiers ----------------------------------------------------------

    def _probe_edge(self) -> bool:
        t0 = time.monotonic()
        try:
            conn = self._conn(timeout=5.0)
            try:
                conn.request("GET", "/healthz", headers=self._headers())
                resp = conn.getresponse()
                resp.read()
                ok = resp.status == 200
                err = None if ok else f"status {resp.status}"
            finally:
                conn.close()
        except (OSError, http.client.HTTPException) as e:
            ok, err = False, repr(e)
        self._record("edge", ok, time.monotonic() - t0, err)
        return ok

    def _plane_path(self) -> str | None:
        server = self._server() if self._server is not None else None
        plane = getattr(server, "misaka_plane", None) if server else None
        if plane is not None and not getattr(plane, "_closed", False):
            return plane.path
        return None

    def _probe_plane(self) -> bool | None:
        """None = no plane serving in this process (tier off)."""
        path = self._plane_path()
        if path is None:
            self._mark_off("plane", "no compute plane in this process")
            return None
        from misaka_tpu.runtime import edge as edge_mod
        from misaka_tpu.runtime.frontends import (
            _recv_exact, _REQ_HDR, _RESP_HDR, PLANE_DRAINING,
        )

        t0 = time.monotonic()
        ok, err = False, None
        try:
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(2.0)
            try:
                sock.connect(path)
                secret = edge_mod.plane_secret()
                if secret is not None:
                    sock.sendall(edge_mod.plane_handshake(secret))
                meta = b'{"probe": 1}'
                sock.sendall(_REQ_HDR.pack(0, len(meta)) + meta)
                status, length = _RESP_HDR.unpack(_recv_exact(sock, 8))
                if length:
                    _recv_exact(sock, length)
                ok = status in (200, PLANE_DRAINING)
                err = None if ok else f"plane status {status}"
            finally:
                sock.close()
        except (OSError, struct.error) as e:
            ok, err = False, repr(e)
        self._record("plane", ok, time.monotonic() - t0, err)
        return ok

    def _ensure_program(self) -> bool:
        """Publish the known-answer program once — through the registry
        when one is in-process, over the public POST /programs surface
        otherwise (the fleet parent: the upload fans out to every
        replica).  Non-fatal: a busy registry retries next cycle."""
        if self._registered:
            return True
        if self._registry is not None:
            try:
                listing = self._registry.list_programs()["programs"]
                if self.program not in listing:
                    self._registry.publish(self.program, tis=SOURCE)
                self._registered = True
            except Exception as e:
                log.warning("canary: cannot register %s yet: %s",
                            self.program, e)
            return self._registered
        if not self._full_stack:
            return False
        try:
            from urllib.parse import urlencode

            body = urlencode(
                {"name": self.program, "program": SOURCE}
            ).encode()
            conn = self._conn(timeout=10.0)
            try:
                conn.request(
                    "POST", "/programs", body, headers={
                        **self._headers(),
                        "Content-Type":
                            "application/x-www-form-urlencoded",
                    },
                )
                resp = conn.getresponse()
                resp.read()
                self._registered = resp.status == 200
                if not self._registered:
                    log.warning(
                        "canary: POST /programs for %s answered %d",
                        self.program, resp.status,
                    )
            finally:
                conn.close()
        except (OSError, http.client.HTTPException) as e:
            log.warning("canary: cannot register %s yet: %s",
                        self.program, e)
        return self._registered

    def _probe_engine(self) -> bool | None:
        """Direct compute through the canary program's engine lease —
        no HTTP, no plane.  None when no registry is armed (the
        exclusion contract needs the _canary tenant to bill to)."""
        if self._registry is None:
            self._mark_off("engine", "no program registry in this process")
            return None
        if not self._ensure_program():
            self._mark_off("engine", "canary program not registered yet")
            return None
        vals = list(range(1, self.values + 1))
        t0 = time.monotonic()
        ok, err = False, None
        try:
            with self._registry.lease(self.program, values=len(vals)) as m:
                out = m.compute_many(vals, timeout=self.probe_timeout_s)
            got = [int(v) for v in out]
            want = [v + DELTA for v in vals]
            ok = got == want
            err = None if ok else f"answer {got} != {want}"
        except Exception as e:
            ok, err = False, repr(e)
        self._record("engine", ok, time.monotonic() - t0, err)
        return ok

    def _probe_full(self) -> bool | None:
        """The whole public stack: POST /programs/_canary/compute_raw."""
        if not self._full_stack:
            self._mark_off("full", "no program registry behind this surface")
            return None
        if not self._ensure_program():
            self._mark_off("full", "canary program not registered yet")
            return None
        vals = list(range(1, self.values + 1))
        body = b"".join(struct.pack("<i", v) for v in vals)
        t0 = time.monotonic()
        ok, err = False, None
        try:
            conn = self._conn(timeout=self.probe_timeout_s)
            try:
                conn.request(
                    "POST",
                    f"/programs/{self.program}/compute_raw?spread=1",
                    body, headers=self._headers(),
                )
                resp = conn.getresponse()
                raw = resp.read()
                if resp.status != 200:
                    err = f"status {resp.status}: {raw[:120]!r}"
                else:
                    got = [
                        struct.unpack_from("<i", raw, i * 4)[0]
                        for i in range(len(raw) // 4)
                    ]
                    want = [v + DELTA for v in vals]
                    ok = got == want
                    err = None if ok else f"answer {got} != {want}"
            finally:
                conn.close()
        except (OSError, http.client.HTTPException, struct.error) as e:
            ok, err = False, repr(e)
        self._record("full", ok, time.monotonic() - t0, err)
        return ok

    # --- one cycle ----------------------------------------------------------

    def probe_once(self) -> dict:
        """All tiers, shallow to deep; returns state() (tests call this
        directly for deterministic cadence)."""
        edge_ok = self._probe_edge()
        plane_ok = self._probe_plane()
        engine_ok = self._probe_engine()
        full_ok = self._probe_full()
        failing = None
        if edge_ok is False:
            failing = "edge"
        elif plane_ok is False:
            failing = "plane"
        elif engine_ok is False:
            failing = "engine"
        elif full_ok is False:
            # every tier underneath passed: the fault is the serving
            # path between them (frontend routing / the batcher)
            failing = "serve"
        with self._lock:
            self._cycles += 1
            if full_ok is False:
                self._consecutive_full_failures += 1
            elif full_ok:
                self._consecutive_full_failures = 0
            self._failing_tier = failing
        return self.state()

    def state(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "running": self.running,
                "interval_s": self.interval_s,
                "program": self.program,
                "cycles": self._cycles,
                "failing_tier": self._failing_tier,
                "consecutive_full_failures":
                    self._consecutive_full_failures,
                "tiers": {t: dict(v) for t, v in self._tiers.items()},
            }


# --- the process-global instance --------------------------------------------

_lock = threading.Lock()
_canary: CanaryProber | None = None


def enabled(environ=os.environ) -> bool:
    return environ.get("MISAKA_CANARY", "1") != "0"


def get() -> CanaryProber | None:
    return _canary


def ensure_started(base_url: str, registry=None, server=None,
                   token: str | None = None, full_stack: bool | None = None,
                   environ=os.environ) -> CanaryProber | None:
    """Start the process canary against `base_url` — called by the real
    serving entrypoints (runtime/app.py, the fleet parent), never by
    bare make_http_server (see the module docstring).  None when
    MISAKA_CANARY=0."""
    global _canary
    if not enabled(environ):
        return None
    with _lock:
        if _canary is None:
            try:
                interval = float(
                    environ.get("MISAKA_CANARY_INTERVAL_S", "")
                    or DEFAULT_INTERVAL_S
                )
            except ValueError:
                interval = DEFAULT_INTERVAL_S
            _canary = CanaryProber(
                base_url, registry=registry, server=server,
                interval_s=interval, full_stack=full_stack,
                token=token or environ.get("MISAKA_EDGE_INTERNAL_TOKEN")
                or None,
            )
        if not _canary.running:
            _canary.start()
    return _canary


def shutdown() -> None:
    """Stop and drop the process canary (tests; the A/B's off side)."""
    global _canary
    with _lock:
        if _canary is not None:
            _canary.stop()
            _canary = None


def state_payload() -> dict | None:
    """The `canary` block on /healthz (None when no prober runs)."""
    c = _canary
    return c.state() if c is not None else None
