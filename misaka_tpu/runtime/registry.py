"""Program registry: multi-tenant serving of versioned TIS networks.

The reference's whole "model management" surface was one mutable slot:
``POST /load`` reprogrammed THE running network in place (master.go:145-195)
— the primordial form of a model registry.  Production serving means many
networks loaded, versioned, and routed concurrently; this module is that
control-plane layer, the multi-model inference-server pattern grown over
the substrate PRs 3-5 built:

  * **upload & version**: programs arrive as TIS source, topology JSON, or
    a reference docker-compose file; each upload is compiled FIRST (a
    parse error can never touch a serving engine), canonicalized, and
    content-addressed — the version ID is sha256 of the canonical source,
    so identical uploads dedup to one version.  ``name@<version>``
    addresses an exact version; the mutable ``name@latest`` alias (and
    bare ``name``) follows publishes.
  * **per-program engines**: each *active* program version owns a full
    MasterNode — its own device loop / native pool and its own
    ServeBatcher, so cross-request coalescing stays strictly per-program.
    Activation is lazy (first compute), warmed before serving.
  * **LRU eviction**: MISAKA_REGISTRY_MAX_ACTIVE caps live engines; the
    coldest idle program is drained and checkpointed through the durable
    save_checkpoint path (manifest + atomic replace, runtime/master.py),
    so re-activation restores its state bit-identically via the
    verify_checkpoint gate.
  * **hot-swap**: publishing a new version under a live engine builds and
    WARMS the replacement first, then parks alias-addressed requests for
    the brief flip window, installs the new engine, and lets in-flight
    requests drain on the old one before it is checkpointed and closed —
    zero client-visible errors under sustained load (the chaos scenario
    ``swap_during_load`` widens the park window to prove it).

Addressing rides everywhere a request travels: HTTP routes
(``POST /programs/<name>/compute*``, ``X-Misaka-Program`` on the legacy
routes), compute-plane frame metadata (runtime/frontends.py), the
``program`` label on the registry metric series below (with a cardinality
guard — an unauthenticated upload must not mint unbounded label values),
and the ``program`` attr on ``serve.pass`` trace spans.

The persistent store (``MISAKA_PROGRAMS_DIR``) survives restarts::

    <dir>/<name>/versions/<version>.json   canonical source + metadata
    <dir>/<name>/aliases.json              {"latest": "<version>"}
    <dir>/<name>/state-<version>.npz       eviction checkpoint (+ .manifest)

Tests construct ``ProgramRegistry(None)``: sources then live in memory
and eviction checkpoints in a registry-owned temporary directory.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import os
import re
import threading
import time

from misaka_tpu.runtime import edge as edge_mod
from misaka_tpu.runtime import usage
from misaka_tpu.runtime.topology import Topology
from misaka_tpu.utils import faults
from misaka_tpu.utils import metrics
from misaka_tpu.utils import slo

log = logging.getLogger("misaka_tpu.registry")

# Program names share the checkpoint-name discipline (make_http_server):
# an unauthenticated form field must never choose server-side paths.
NAME_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")
VERSION_LEN = 12  # hex chars of sha256 — plenty against accident, short on the wire

# --- the metrics plane ------------------------------------------------------
# Registry series carry a `program` label; _program_label below caps the
# distinct values (MISAKA_REGISTRY_LABEL_MAX, default 64) so an upload
# flood collapses to program="other" instead of minting unbounded series.
M_PROG_REQS = metrics.counter(
    "misaka_program_requests_total",
    "Compute requests routed through the program registry, by program",
    ("program",),
)
M_PROG_VALUES = metrics.counter(
    "misaka_program_values_total",
    "Values routed through the program registry, by program",
    ("program",),
)
M_PROG_ACTIVE = metrics.gauge(
    "misaka_program_active_engines",
    "Per-program engine instances currently active (live registry)",
)
M_PROG_UPLOADS = metrics.counter(
    "misaka_program_uploads_total",
    "Program uploads accepted (deduped uploads count too)",
)
M_PROG_ACTIVATIONS = metrics.counter(
    "misaka_program_activations_total",
    "Engine activations (cold start or checkpoint revival), by program",
    ("program",),
)
M_PROG_EVICTIONS = metrics.counter(
    "misaka_program_evictions_total",
    "Engines drained + checkpointed out of the active set, by program",
    ("program",),
)
M_PROG_SWAPS = metrics.counter(
    "misaka_program_swaps_total",
    "Live hot-swaps completed (new version published under traffic), "
    "by program",
    ("program",),
)

_label_lock = threading.Lock()
_label_seen: set[str] = set()


def _program_label(name: str) -> str:
    """`name`, or "other" once the label-cardinality budget is spent."""
    with _label_lock:
        if name in _label_seen:
            return name
        cap = int(os.environ.get("MISAKA_REGISTRY_LABEL_MAX", "") or 64)
        if len(_label_seen) < cap:
            _label_seen.add(name)
            return name
    return "other"


class RegistryError(ValueError):
    """A registry operation the caller got wrong (bad name, bad source,
    publishing over the seeded boot program)."""


class ReplayDivergence(RegistryError):
    """A ``?verify=replay`` publish whose candidate answered captured
    traffic differently than the recorded responses — the hot-swap was
    refused (deploy-didn't-happen).  ``.diffs`` carries the per-request
    diff dicts (trace ID, record offset, expected/actual heads) the HTTP
    surface renders as the 409 body."""

    def __init__(self, message: str, diffs: list | None = None):
        super().__init__(message)
        self.diffs = diffs or []


class ProgramNotFound(KeyError):
    """An unknown program name or version — the typed 404.

    A KeyError subclass so the jax-free compute plane
    (runtime/frontends.py) can answer it as 404 without importing this
    (jax-adjacent) module."""

    def __str__(self) -> str:  # KeyError str() quotes its arg; keep prose
        return self.args[0] if self.args else "program not found"


def canonical_topology(topology: Topology) -> str:
    """The canonicalized source text the content address is taken over:
    one sorted-key JSON form, so the same network uploaded as TIS source,
    topology JSON (any key order), or compose YAML dedups to one ID."""
    return json.dumps(
        {
            "nodes": dict(topology.node_info),
            "programs": dict(topology.programs),
            "stack_cap": topology.stack_cap,
            "in_cap": topology.in_cap,
            "out_cap": topology.out_cap,
        },
        sort_keys=True,
        separators=(",", ":"),
    )


def version_of(canonical: str) -> str:
    return hashlib.sha256(canonical.encode()).hexdigest()[:VERSION_LEN]


def topology_from_canonical(canonical: str) -> Topology:
    raw = json.loads(canonical)
    return Topology(
        node_info=raw["nodes"],
        programs=raw["programs"],
        stack_cap=int(raw["stack_cap"]),
        in_cap=int(raw["in_cap"]),
        out_cap=int(raw["out_cap"]),
    )


class _Engine:
    """One active program version's serving state.

    ``ready`` latches once ``master`` is installed (or ``error`` set);
    ``leases`` counts requests currently inside the engine; ``retired``
    marks an engine removed from the active set whose last lease-holder
    must close it (a hot-swap drain that outlived its timeout)."""

    __slots__ = ("master", "leases", "ready", "error", "retired", "closed")

    def __init__(self, master=None):
        self.master = master
        self.leases = 0
        self.ready = threading.Event()
        if master is not None:
            self.ready.set()
        self.error: BaseException | None = None
        self.retired = False
        self.closed = False


class _Entry:
    """One program name: its uploaded versions + the mutable alias map."""

    __slots__ = ("versions", "aliases", "pinned")

    def __init__(self):
        self.versions: dict[str, dict] = {}   # version -> metadata
        self.aliases: dict[str, str] = {}     # "latest" -> version
        self.pinned = False                   # the seeded boot program


class ProgramRegistry:
    """Versioned multi-program serving over per-program MasterNode engines.

    One registry per serving process.  Thread-safe throughout: one
    condition guards the bookkeeping (entries, engines, LRU, swap/publish
    gates); engine builds, checkpoint saves, and warm-ups all run off the
    lock so one program's multi-second compile never stalls another
    program's traffic.
    """

    def __init__(
        self,
        programs_dir: str | None = None,
        *,
        batch: int | None = None,
        engine: str = "auto",
        chunk_steps: int = 128,
        max_active: int | None = None,
        caps: dict | None = None,
        drain_timeout_s: float | None = None,
    ):
        self._dir = programs_dir
        self._tmpdir = None
        if programs_dir is None:
            import tempfile

            self._tmpdir = tempfile.TemporaryDirectory(prefix="misaka-registry-")
            self._dir = self._tmpdir.name
        self._batch = batch
        self._engine = engine
        self._chunk = int(chunk_steps)
        self._caps = dict(caps or {})
        if max_active is None:
            max_active = int(
                os.environ.get("MISAKA_REGISTRY_MAX_ACTIVE", "") or 4
            )
        self._max_active = max(1, int(max_active))
        if drain_timeout_s is None:
            drain_timeout_s = float(
                os.environ.get("MISAKA_SWAP_DRAIN_S", "") or 30.0
            )
        self._drain_s = float(drain_timeout_s)
        self._cond = threading.Condition()
        self._entries: dict[str, _Entry] = {}
        self._engines: dict[tuple[str, str], _Engine] = {}
        self._lru: dict[tuple[str, str], float] = {}
        self._swapping: set[str] = set()
        self._publishing: set[str] = set()
        # keys mid-deactivation: their drain checkpoint is being written
        # OFF-lock, and a concurrent re-activation must wait for it (or
        # it would build a fresh engine against a stale/absent snapshot)
        self._evicting: set[tuple[str, str]] = set()
        self._default: str | None = None
        self._closed = False
        if programs_dir is not None:
            self._load_store()
        import weakref

        ref = weakref.ref(self)
        M_PROG_ACTIVE.set_function(
            lambda: len(r._engines) if (r := ref()) is not None else 0
        )

    # --- persistence --------------------------------------------------------

    def _name_dir(self, name: str) -> str:
        return os.path.join(self._dir, name)

    def _version_path(self, name: str, version: str) -> str:
        return os.path.join(self._name_dir(name), "versions", f"{version}.json")

    def _alias_path(self, name: str) -> str:
        return os.path.join(self._name_dir(name), "aliases.json")

    def _state_path(self, name: str, version: str) -> str:
        return os.path.join(self._name_dir(name), f"state-{version}.npz")

    def _load_store(self) -> None:
        """Boot: re-register every persisted program (nothing activates)."""
        try:
            names = sorted(os.listdir(self._dir))
        except OSError:
            return
        for name in names:
            if not NAME_RE.match(name):
                continue
            vdir = os.path.join(self._name_dir(name), "versions")
            try:
                vfiles = sorted(os.listdir(vdir))
            except OSError:
                continue
            entry = _Entry()
            for vf in vfiles:
                if not vf.endswith(".json"):
                    continue
                try:
                    with open(os.path.join(vdir, vf)) as f:
                        meta = json.load(f)
                    version = vf[: -len(".json")]
                    if version_of(meta["source"]) != version:
                        raise ValueError("content address mismatch")
                    entry.versions[version] = meta
                except (OSError, ValueError, KeyError) as e:
                    log.warning(
                        "registry: skipping corrupt version file %s/%s (%s)",
                        name, vf, e,
                    )
            if not entry.versions:
                continue
            try:
                with open(self._alias_path(name)) as f:
                    aliases = json.load(f)
                if aliases.get("latest") in entry.versions:
                    entry.aliases = {"latest": aliases["latest"]}
            except (OSError, ValueError):
                pass
            if "latest" not in entry.aliases:
                # fall back to the newest upload on record
                entry.aliases["latest"] = max(
                    entry.versions,
                    key=lambda v: entry.versions[v].get("created_unix", 0),
                )
            self._entries[name] = entry
            spec = entry.versions[entry.aliases["latest"]].get("slo")
            if spec:
                try:  # the latest version's objectives survive restarts
                    slo.set_objectives(name, spec)
                except slo.SLOSpecError:
                    log.warning(
                        "registry: ignoring corrupt slo spec on %s@%s",
                        name, entry.aliases["latest"],
                    )
            # NOTE: persisted per-program quota overrides are installed by
            # install_quotas() when make_http_server builds the process
            # chain — the registry boots BEFORE any chain exists, and a
            # write to edge.current() here would land on the disarmed
            # placeholder (or a previous server's chain)
            log.info(
                "registry: loaded program %s (%d version(s), latest %s)",
                name, len(entry.versions), entry.aliases["latest"],
            )

    def _persist_version(
        self, name: str, version: str, meta: dict, overwrite: bool = False
    ) -> None:
        path = self._version_path(name, version)
        if os.path.exists(path) and not overwrite:
            return  # content-addressed: identical by construction
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, path)

    def _persist_aliases(self, name: str, aliases: dict) -> None:
        path = self._alias_path(name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(aliases, f)
        os.replace(tmp, path)

    # --- source parsing -----------------------------------------------------

    def parse_source(
        self,
        *,
        tis: str | None = None,
        topology_json: str | None = None,
        compose: str | None = None,
    ) -> Topology:
        """One uploaded source body -> a Topology (exactly one form given).

        TIS source wraps into a single-node network (node "main") so a
        bare program uploads as easily as the reference's /load form
        field; line endings are normalized (trailing newlines are KEPT —
        they cost a NOP slot, reference parity)."""
        given = [s for s in (tis, topology_json, compose) if s is not None]
        if len(given) != 1:
            raise RegistryError(
                "provide exactly one of: program (TIS source), "
                "topology (JSON), compose (YAML)"
            )
        if tis is not None:
            source = tis.replace("\r\n", "\n")
            if not source.strip():
                raise RegistryError("empty TIS source")
            return Topology(
                node_info={"main": "program"},
                programs={"main": source},
                **self._caps,
            )
        if topology_json is not None:
            try:
                raw = json.loads(topology_json)
            except ValueError as e:
                raise RegistryError(f"topology is not valid JSON: {e}") from e
            if not isinstance(raw, dict) or "nodes" not in raw:
                raise RegistryError(
                    'topology JSON must be {"nodes": ..., "programs": ...}'
                )
            caps = dict(self._caps)
            for field in ("stack_cap", "in_cap", "out_cap"):
                if field in raw:
                    caps[field] = int(raw[field])
            return Topology(
                node_info=dict(raw["nodes"]),
                programs=dict(raw.get("programs", {})),
                **caps,
            )
        from misaka_tpu.runtime.compose import ComposeError, parse_compose

        try:
            return parse_compose(compose, **self._caps)
        except ComposeError as e:
            raise RegistryError(str(e)) from e

    # --- seeding (the boot program) -----------------------------------------

    def seed(self, name: str, master, topology: Topology | None = None) -> str:
        """Register the boot network + its LIVE engine under `name`.

        The seeded program is PINNED: never LRU-evicted, never hot-swapped
        by publish (it stays under the legacy /run /pause /reset /load
        lifecycle the HTTP surface binds to this master) — full backward
        compatibility for every pre-registry client."""
        if not NAME_RE.match(name):
            raise RegistryError(f"invalid program name {name!r}")
        topo = topology if topology is not None else master._topology
        canonical = canonical_topology(topo)
        version = version_of(canonical)
        meta = {
            "source": canonical,
            "created_unix": round(time.time(), 3),
            "seeded": True,
        }
        with self._cond:
            entry = self._entries.setdefault(name, _Entry())
            entry.pinned = True
            entry.versions.setdefault(version, meta)
            entry.aliases["latest"] = version
            self._engines[(name, version)] = _Engine(master)
            self._lru[(name, version)] = time.monotonic()
            self._default = name
        master.program_label = name
        if self._tmpdir is None:
            self._persist_version(name, version, meta)
            self._persist_aliases(name, dict(entry.aliases))
        return version

    @property
    def default_name(self) -> str | None:
        return self._default

    def waiting_values(self) -> int:
        """Live ServeBatcher backlog summed across every active engine —
        the edge admission governor's queue-depth signal (the seeded
        default program's engine is the boot master, so this sum covers
        the whole process)."""
        with self._cond:
            masters = [
                e.master for e in self._engines.values()
                if e.master is not None
            ]
        total = 0
        for m in masters:
            b = getattr(m, "_batcher", None)
            if b is not None:
                total += b.waiting_values()
        return total

    def install_quotas(self, chain) -> None:
        """Install every program's latest `quota` override into an edge
        chain.  make_http_server calls this after building the process
        chain: the registry boots (and reloads its persisted store)
        BEFORE the chain exists, so boot-time overrides would otherwise
        land on the disarmed placeholder."""
        with self._cond:
            specs = {
                name: entry.versions[entry.aliases["latest"]].get("quota")
                for name, entry in self._entries.items()
                if entry.aliases.get("latest") in entry.versions
            }
        for name, spec in specs.items():
            if spec:
                try:
                    chain.set_program_quota(name, spec)
                except edge_mod.QuotaSpecError:
                    log.warning(
                        "registry: ignoring corrupt quota spec on %s", name
                    )

    # --- publish / hot-swap -------------------------------------------------

    def publish(
        self,
        name: str,
        *,
        tis: str | None = None,
        topology_json: str | None = None,
        compose: str | None = None,
        slo_spec: str | None = None,
        quota_spec: str | None = None,
        verify: str | None = None,
    ) -> dict:
        """Upload one program version; hot-swap the live engine when the
        `latest` alias moves under it.

        Compile-FIRST discipline: the source is parsed, lowered, and
        compiled at the registry's serving batch before any bookkeeping
        mutates — a bad upload is a 400 that touches nothing (the fix the
        legacy /load route needed too, runtime/master.py).

        `slo_spec` (the upload form's `slo` field) declares per-program
        service objectives in MISAKA_SLO grammar (e.g. "p99<25ms,
        err<0.1%"): stored in the version metadata, installed into the
        burn-rate engine (utils/slo.py) when the version becomes
        `latest`, overriding the env-wide default objectives for this
        program.  Validated HERE — a malformed spec is a 400 that
        touches nothing, same as a bad source.

        `quota_spec` (the upload form's `quota` field) declares the
        per-program quota override in MISAKA_QUOTA grammar
        ("rps<100,vps<500000,cpu<0.5", runtime/edge.py): installed into
        the edge chain when the version becomes `latest`, field-wise
        overriding the env default (a key-file quota still wins over
        both).  Validated here like the slo field."""
        if not NAME_RE.match(name):
            raise RegistryError(f"invalid program name {name!r}")
        if verify not in (None, "", "replay"):
            raise RegistryError(
                f"unknown verify mode {verify!r} (supported: replay)"
            )
        if slo_spec is not None:
            try:
                slo.parse_spec(slo_spec)  # validate-first, like the source
            except slo.SLOSpecError as e:
                raise RegistryError(f"invalid slo spec: {e}") from e
        if quota_spec is not None:
            try:
                edge_mod.parse_quota_spec(quota_spec)
            except edge_mod.QuotaSpecError as e:
                raise RegistryError(f"invalid quota spec: {e}") from e
        topo = self.parse_source(
            tis=tis, topology_json=topology_json, compose=compose
        )
        topo.compile(batch=self._batch)  # compile-first: raises before any swap
        if verify == "replay":
            # the deploy gate: a shadow engine running THIS candidate must
            # reproduce the captured stream byte-for-byte before any
            # bookkeeping mutates — a divergence (or an unsound capture)
            # is a refusal that touches nothing, same as a bad source
            self._verify_replay(name, topo)
        canonical = canonical_topology(topo)
        version = version_of(canonical)
        meta = {"source": canonical, "created_unix": round(time.time(), 3)}
        if slo_spec is not None:
            meta["slo"] = slo_spec
        if quota_spec is not None:
            meta["quota"] = quota_spec
        with self._cond:
            entry = self._entries.get(name)
            if entry is not None and entry.pinned:
                raise RegistryError(
                    f"program {name!r} is the seeded boot program; "
                    f"reprogram it through POST /load"
                )
            while name in self._publishing:
                self._cond.wait()
            self._publishing.add(name)
        try:
            with self._cond:
                entry = self._entries.setdefault(name, _Entry())
                created = version not in entry.versions
                slo_changed = False
                if created:
                    entry.versions[version] = meta
                else:
                    # content-addressed dedup keeps the stored meta; an
                    # slo/quota re-declaration on a known version still
                    # lands (the ONLY dedup'd cases worth a disk rewrite)
                    if (
                        slo_spec is not None
                        and entry.versions[version].get("slo") != slo_spec
                    ):
                        entry.versions[version]["slo"] = slo_spec
                        slo_changed = True
                    if (
                        quota_spec is not None
                        and entry.versions[version].get("quota") != quota_spec
                    ):
                        entry.versions[version]["quota"] = quota_spec
                        slo_changed = True
                meta = entry.versions[version]
                prev = entry.aliases.get("latest")
                old_key = (name, prev) if prev is not None else None
                need_swap = (
                    prev is not None
                    and prev != version
                    and old_key in self._engines
                )
            self._persist_version(name, version, meta, overwrite=slo_changed)
            M_PROG_UPLOADS.inc()
            swapped = False
            if need_swap:
                self._hot_swap(name, version, old_key)
                swapped = True
            else:
                with self._cond:
                    entry.aliases["latest"] = version
                self._persist_aliases(name, {"latest": version})
            # the new `latest` owns this program's objectives: its spec
            # overrides MISAKA_SLO for this program; a latest without one
            # clears any previous override back to the env default.  A
            # refused install (override budget exhausted — the shared
            # MISAKA_USAGE_LABEL_MAX cap bounds slo gauge cardinality)
            # must not fail the upload: the program serves under the env
            # defaults and the refusal is loud in the log.
            try:
                slo.set_objectives(name, meta.get("slo"))
            except slo.SLOSpecError as e:
                log.warning("registry: slo override for %s not installed: %s",
                            name, e)
            # the new `latest` owns this program's quota override too: a
            # latest without one clears any previous override back to the
            # env/key-file defaults (runtime/edge.py precedence)
            try:
                edge_mod.current().set_program_quota(name, meta.get("quota"))
            except edge_mod.QuotaSpecError as e:
                log.warning(
                    "registry: quota override for %s not installed: %s",
                    name, e,
                )
            return {
                "name": name,
                "version": version,
                "created": created,
                "latest": version,
                "swapped": swapped,
            }
        finally:
            with self._cond:
                self._publishing.discard(name)
                self._cond.notify_all()

    def _verify_replay(self, name: str, topo) -> None:
        """The ``?verify=replay`` deploy gate: drive the last captured
        requests for ``name`` against a SHADOW engine compiled from the
        candidate topology — in-process, no live traffic touched.  The
        shadow restores the capture's anchor state first (the recorded
        stream replays from its starting checkpoint), then must answer
        every record byte-for-byte.  Any divergence — including an
        anchor the candidate cannot even restore (shape change) — raises
        ReplayDivergence; an unsound capture (no anchor, evicted
        records, recorder killed) raises RegistryError."""
        from misaka_tpu.runtime import capture as capture_mod
        from misaka_tpu.runtime.master import MasterNode

        try:
            anchor, recs = capture_mod.verify_bundle(name)
        except capture_mod.CaptureError as e:
            raise RegistryError(f"verify=replay refused: {e}") from e
        shadow = MasterNode(
            topo, chunk_steps=self._chunk, batch=self._batch,
            engine=self._engine,
        )
        try:
            try:
                shadow.restore(anchor["state"])
            except ValueError as e:
                # a candidate that cannot hold the anchor state is by
                # definition not answer-compatible with the capture
                raise ReplayDivergence(
                    f"candidate for {name!r} cannot restore the capture "
                    f"anchor: {e}"
                ) from e
            shadow.run()
            diffs = capture_mod.replay_records(shadow, recs)
        finally:
            try:
                shadow.close()
            except Exception:
                log.warning("replay shadow close failed", exc_info=True)
        if diffs:
            for d in diffs:
                log.warning("registry: %s", capture_mod.format_diff(d))
            raise ReplayDivergence(
                f"candidate for {name!r} diverged on "
                f"{len(diffs)}/{len(recs)} captured requests",
                diffs=diffs,
            )
        log.info(
            "registry: verify=replay green for %s (%d captured requests "
            "byte-identical)", name, len(recs),
        )
        self._verify_replay_history(name, topo, capture_mod, MasterNode)

    def _verify_replay_history(self, name, topo, capture_mod,
                               MasterNode) -> None:
        """With the capture spool armed, widen the gate past the live
        ring: replay the newest MISAKA_REPLAY_HISTORY rotated segments
        (default 2) against the candidate too.  Unsound history segments
        are skipped (the in-memory bundle above is the gate's floor) —
        but a divergence on any swept segment fails the deploy just as
        loudly."""
        try:
            depth = int(os.environ.get("MISAKA_REPLAY_HISTORY", "") or 2)
        except ValueError:
            depth = 2
        if depth <= 0 or capture_mod.spool_status() is None:
            return
        for apath, hrecs, seg in capture_mod.history_bundles(
                name, limit_segments=depth):
            try:
                _meta, state = capture_mod.load_anchor_checkpoint(apath)
            except Exception as e:
                log.warning("registry: history anchor %s unreadable: %s",
                            apath, e)
                continue
            shadow = MasterNode(
                topo, chunk_steps=self._chunk, batch=self._batch,
                engine=self._engine,
            )
            try:
                try:
                    shadow.restore(state)
                except ValueError as e:
                    raise ReplayDivergence(
                        f"candidate for {name!r} cannot restore the "
                        f"history anchor from {seg}: {e}"
                    ) from e
                shadow.run()
                diffs = capture_mod.replay_records(shadow, hrecs)
            finally:
                try:
                    shadow.close()
                except Exception:
                    log.warning("replay shadow close failed", exc_info=True)
            if diffs:
                for d in diffs:
                    log.warning("registry: %s", capture_mod.format_diff(d))
                raise ReplayDivergence(
                    f"candidate for {name!r} diverged on "
                    f"{len(diffs)}/{len(hrecs)} requests from history "
                    f"segment {seg}",
                    diffs=diffs,
                )
            log.info(
                "registry: verify=replay history green for %s over %s "
                "(%d requests)", name, os.path.basename(seg), len(hrecs),
            )

    def _hot_swap(
        self, name: str, version: str, old_key: tuple[str, str]
    ) -> None:
        """Replace the live alias engine with `version` under traffic.

        Order of operations is the whole point:
          1. build + WARM + run the new engine with NO gate closed — live
             traffic keeps flowing to the old version through the compile;
          2. close the park gate (`_swapping`): alias-addressed requests
             arriving now wait (they will serve on the new version);
          3. flip the alias, install the new engine, retire the old one
             from the active set (no NEW lease can reach it), open the
             gate — parked requests resolve the new alias and go;
          4. drain: wait for the old engine's in-flight leases, then
             checkpoint (durable manifest — `name@<old>` re-activates
             with its state intact) and close it.

        The `swap_during_load` chaos point (utils/faults.py) sleeps with
        the gate closed, widening the parked window the slow chaos test
        drives 32 pooled clients through."""
        new_master = self._build_master(name, version, fresh=True)
        with self._cond:
            self._swapping.add(name)
        try:
            entry = self._entries[name]
            delay = faults.fire("swap_during_load")
            if delay is not None:
                time.sleep(max(0.0, delay))
            with self._cond:
                entry.aliases["latest"] = version
                # Retire only a READY old engine.  A mid-build placeholder
                # (an explicit name@<old> activation still compiling) is
                # left alone: its builder installs it as a legitimate
                # explicit-version engine under the old key — popping it
                # here would orphan the master the builder is about to
                # finish (a running-engine leak).
                old = self._engines.get(old_key)
                if old is not None and old.ready.is_set() \
                        and old.error is None and not old.closed:
                    del self._engines[old_key]
                    self._lru.pop(old_key, None)
                    # gate re-activation of the old version NOW, in the
                    # same critical section that removes it: a name@<old>
                    # request must wait for the drain checkpoint, never
                    # build a duplicate engine against the still-live one
                    self._evicting.add(old_key)
                else:
                    old = None
                # Install the replacement ONLY if no engine occupies the
                # new key: a concurrent explicit name@<new> activation
                # (the version is addressable the moment publish records
                # it) may have gotten there first — ready or mid-build.
                # Clobbering its _Engine would orphan the master its
                # builder is about to install (a running-engine leak);
                # its engine serves the alias just as well, so ours is
                # discarded below instead.
                surplus = None
                if (name, version) in self._engines:
                    surplus = new_master
                else:
                    self._engines[(name, version)] = _Engine(new_master)
                    self._lru[(name, version)] = time.monotonic()
        finally:
            with self._cond:
                self._swapping.discard(name)
                self._cond.notify_all()
        self._persist_aliases(name, {"latest": version})
        M_PROG_SWAPS.labels(program=_program_label(name)).inc()
        log.info(
            "program %s hot-swapped %s -> %s", name, old_key[1], version
        )
        if surplus is not None:
            self._deactivate_engine(
                (name, version), surplus, checkpoint=False
            )
        if old is not None:
            self._retire(old_key, old)

    def _retire(self, key: tuple[str, str], eng: _Engine) -> None:
        """Drain a just-replaced engine and deactivate it (checkpoint +
        close).  The caller (_hot_swap) already put `key` in `_evicting`
        (in the same critical section that removed the engine), so no
        re-activation can fork a duplicate against the still-live state;
        this method owns releasing that gate — EXCEPT on the drain-timeout
        path, where the gate stays armed (the retired engine is still
        live with in-flight leases; releasing it would let a name@<old>
        request build a duplicate against un-checkpointed state) and the
        last lease-holder's _checkin releases it after writing the drain
        checkpoint.  A drain that outlives the timeout therefore hands
        closing to the last request out the door instead of blocking
        publish forever; further name@<old> checkouts park on the gate,
        deadline-bounded."""
        deadline = time.monotonic() + self._drain_s
        with self._cond:
            while eng.leases > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    eng.retired = True
                    log.warning(
                        "program %s@%s: %d request(s) still in flight "
                        "after %.0fs drain; closing when they finish",
                        key[0], key[1], eng.leases, self._drain_s,
                    )
                    return  # gate stays armed for _checkin (see above)
                self._cond.wait(min(0.25, remaining))
            if eng.closed:
                self._evicting.discard(key)
                self._cond.notify_all()
                return
            eng.closed = True
        self._deactivate_guarded(key, eng.master, checkpoint=True)

    # --- activation / eviction ---------------------------------------------

    def _build_master(self, name: str, version: str, fresh: bool = False):
        """Construct + (optionally) restore + warm + run one engine.
        Runs OFF the registry lock — compiles take seconds."""
        with self._cond:
            entry = self._entries.get(name)
            if entry is None or version not in entry.versions:
                raise ProgramNotFound(f"unknown program {name!r}@{version}")
            source = entry.versions[version]["source"]
        from misaka_tpu.runtime.master import MasterNode

        topo = topology_from_canonical(source)
        master = MasterNode(
            topo, chunk_steps=self._chunk, batch=self._batch,
            engine=self._engine,
            # per-program specialized native ticks (core/specialize.py),
            # cached next to the version store: a reactivation (or a
            # restart) reuses the content-keyed .so instead of recompiling;
            # hot-swap to a new version keys a new entry automatically
            native_spec_dir=os.path.join(self._name_dir(name), "native"),
        )
        master.program_label = name
        ckpt = self._state_path(name, version)
        if not fresh and os.path.exists(ckpt):
            try:
                master.load_checkpoint(ckpt)  # manifest-verified restore
                log.info(
                    "program %s@%s: state restored from eviction "
                    "checkpoint", name, version,
                )
            except Exception as e:
                # a corrupt eviction checkpoint costs the state, never the
                # activation — the durable manifest already rejected it
                log.warning(
                    "program %s@%s: eviction checkpoint rejected (%s); "
                    "activating with fresh state", name, version, e,
                )
        # pre-compile the serve jits on throwaway state so the first
        # (possibly parked-behind-a-swap) request never pays the compile
        master._warm_engine(master._net, master._runner,
                            master._batched_serve)
        master.run()
        return master

    def _deactivate_guarded(self, key, master, checkpoint: bool) -> None:
        """Deactivate with the re-activation gate held, then release it.

        CONTRACT: the caller already added `key` to `_evicting` INSIDE
        the same critical section that removed the engine from
        `_engines` — arming the gate after releasing that lock would
        leave a window where _checkout sees neither and builds a
        duplicate engine against a snapshot that is still being written.
        _checkout parks on `_evicting` until the drain checkpoint is
        fully committed, so a revival never races the save."""
        try:
            self._deactivate_engine(key, master, checkpoint)
        finally:
            with self._cond:
                self._evicting.discard(key)
                self._cond.notify_all()

    def _deactivate_engine(self, key, master, checkpoint: bool) -> None:
        name, version = key
        try:
            master.pause()
        except Exception:  # pragma: no cover — deactivation is best-effort
            log.exception("pausing %s@%s failed", name, version)
        if checkpoint:
            try:
                os.makedirs(self._name_dir(name), exist_ok=True)
                # include_history=False: the TSDB history is
                # process-global — every evicted program carrying its
                # own copy would multiply disk by the active set for a
                # blob the strictly-newer restore merge discards anyway
                master.save_checkpoint(
                    self._state_path(name, version), include_history=False
                )
            except Exception:
                log.exception(
                    "eviction checkpoint for %s@%s failed; state lost",
                    name, version,
                )
        try:
            master.close()
        except Exception:  # pragma: no cover
            log.exception("closing %s@%s failed", name, version)

    def _evict_over_cap(self, exclude: tuple[str, str]) -> None:
        """Drop the least-recently-used idle engines until the active set
        (ready + building) fits MISAKA_REGISTRY_MAX_ACTIVE.  Runs off the
        lock per victim; never evicts the pinned boot program, a busy
        engine, or `exclude` (the engine being activated)."""
        while True:
            with self._cond:
                if len(self._engines) <= self._max_active:
                    return
                candidates = [
                    k for k, e in self._engines.items()
                    if k != exclude
                    and e.ready.is_set()
                    and e.error is None
                    and e.leases == 0
                    and not self._entries[k[0]].pinned
                ]
                if not candidates:
                    return  # everything is busy or pinned: run over cap
                victim = min(candidates, key=lambda k: self._lru.get(k, 0.0))
                eng = self._engines.pop(victim)
                self._lru.pop(victim, None)
                eng.closed = True
                self._evicting.add(victim)  # same critical section as the pop
            log.info("registry: evicting cold program %s@%s", *victim)
            self._deactivate_guarded(victim, eng.master, checkpoint=True)
            M_PROG_EVICTIONS.labels(program=_program_label(victim[0])).inc()

    def deactivate(self, ref: str | None = None) -> bool:
        """Evict one program's active engine NOW (ops/test surface);
        True when an engine was active and is now checkpointed + closed."""
        with self._cond:
            name, version = self._resolve_locked(ref)
            if self._entries[name].pinned:
                raise RegistryError(
                    f"program {name!r} is the seeded boot program"
                )
            key = (name, version)
            eng = self._engines.get(key)
            if eng is None:
                return False
            deadline = time.monotonic() + self._drain_s
            while (eng.leases > 0 or not eng.ready.is_set()) \
                    and time.monotonic() < deadline:
                # not ready = an activation is mid-build; evicting its
                # placeholder would orphan the master the builder is
                # about to install — wait for it like a lease
                self._cond.wait(0.25)
            if eng.leases > 0 or not eng.ready.is_set():
                raise RegistryError(
                    f"program {name}@{version} is busy "
                    f"({eng.leases} request(s) in flight)"
                )
            if self._engines.get(key) is not eng:
                return False  # evicted/retired by someone else meanwhile
            del self._engines[key]
            self._lru.pop(key, None)
            eng.closed = True
            self._evicting.add(key)  # same critical section as the pop
        self._deactivate_guarded(key, eng.master, checkpoint=True)
        M_PROG_EVICTIONS.labels(program=_program_label(name)).inc()
        return True

    # --- request-side surface ----------------------------------------------

    def _resolve_locked(self, ref: str | None) -> tuple[str, str]:
        """`ref` -> (name, version).  Callers hold self._cond.

        None/"" is the seeded default; "name" and "name@latest" follow
        the alias; "name@<version>" is exact.  Unknowns raise the typed
        ProgramNotFound the HTTP surface answers as 404."""
        if ref is None or ref == "":
            if self._default is None:
                raise ProgramNotFound("no default program seeded")
            ref = self._default
        name, _, version = str(ref).partition("@")
        entry = self._entries.get(name)
        if entry is None:
            raise ProgramNotFound(f"unknown program {name!r}")
        if version in ("", "latest"):
            version = entry.aliases.get("latest")
            if version is None:
                raise ProgramNotFound(f"program {name!r} has no versions")
        elif version not in entry.versions:
            raise ProgramNotFound(
                f"program {name!r} has no version {version!r}"
            )
        return name, version

    def resolve(self, ref: str | None) -> tuple[str, str]:
        with self._cond:
            return self._resolve_locked(ref)

    def _checkout(self, ref: str | None):
        """Resolve + lease one engine, activating it if cold.  Parks while
        the program's alias is mid-swap (re-resolving after, so a parked
        request serves on the NEW version)."""
        deadline = time.monotonic() + self._drain_s
        while True:
            build = False
            with self._cond:
                if self._closed:
                    raise RegistryError("registry is closed")
                name, version = self._resolve_locked(ref)
                if name in self._swapping:
                    # parked: the publish gate is closed for the flip
                    # window; wake re-resolves against the new alias
                    if not self._cond.wait(0.05) and \
                            time.monotonic() > deadline:
                        raise RegistryError(
                            f"program {name!r} swap did not complete "
                            f"within {self._drain_s}s"
                        )
                    continue
                key = (name, version)
                if key in self._evicting:
                    # a drain checkpoint for this exact version is being
                    # committed; wait for it rather than reviving against
                    # a stale/absent snapshot.  Deadline-bounded like the
                    # swap park: a wedged checkpoint save (hung disk)
                    # must surface as a typed error, not a 20 Hz spin.
                    self._cond.wait(0.05)
                    if time.monotonic() > deadline:
                        raise RegistryError(
                            f"program {name}@{version} deactivation did "
                            f"not complete within {self._drain_s}s"
                        )
                    continue
                eng = self._engines.get(key)
                if eng is None:
                    eng = _Engine()
                    self._engines[key] = eng
                    self._lru[key] = time.monotonic()
                    build = True
                elif eng.ready.is_set() and eng.error is None:
                    eng.leases += 1
                    self._lru[key] = time.monotonic()
                    return key, eng
            if build:
                try:
                    self._evict_over_cap(exclude=key)
                    master = self._build_master(name, version)
                except BaseException as e:
                    with self._cond:
                        eng.error = e
                        if self._engines.get(key) is eng:
                            del self._engines[key]
                            self._lru.pop(key, None)
                        eng.ready.set()
                        self._cond.notify_all()
                    raise
                doomed = False
                with self._cond:
                    if self._closed:
                        # close() ran while this engine was compiling;
                        # installing it now would leak a running master
                        # nothing will ever stop
                        eng.error = RegistryError("registry is closed")
                        self._engines.pop(key, None)
                        self._lru.pop(key, None)
                        eng.ready.set()
                        self._cond.notify_all()
                        doomed = True
                    else:
                        eng.master = master
                        eng.ready.set()
                        eng.leases += 1
                        self._lru[key] = time.monotonic()
                        self._cond.notify_all()
                if doomed:
                    self._deactivate_engine(key, master, checkpoint=False)
                    raise RegistryError("registry is closed")
                M_PROG_ACTIVATIONS.labels(
                    program=_program_label(name)
                ).inc()
                return key, eng
            # someone else is building (or it raced away): wait and retry
            eng.ready.wait(timeout=60.0)
            with self._cond:
                if eng.error is None and eng.ready.is_set() \
                        and self._engines.get(key) is eng \
                        and not eng.retired:
                    eng.leases += 1
                    self._lru[key] = time.monotonic()
                    return key, eng
                if isinstance(eng.error, BaseException):
                    raise RegistryError(
                        f"activating {name}@{version} failed: {eng.error}"
                    ) from eng.error
            # engine was evicted/retired between resolve and lease: retry

    def _checkin(self, key, eng: _Engine) -> None:
        close = False
        with self._cond:
            eng.leases -= 1
            if eng.leases == 0:
                self._cond.notify_all()
                if eng.retired and not eng.closed:
                    eng.closed = True
                    self._evicting.add(key)  # same critical section
                    close = True
        if close:
            # the straggler path: a hot-swap drain timed out and handed
            # closing to the last request out the door.  The engine is
            # quiescent now (zero leases), so the drain checkpoint is
            # still written — name@<old> keeps its revival contract even
            # on this path.
            self._deactivate_guarded(key, eng.master, checkpoint=True)

    @contextlib.contextmanager
    def lease(self, ref: str | None = None, values: int = 0):
        """The request-side entry point: resolve `ref`, activate if
        needed, park through a swap, count per-program metrics, and yield
        the engine for the request's lifetime.  The program name is made
        current on this thread for the scope (runtime/usage.py), so
        structured log lines emitted while serving carry a `program`
        field next to `trace_id` (utils/jsonlog.py)."""
        key, eng = self._checkout(ref)
        label = _program_label(key[0])
        M_PROG_REQS.labels(program=label).inc()
        if values:
            M_PROG_VALUES.labels(program=label).inc(values)
        try:
            with usage.program_scope(key[0]):
                yield eng.master
        finally:
            self._checkin(key, eng)

    # --- introspection ------------------------------------------------------

    def list_programs(self) -> dict:
        with self._cond:
            active = {
                k: e.leases for k, e in self._engines.items()
                if e.ready.is_set() and e.error is None
            }
            programs = {}
            for name, entry in self._entries.items():
                programs[name] = {
                    "latest": entry.aliases.get("latest"),
                    "pinned": entry.pinned,
                    "default": name == self._default,
                    # the usage ledger (runtime/usage.py): what this
                    # program has cost the box — None until it serves
                    "usage": usage.program_snapshot(name),
                    "versions": {
                        v: {
                            "created_unix": meta.get("created_unix"),
                            "active": (name, v) in active,
                            "leases": active.get((name, v), 0),
                            "checkpoint": os.path.exists(
                                self._state_path(name, v)
                            ),
                        }
                        for v, meta in entry.versions.items()
                    },
                }
        return {
            "max_active": self._max_active,
            "active_engines": len(active),
            "programs": programs,
        }

    def info(self, name: str) -> dict:
        listing = self.list_programs()
        if name not in listing["programs"]:
            raise ProgramNotFound(f"unknown program {name!r}")
        return {"name": name, **listing["programs"][name]}

    def summary(self) -> dict:
        """The /status payload: small, no filesystem walks."""
        with self._cond:
            return {
                "max_active": self._max_active,
                "active": sorted(
                    f"{n}@{v}" for (n, v), e in self._engines.items()
                    if e.ready.is_set() and e.error is None
                ),
                "names": sorted(self._entries),
                "default": self._default,
            }

    def active_versions(self) -> list[tuple[str, str]]:
        """Active (name, version) pairs, least-recently-used first."""
        with self._cond:
            return sorted(self._engines, key=lambda k: self._lru.get(k, 0.0))

    def active_masters(self) -> list[tuple[str, object]]:
        """(name, master) for every ready engine — the capture plane
        anchors each live program's state at /captures/start."""
        with self._cond:
            return [
                (n, e.master) for (n, _v), e in self._engines.items()
                if e.ready.is_set() and e.error is None
                and e.master is not None
            ]

    # --- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        """Checkpoint + close every registry-built engine (the pinned boot
        engine belongs to the caller and is left running).  In-flight
        leases get a bounded grace window to finish first — pausing an
        engine under a live request would park that caller for its full
        compute timeout instead of completing it."""
        with self._cond:
            self._closed = True  # no new checkouts past this point
            self._cond.notify_all()
            victims = [
                (k, e) for k, e in self._engines.items()
                if not self._entries[k[0]].pinned and e.ready.is_set()
                and e.error is None and not e.closed
            ]
            deadline = time.monotonic() + min(self._drain_s, 10.0)
            while any(e.leases > 0 for _, e in victims):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    log.warning(
                        "registry close: request(s) still in flight after "
                        "the grace window; closing anyway"
                    )
                    break
                self._cond.wait(min(0.25, remaining))
            for k, e in victims:
                self._engines.pop(k, None)
                self._lru.pop(k, None)
                e.closed = True
            self._cond.notify_all()
        for k, e in victims:
            self._deactivate_engine(k, e.master, checkpoint=True)
        if self._tmpdir is not None:
            self._tmpdir.cleanup()
            self._tmpdir = None
