"""docker-compose importer: run a reference deployment file as one fused network.

The reference's topology lives in a docker-compose file: the master service
carries NODE_INFO (cmd/app.go:30-35), each program service carries NODE_TYPE/
PROGRAM envs (docker-compose.yml:32-43), and stack services just declare
NODE_TYPE=stack.  A user migrating from the reference already has such a
file — this module turns it directly into a `Topology`, so

    MISAKA_TOPOLOGY=docker-compose.yml python -m misaka_tpu serve
    python -m misaka_tpu check docker-compose.yml            (or disasm/debug)

runs the exact network their containers ran, fused into one TPU kernel,
without hand-translating anything.

Mapping rules (strict on what matters, lenient on container plumbing):
  * services with environment.NODE_TYPE program/stack become nodes, keyed by
    service name (the reference addresses peers by compose service DNS name,
    program.go:476);
  * a program service's PROGRAM env becomes its TIS source (YAML block
    scalars keep their trailing newline — one NOP slot, parity with Go's
    strings.Split);
  * the master service's NODE_INFO is cross-checked against the services:
    nodes declared in one place but not the other are an error, because the
    reference would break the same way at runtime (unknown target dials);
  * image/build/ports/networks/cert envs are container plumbing — ignored.
"""

from __future__ import annotations

import json

from misaka_tpu.runtime.topology import Topology, TopologyError


class ComposeError(ValueError):
    """Raised when a compose file cannot be mapped onto a network."""


def _env_of(service: dict) -> dict[str, str]:
    env = service.get("environment") or {}
    if isinstance(env, list):  # compose also allows ["KEY=value", ...]
        out = {}
        for item in env:
            key, _, value = str(item).partition("=")
            out[key] = value
        return out
    return {str(k): ("" if v is None else str(v)) for k, v in env.items()}


def parse_compose(text: str, **caps) -> Topology:
    """Parse docker-compose YAML text into a Topology."""
    import yaml

    try:
        doc = yaml.safe_load(text)
    except yaml.YAMLError as e:
        raise ComposeError(f"invalid YAML: {e}") from e
    if not isinstance(doc, dict) or not isinstance(doc.get("services"), dict):
        raise ComposeError("compose file has no services mapping")

    node_info: dict[str, str] = {}
    programs: dict[str, str] = {}
    declared: dict[str, str] | None = None  # master's NODE_INFO view

    for name, service in doc["services"].items():
        env = _env_of(service or {})
        node_type = env.get("NODE_TYPE")
        if node_type in ("program", "stack"):
            node_info[name] = node_type
            if node_type == "program" and "PROGRAM" in env:
                programs[name] = env["PROGRAM"]
        elif node_type == "master":
            raw = env.get("NODE_INFO")
            if raw:
                try:
                    parsed = json.loads(raw)
                    if not isinstance(parsed, dict):
                        raise TypeError(f"expected a JSON object, got {type(parsed).__name__}")
                    declared = {n: spec["type"] for n, spec in parsed.items()}
                except (json.JSONDecodeError, TypeError, KeyError) as e:
                    raise ComposeError(f"master NODE_INFO is not valid: {e}") from e
        # services without NODE_TYPE are unrelated containers; skip

    if not node_info:
        raise ComposeError("no services with NODE_TYPE program/stack found")

    if declared is not None and declared != node_info:
        missing = set(declared) - set(node_info)
        extra = set(node_info) - set(declared)
        mismatched = {
            n
            for n in set(declared) & set(node_info)
            if declared[n] != node_info[n]
        }
        detail = "; ".join(
            part
            for part in (
                f"in NODE_INFO but not deployed: {sorted(missing)}" if missing else "",
                f"deployed but not in NODE_INFO: {sorted(extra)}" if extra else "",
                f"type mismatch: {sorted(mismatched)}" if mismatched else "",
            )
            if part
        )
        raise ComposeError(f"master NODE_INFO disagrees with services ({detail})")

    try:
        return Topology(node_info=node_info, programs=programs, **caps)
    except TopologyError as e:
        raise ComposeError(str(e)) from e


def load_compose(path: str, **caps) -> Topology:
    """Read + parse a compose file from disk (caps: stack_cap/in_cap/out_cap)."""
    with open(path) as f:
        return parse_compose(f.read(), **caps)
