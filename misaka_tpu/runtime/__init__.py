"""Host runtime: topology config, master HTTP control surface, entrypoint.

Lazy re-exports (PEP 562): `python -m misaka_tpu.runtime.app` imports THIS
package before app.py's body can arm its provisional boot-window signal
handlers — an eager `from .master import ...` here would widen the window
in which a SIGTERM kills the server with the default disposition instead
of a clean exit 0 (tests/test_lifecycle.py pins the contract).
"""

__all__ = ["Topology", "TopologyError", "MasterNode", "make_http_server"]


def __getattr__(name):
    if name in ("Topology", "TopologyError"):
        from misaka_tpu.runtime import topology

        return getattr(topology, name)
    if name in ("MasterNode", "make_http_server"):
        from misaka_tpu.runtime import master

        return getattr(master, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
