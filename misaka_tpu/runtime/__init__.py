"""Host runtime: topology config, master HTTP control surface, entrypoint."""

from misaka_tpu.runtime.topology import Topology, TopologyError
from misaka_tpu.runtime.master import MasterNode, make_http_server

__all__ = ["Topology", "TopologyError", "MasterNode", "make_http_server"]
