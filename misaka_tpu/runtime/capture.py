"""Wire-level traffic capture + deterministic shadow replay.

The system's superpower is bit-determinism: fixed tick semantics, a
bit-identical scalar/generic/avx2 engine ladder (r16), bit-identical
checkpoint restore (PRs 6/8).  This module exploits it: record what the
system was actually asked (raw input values, response values, status,
trace ID, tenant, engine tick) at every serving surface, then drive the
recorded stream plus its starting state against a shadow ``MasterNode``
running a CANDIDATE program version — unchanged semantics must reproduce
every response byte-for-byte; any change diffs loudly per request.

Surfaces, partitioned so every request is recorded exactly once — a
record is cut at the surface that TERMINATED the request:

  "http"    engine route table (/compute, /compute_batch, /compute_raw)
  "plane"   engine-side compute-plane frames (worker- and edge-shipped)
  "edge"    C++ frontend locally-terminated rejects (shed 429, 401, 413,
            overload) — requests the engine never sees
  "worker"  CPython frontend locally-terminated rejects (shed cache)

Knobs (configure() re-reads the environment, tracespan-style):

  MISAKA_CAPTURE=0          hard kill switch: start() refuses, every hook
                            stays a single falsy attribute check
  MISAKA_CAPTURE_MB         in-memory ring budget in MiB (default 16;
                            oldest records evict first, counted)
  MISAKA_CAPTURE_SAMPLE     record sampling rate (default 1.0).  Requests
                            carrying an INBOUND X-Misaka-Trace bypass
                            sampling — a traced request is always captured
  MISAKA_CAPTURE_DIR        default directory for exported segments
  MISAKA_REPLAY_VERIFY_MAX  newest records replayed by ?verify=replay
                            (default 256)

Replay soundness model (documented, enforced where checkable):

  * An anchor — ``master.snapshot()`` + tick + topology metadata — is
    taken per active program at start().  Replay restores the anchor
    into the shadow and feeds records in sequence order; absolute tick
    values are diagnostic (the recorded ORDER is what anchors replay).
  * Replay-grade captures need sample=1.0 and a contiguous stream: if
    the ring evicted records for a program since its anchor, replay of
    that program is refused (CaptureError) rather than silently wrong.
  * Per-program traffic must be serialized for byte-exactness (the
    serve scheduler coalesces concurrent callers nondeterministically);
    mixed-tenant capture is fine — programs are independent engines.
  * Arm in a quiet window: values in flight at start() are not in the
    anchor.  Background mutators (canaries driving the engine directly,
    lifecycle resets) are invisible to the wire and break replay.

Stdlib + numpy only on the record path; jax is touched only through the
master objects handed in by callers.
"""

from __future__ import annotations

import base64
import hashlib
import json
import logging
import os
import random
import struct
import threading
import time
from collections import deque

from misaka_tpu.utils import metrics

log = logging.getLogger("misaka.capture")

MAGIC = b"MSKCAP1\n"
_LEN = struct.Struct("<I")
# per-record bookkeeping overhead (dict + key strings + counters), used
# for the MISAKA_CAPTURE_MB budget accounting
_REC_OVERHEAD = 160
_MAX_FRAME = 64 << 20

M_RECORDS = metrics.counter(
    "misaka_capture_records_total",
    "Captured wire records, by serving surface",
    ("surface",),
)
M_DROPPED = metrics.counter(
    "misaka_capture_dropped_total",
    "Capture records evicted by the MISAKA_CAPTURE_MB ring budget",
)
M_SAMPLED_OUT = metrics.counter(
    "misaka_capture_sampled_out_total",
    "Requests skipped by MISAKA_CAPTURE_SAMPLE while recording",
)
M_RING_BYTES = metrics.gauge(
    "misaka_capture_ring_bytes",
    "Current capture ring memory footprint (payloads + overhead)",
)
M_RECORDING = metrics.gauge(
    "misaka_capture_recording",
    "1 while a capture is armed, else 0",
)
M_REPLAY_RUNS = metrics.counter(
    "misaka_replay_runs_total",
    "Shadow replay runs, by verdict",
    ("verdict",),
)
M_REPLAY_DIVERGENCES = metrics.counter(
    "misaka_replay_divergences_total",
    "Individual replayed records whose response bytes diverged",
)


class CaptureError(RuntimeError):
    """Capture/replay plane refusal (killed, torn segment, unsound replay)."""


# module-level fast flag: every hook is `if capture.RECORDING: ...` — one
# attribute load when idle, and MISAKA_CAPTURE=0 keeps it False forever
RECORDING = False

_lock = threading.Lock()
_ring: deque = deque()
_ring_bytes = 0
_seq = 0
_dropped = 0
_sampled_out = 0
_dropped_since_anchor: dict = {}
_anchors: dict = {}
_started_unix = 0.0

_KILLED = False
_BUDGET = 16 << 20
_SAMPLE = 1.0
_DIR = "captures"
_VERIFY_MAX = 256


def configure(environ=os.environ) -> None:
    """(Re-)read the env knobs — called at import; tests and the bench
    A/B call it again after toggling the environment."""
    global _KILLED, _BUDGET, _SAMPLE, _DIR, _VERIFY_MAX
    _KILLED = environ.get("MISAKA_CAPTURE", "1") == "0"
    try:
        mb = float(environ.get("MISAKA_CAPTURE_MB", "") or 16)
    except ValueError:
        mb = 16.0
    _BUDGET = max(1 << 16, int(mb * (1 << 20)))
    try:
        _SAMPLE = min(1.0, max(0.0, float(
            environ.get("MISAKA_CAPTURE_SAMPLE", "") or 1.0
        )))
    except ValueError:
        _SAMPLE = 1.0
    _DIR = environ.get("MISAKA_CAPTURE_DIR", "") or "captures"
    try:
        _VERIFY_MAX = max(1, int(
            environ.get("MISAKA_REPLAY_VERIFY_MAX", "") or 256
        ))
    except ValueError:
        _VERIFY_MAX = 256


configure()


def available() -> bool:
    return not _KILLED


def recording() -> bool:
    return RECORDING


def sample_rate() -> float:
    return _SAMPLE


def mem_bytes() -> int:
    return _ring_bytes


# ---------------------------------------------------------------------------
# Anchors
# ---------------------------------------------------------------------------

def anchor_from_master(label: str, master) -> dict | None:
    """Snapshot one engine into a replay anchor: deep-copied state
    pytree, tick, and the same topology metadata save_checkpoint embeds.
    Returns None for masters without the MasterNode snapshot surface
    (the distributed control plane cannot anchor)."""
    snap = getattr(master, "snapshot", None)
    topo = getattr(master, "_topology", None)
    if snap is None or topo is None:
        return None
    # batch=None is a real mode (single-instance serving, no batch axis
    # on the state arrays) — preserve it so the shadow rebuilds the same
    # shape, don't coerce to 1
    batch = getattr(master, "_batch", None)
    batch = int(batch) if batch is not None else None
    return {
        "label": label,
        "state": snap(),
        "tick": int(getattr(master, "_ticks_done", 0) or 0),
        "batch": batch,
        "engine": getattr(master, "engine_name", None),
        "meta": {
            "nodes": topo.node_info,
            "programs": topo.programs,
            "stack_cap": topo.stack_cap,
            "in_cap": topo.in_cap,
            "out_cap": topo.out_cap,
            "batch": batch,
        },
    }


def write_anchor_checkpoint(path: str, anchor: dict) -> None:
    """One anchor -> a load_checkpoint-compatible .npz, written with the
    r9 durable discipline (tmp+fsync, sha256 manifest sidecar, atomic
    replaces, directory fsync)."""
    import numpy as np

    from misaka_tpu.runtime.master import _fsync_dir, manifest_path

    state = anchor["state"]
    arrays = {f: np.asarray(getattr(state, f)) for f in state._fields}
    arrays["__topology__"] = np.frombuffer(
        json.dumps(anchor["meta"]).encode(), dtype=np.uint8
    )
    tmp = f"{path}.tmp.{os.getpid()}"
    mtmp = f"{manifest_path(path)}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        h = hashlib.sha256()
        with open(tmp, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        size = os.path.getsize(tmp)
        with open(mtmp, "w") as f:
            json.dump({
                "format": 1,
                "sha256": h.hexdigest(),
                "size": size,
                "saved_unix": round(time.time(), 3),
                "batch": anchor["batch"],
            }, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        os.replace(mtmp, manifest_path(path))
    except BaseException:
        for leftover in (tmp, mtmp):
            try:
                os.unlink(leftover)
            except OSError:
                pass
        raise
    _fsync_dir(os.path.dirname(os.path.abspath(path)))


# ---------------------------------------------------------------------------
# Recording
# ---------------------------------------------------------------------------

def start(anchors: dict | None = None) -> dict:
    """Arm the recorder: reset the ring, install per-program anchors.
    Refuses under MISAKA_CAPTURE=0 and when already recording."""
    global RECORDING, _ring_bytes, _seq, _dropped, _sampled_out
    global _anchors, _started_unix, _dropped_since_anchor
    if _KILLED:
        raise CaptureError(
            "capture disabled (MISAKA_CAPTURE=0 is the kill switch)"
        )
    with _lock:
        if RECORDING:
            raise CaptureError("a capture is already recording")
        _ring.clear()
        _ring_bytes = 0
        _seq = 0
        _dropped = 0
        _sampled_out = 0
        _dropped_since_anchor = {}
        _anchors = dict(anchors or {})
        _started_unix = time.time()
        RECORDING = True
    M_RECORDING.set(1)
    M_RING_BYTES.set(0)
    return status()


def stop() -> dict:
    """Disarm; the ring and anchors stay readable for export/replay."""
    global RECORDING
    with _lock:
        RECORDING = False
    M_RECORDING.set(0)
    return status()


def status() -> dict:
    with _lock:
        return {
            "recording": RECORDING,
            "available": not _KILLED,
            "records": len(_ring),
            "ring_bytes": _ring_bytes,
            "budget_bytes": _BUDGET,
            "sample": _SAMPLE,
            "dropped": _dropped,
            "sampled_out": _sampled_out,
            "started_unix": _started_unix if RECORDING or _ring else None,
            "anchors": {
                k: {"tick": a["tick"], "batch": a["batch"],
                    "engine": a.get("engine")}
                for k, a in _anchors.items()
            },
        }


def _evict_locked() -> None:
    global _ring_bytes, _dropped
    while _ring_bytes > _BUDGET and _ring:
        old = _ring.popleft()
        _ring_bytes -= old["_sz"]
        _dropped += 1
        label = old["program"]
        _dropped_since_anchor[label] = (
            _dropped_since_anchor.get(label, 0) + 1
        )
        M_DROPPED.inc()


def note(surface: str, *, program: str | None, trace: str | None,
         inbound: bool, vals: bytes, resp: bytes, status: int,
         tick: int | None, reqs: int = 1, op: str = "coalesced",
         segs=None, t: float | None = None) -> None:
    """Record one terminated request (or coalesced plane frame).

    ``vals``/``resp`` are raw little-endian int32 payload bytes for
    successes (the byte-for-byte replay comparands); ``resp`` is UTF-8
    reject text otherwise.  ``op`` names the compute lane ("coalesced"
    or "many") so replay drives the identical code path."""
    global _seq, _ring_bytes, _sampled_out
    if not RECORDING:
        return
    if not inbound and _SAMPLE < 1.0 and random.random() >= _SAMPLE:
        with _lock:
            _sampled_out += 1
        M_SAMPLED_OUT.inc()
        return
    label = program if program else "default"
    rec = {
        "surface": surface,
        "program": label,
        "trace": trace,
        "inbound": bool(inbound),
        "t": time.time() if t is None else t,
        "tick": tick,
        "status": int(status),
        "op": op,
        "reqs": int(reqs),
        "n": len(vals) // 4,
        "vals": vals,
        "resp": resp,
    }
    if segs:
        rec["segs"] = segs
    rec["_sz"] = len(vals) + len(resp) + _REC_OVERHEAD
    with _lock:
        if not RECORDING:
            return
        rec["seq"] = _seq
        _seq += 1
        _ring.append(rec)
        _ring_bytes += rec["_sz"]
        _evict_locked()
        ring_bytes = _ring_bytes
    M_RECORDS.labels(surface=surface).inc()
    M_RING_BYTES.set(ring_bytes)


def ingest(surface: str, rows, pre_sampled: bool = False) -> None:
    """Locally-terminated rejects shipped up from the edge/worker tiers:
    bounded rows of {t, program, trace, in, status, reason, n}.  The C++
    edge applies MISAKA_CAPTURE_SAMPLE itself (pre_sampled=True); worker
    rows sample here."""
    if not RECORDING:
        return
    for row in rows:
        try:
            inbound = bool(row.get("in"))
            if (not pre_sampled and not inbound and _SAMPLE < 1.0
                    and random.random() >= _SAMPLE):
                M_SAMPLED_OUT.inc()
                continue
            reason = str(row.get("reason") or "reject")
            note(
                surface,
                program=row.get("program") or None,
                trace=row.get("trace") or None,
                inbound=True,  # sampling already settled above
                vals=b"",
                resp=reason.encode(),
                status=int(row.get("status") or 0),
                tick=None,
                reqs=1,
                op="reject",
                t=float(row["t"]) if row.get("t") is not None else None,
            )
        except (TypeError, ValueError, KeyError):
            continue  # a malformed row must never hurt the serving path


def records(program: str | None = None, limit: int | None = None) -> list:
    """Newest-last copies of the ring (optionally one program's)."""
    with _lock:
        out = list(_ring)
    if program is not None:
        out = [r for r in out if r["program"] == program]
    if limit is not None and len(out) > limit:
        out = out[-limit:]
    return out


def dropped_since_anchor(program: str) -> int:
    with _lock:
        return _dropped_since_anchor.get(program, 0)


def anchor(program: str) -> dict | None:
    with _lock:
        return _anchors.get(program)


def debug_payload(limit: int = 100) -> dict:
    """GET /debug/captures: recorder status + the newest records with
    value previews (full payloads live in exports, not the debug JSON)."""
    payload = status()
    rows = []
    for r in records(limit=limit):
        rows.append({
            "seq": r["seq"],
            "surface": r["surface"],
            "program": r["program"],
            "trace": r["trace"],
            "inbound": r["inbound"],
            "t": round(r["t"], 6),
            "tick": r["tick"],
            "status": r["status"],
            "op": r["op"],
            "reqs": r["reqs"],
            "n": r["n"],
            "vals_head": _preview(r["vals"]),
            "resp_head": (
                _preview(r["resp"]) if r["status"] == 200
                else r["resp"][:80].decode("utf-8", "replace")
            ),
        })
    payload["preview"] = rows
    sp = spool_status()
    if sp is not None:
        payload["spool"] = sp
    return payload


def _preview(raw: bytes, k: int = 8) -> list:
    import numpy as np

    return np.frombuffer(raw[: 4 * k], dtype="<i4").tolist()


# ---------------------------------------------------------------------------
# Segment files (length-prefixed append-only, fsync + manifest)
# ---------------------------------------------------------------------------

def _segment_manifest_path(path: str) -> str:
    return f"{path}.manifest"


def _record_to_json(rec: dict) -> dict:
    out = {k: v for k, v in rec.items()
           if k not in ("vals", "resp", "_sz")}
    out["vals_b64"] = base64.b64encode(rec["vals"]).decode()
    out["resp_b64"] = base64.b64encode(rec["resp"]).decode()
    return out


def _record_from_json(obj: dict) -> dict:
    rec = dict(obj)
    rec["vals"] = base64.b64decode(rec.pop("vals_b64", ""))
    rec["resp"] = base64.b64decode(rec.pop("resp_b64", ""))
    return rec


def write_segment(path: str, anchor_files: dict | None = None) -> dict:
    """The current ring -> one segment file: MAGIC, then u32-length-
    prefixed JSON frames (frame 0 is the header), tmp+fsync'd with a
    sha256 manifest sidecar and atomic replaces — the r9 durable-
    checkpoint discipline for wire records."""
    from misaka_tpu.runtime.master import _fsync_dir

    recs = records()
    st = status()
    header = {
        "format": 1,
        "kind": "header",
        "started_unix": st["started_unix"],
        "saved_unix": round(time.time(), 3),
        "sample": st["sample"],
        "budget_bytes": st["budget_bytes"],
        "dropped": st["dropped"],
        "records": len(recs),
        "anchors": {
            label: {
                "tick": a["tick"], "batch": a["batch"],
                "engine": a.get("engine"),
                "dropped_since_anchor": dropped_since_anchor(label),
                "file": (anchor_files or {}).get(label),
            }
            for label, a in _anchors.items()
        },
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    mtmp = f"{_segment_manifest_path(path)}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(MAGIC)
            for obj in [header] + [_record_to_json(r) for r in recs]:
                blob = json.dumps(obj, separators=(",", ":")).encode()
                f.write(_LEN.pack(len(blob)))
                f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        h = hashlib.sha256()
        with open(tmp, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        size = os.path.getsize(tmp)
        with open(mtmp, "w") as f:
            json.dump({
                "format": 1,
                "sha256": h.hexdigest(),
                "size": size,
                "saved_unix": round(time.time(), 3),
                "records": len(recs),
            }, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        os.replace(mtmp, _segment_manifest_path(path))
    except BaseException:
        for leftover in (tmp, mtmp):
            try:
                os.unlink(leftover)
            except OSError:
                pass
        raise
    _fsync_dir(os.path.dirname(os.path.abspath(path)))
    return header


def verify_segment(path: str) -> dict:
    """Durability gate before any replay trusts a segment: the manifest
    sidecar's size + sha256 must match (CaptureError with evidence
    otherwise); without a sidecar, the frame walk itself must complete."""
    if not os.path.exists(path):
        raise CaptureError(f"no capture segment at {path}")
    mpath = _segment_manifest_path(path)
    if os.path.exists(mpath):
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            raise CaptureError(f"unreadable segment manifest {mpath}: {e}")
        size = os.path.getsize(path)
        if size != manifest.get("size"):
            raise CaptureError(
                f"segment {path} is {size} bytes; manifest says "
                f"{manifest.get('size')} (torn write?)"
            )
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        if h.hexdigest() != manifest.get("sha256"):
            raise CaptureError(
                f"segment {path} sha256 mismatch vs manifest (corrupt)"
            )
        return manifest
    header, recs = read_segment(path)  # structural walk is the fallback
    return {"format": 1, "records": len(recs), "sha256": None}


def read_segment(path: str, verify: bool = False):
    """-> (header dict, [records]) with payload bytes decoded."""
    if verify:
        verify_segment(path)
    frames = []
    with open(path, "rb") as f:
        magic = f.read(len(MAGIC))
        if magic != MAGIC:
            raise CaptureError(
                f"{path} is not a capture segment (bad magic {magic!r})"
            )
        while True:
            raw = f.read(4)
            if not raw:
                break
            if len(raw) < 4:
                raise CaptureError(f"segment {path}: torn length prefix")
            (length,) = _LEN.unpack(raw)
            if length > _MAX_FRAME:
                raise CaptureError(
                    f"segment {path}: frame of {length} bytes exceeds "
                    f"the {_MAX_FRAME}-byte cap"
                )
            blob = f.read(length)
            if len(blob) < length:
                raise CaptureError(f"segment {path}: torn frame")
            try:
                frames.append(json.loads(blob.decode()))
            except (ValueError, UnicodeDecodeError) as e:
                raise CaptureError(f"segment {path}: bad frame JSON: {e}")
    if not frames or frames[0].get("kind") != "header":
        raise CaptureError(f"segment {path}: missing header frame")
    return frames[0], [_record_from_json(o) for o in frames[1:]]


def export(path: str | None = None) -> dict:
    """Segment + per-program anchor checkpoints to disk; returns the
    header plus the paths written.  Works recording or stopped (the ring
    persists until the next start())."""
    if not _ring and not _anchors:
        raise CaptureError("nothing captured (POST /captures/start first)")
    if path is None:
        os.makedirs(_DIR, exist_ok=True)
        path = os.path.join(
            _DIR, f"capture-{time.strftime('%Y%m%d-%H%M%S')}.mskcap"
        )
    else:
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
    anchor_files = {}
    with _lock:
        anchors = dict(_anchors)
    for label, a in anchors.items():
        apath = f"{path}.anchor.{label}.npz"
        write_anchor_checkpoint(apath, a)
        anchor_files[label] = os.path.basename(apath)
    header = write_segment(path, anchor_files=anchor_files)
    return {
        "path": path,
        "records": header["records"],
        "dropped": header["dropped"],
        "anchors": {
            label: os.path.join(os.path.dirname(path), fname)
            for label, fname in anchor_files.items()
        },
    }


# ---------------------------------------------------------------------------
# Continuous spooling (the always-on flight-recorder mode)
# ---------------------------------------------------------------------------
#
# With MISAKA_TSDB_DIR set (the durable-telemetry master switch;
# MISAKA_CAPTURE_SPOOL=0 opts this plane out), a rotation daemon makes
# the PR 17 recorder continuous: it arms the ring at boot and, whenever
# the ring grows past MISAKA_CAPTURE_SEG_KB or ages past
# MISAKA_CAPTURE_SEG_S, exports the ring as a finalized
# ``spool-<seq>.mskcap`` segment (manifest + per-program anchors — every
# rotated segment independently replayable) and re-arms with FRESH
# anchors cut at the rotation point.  Records that land between the
# export snapshot and the ring reset are the rotation's bounded loss,
# counted on misaka_capture_spool_dropped_total; oldest segment groups
# are evicted under MISAKA_CAPTURE_DISK_MB.  A crash loses at most the
# un-rotated ring (segments are written atomically, never torn).

M_SPOOL_DROPPED = metrics.counter(
    "misaka_capture_spool_dropped_total",
    "Capture records lost at spool rotation boundaries plus on-disk "
    "segments evicted by the MISAKA_CAPTURE_DISK_MB budget",
)
M_SPOOL_ROTATIONS = metrics.counter(
    "misaka_capture_spool_rotations_total",
    "Capture spool segment rotations",
)
M_SPOOL_BYTES = metrics.gauge(
    "misaka_capture_spool_bytes",
    "On-disk footprint of the capture spool (segments + anchors)",
)

_spool_mu = threading.Lock()
_spool: dict | None = None


def spool_dir(environ=os.environ) -> str | None:
    root = environ.get("MISAKA_TSDB_DIR")
    if not root or environ.get("MISAKA_CAPTURE_SPOOL", "1") == "0":
        return None
    return os.path.join(root, "capture")


def _env_float(environ, name: str, default: float) -> float:
    try:
        return float(environ.get(name, "") or default)
    except ValueError:
        return default


def ensure_spool(environ=os.environ, anchor_fn=None) -> dict | None:
    """Arm the rotation daemon (idempotent; None when the master switch
    is unset or capture is killed).  ``anchor_fn() -> {label: anchor}``
    cuts fresh per-program anchors at boot and at every rotation — the
    HTTP server passes the same closure /captures/start uses."""
    global _spool
    d = spool_dir(environ)
    if d is None or _KILLED:
        return None
    with _spool_mu:
        if _spool is not None:
            return _spool
        os.makedirs(d, exist_ok=True)
        # crash hygiene: a kill mid-export leaves only tmp files behind
        for name in os.listdir(d):
            if ".tmp." in name:
                try:
                    os.unlink(os.path.join(d, name))
                except OSError:
                    pass
        next_seq = 0
        for seq, _ in _spool_groups(d):
            next_seq = max(next_seq, seq + 1)
        st = {
            "dir": d,
            "budget_bytes": int(_env_float(
                environ, "MISAKA_CAPTURE_DISK_MB", 256.0) * (1 << 20)),
            "seg_bytes": int(_env_float(
                environ, "MISAKA_CAPTURE_SEG_KB", 4096.0) * 1024),
            "seg_s": max(0.05, _env_float(
                environ, "MISAKA_CAPTURE_SEG_S", 300.0)),
            "anchor_fn": anchor_fn,
            "next_seq": next_seq,
            "rotations": 0,
            "evicted_segments": 0,
            "last_rotate_mono": time.monotonic(),
            "stop": threading.Event(),
        }
        st["poll_s"] = min(1.0, max(0.05, st["seg_s"] / 4.0))
        _spool = st
    if not RECORDING:
        try:
            start(anchors=_cut_anchors(st))
        except CaptureError:
            pass  # an operator capture already runs; ride it
    threading.Thread(
        target=_spool_loop, args=(st,), daemon=True,
        name="misaka-capture-spool",
    ).start()
    return st


def _cut_anchors(st: dict) -> dict:
    fn = st.get("anchor_fn")
    if fn is None:
        return {}
    try:
        return fn() or {}
    except Exception:
        log.warning("capture spool: anchor cut failed", exc_info=True)
        return {}


def _spool_loop(st: dict) -> None:
    while not st["stop"].wait(st["poll_s"]):
        try:
            if not RECORDING and not _KILLED:
                # always-on: re-arm after an operator stop/export
                try:
                    start(anchors=_cut_anchors(st))
                except CaptureError:
                    pass
            with _lock:
                n, nbytes = len(_ring), _ring_bytes
            age = time.monotonic() - st["last_rotate_mono"]
            if n and (nbytes >= st["seg_bytes"] or age >= st["seg_s"]):
                rotate_now()
        except Exception:  # pragma: no cover — the recorder must never
            log.warning("capture spool tick failed", exc_info=True)
            from misaka_tpu.utils import spool as spool_mod

            spool_mod.M_SPOOL_ERRORS.labels(plane="capture").inc()


def rotate_now() -> dict | None:
    """Finalize the current ring as the next spool segment and re-arm
    with fresh anchors (the daemon's trigger; POST /captures/rotate for
    a deterministic operator cut).  None when the ring is empty."""
    with _spool_mu:
        st = _spool
        if st is None:
            raise CaptureError(
                "capture spool not armed (set MISAKA_TSDB_DIR)"
            )
        with _lock:
            if not _ring:
                return None
        seq = st["next_seq"]
        st["next_seq"] = seq + 1
        path = os.path.join(st["dir"], f"spool-{seq:08d}.mskcap")
        try:
            result = export(path)
        except OSError as e:
            log.warning("capture spool: rotation export failed: %s", e)
            from misaka_tpu.utils import spool as spool_mod

            spool_mod.M_SPOOL_ERRORS.labels(plane="capture").inc()
            return None
        anchors = _cut_anchors(st)
        with _lock:
            ring_now = len(_ring)
        stop()
        lost = max(0, ring_now - result["records"])
        try:
            start(anchors=anchors)
        except CaptureError:  # pragma: no cover — killed mid-rotation
            pass
        if lost:
            M_SPOOL_DROPPED.inc(lost)
        M_SPOOL_ROTATIONS.inc()
        st["rotations"] += 1
        st["last_rotate_mono"] = time.monotonic()
        _enforce_spool_budget(st)
        return result


def _spool_groups(directory: str) -> list[tuple[int, list[str]]]:
    """[(seq, [segment + manifest + anchor paths])] oldest-first."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    groups: dict[int, list[str]] = {}
    for name in names:
        if not name.startswith("spool-"):
            continue
        stem = name.split(".mskcap")[0]
        try:
            seq = int(stem[len("spool-"):])
        except ValueError:
            continue
        groups.setdefault(seq, []).append(os.path.join(directory, name))
    return sorted((seq, sorted(paths)) for seq, paths in groups.items())


def _enforce_spool_budget(st: dict) -> None:
    groups = _spool_groups(st["dir"])
    sizes = []
    total = 0
    for seq, paths in groups:
        size = 0
        for p in paths:
            try:
                size += os.path.getsize(p)
            except OSError:
                pass
        sizes.append(size)
        total += size
    evicted = 0
    for (seq, paths), size in zip(groups, sizes):
        if total <= st["budget_bytes"] or len(groups) - evicted <= 1:
            break
        for p in paths:
            try:
                os.unlink(p)
            except OSError:
                pass
        total -= size
        evicted += 1
    if evicted:
        st["evicted_segments"] += evicted
        M_SPOOL_DROPPED.inc(evicted)
        log.warning(
            "capture spool: disk budget %.1f MiB exceeded — evicted %d "
            "oldest segment group(s)",
            st["budget_bytes"] / (1 << 20), evicted,
        )
    M_SPOOL_BYTES.set(total)


def spool_status() -> dict | None:
    with _spool_mu:
        st = _spool
        if st is None:
            return None
        groups = _spool_groups(st["dir"])
        return {
            "dir": st["dir"],
            "segments": len(groups),
            "rotations": st["rotations"],
            "evicted_segments": st["evicted_segments"],
            "budget_bytes": st["budget_bytes"],
            "segment_bytes": st["seg_bytes"],
            "segment_seconds": st["seg_s"],
            "disk_bytes": sum(
                os.path.getsize(p)
                for _, paths in groups for p in paths
                if os.path.exists(p)
            ),
        }


def shutdown_spool() -> None:
    """Tests: stop the rotation daemon (the ring keeps recording)."""
    global _spool
    with _spool_mu:
        if _spool is not None:
            _spool["stop"].set()
            _spool = None


def history_segments(directory: str | None = None,
                     environ=os.environ) -> list[str]:
    """Finalized spool segments oldest-first (the replay sweep's input)."""
    d = directory or spool_dir(environ)
    if d is None:
        return []
    return [
        paths[0]
        for _, paths in _spool_groups(d)
        if paths and paths[0].endswith(".mskcap")
    ]


def history_bundles(program: str, limit_segments: int = 2,
                    directory: str | None = None) -> list[tuple]:
    """Newest-first [(anchor_path, replayable records, segment_path)]
    from the on-disk spool history for one program — what widens
    verify=replay past the in-memory window.  Unsound segments (missing
    or drop-tainted anchors) are skipped, not fatal: the in-memory
    bundle is the gate's floor, history is extra evidence."""
    out: list[tuple] = []
    for path in reversed(history_segments(directory)):
        if len(out) >= max(0, limit_segments):
            break
        try:
            header, recs = read_segment(path, verify=True)
        except CaptureError:
            continue
        info = (header.get("anchors") or {}).get(program)
        if not info or int(info.get("dropped_since_anchor") or 0):
            continue
        fname = info.get("file")
        if not fname:
            continue
        apath = os.path.join(os.path.dirname(os.path.abspath(path)), fname)
        if not os.path.exists(apath):
            continue
        sel = replayable([r for r in recs if r["program"] == program])
        if sel:
            out.append((apath, sel, path))
    return out


def load_anchor_checkpoint(path: str):
    """Anchor .npz -> (meta dict, NetworkState) after the durability
    gate.  Loaded manually (not via MasterNode.load_checkpoint) because
    a CANDIDATE replay restores the OLD state into a master compiled
    from a DIFFERENT topology."""
    import jax.numpy as jnp
    import numpy as np

    from misaka_tpu.core.state import NetworkState
    from misaka_tpu.runtime.master import verify_checkpoint

    verify_checkpoint(path)
    with np.load(path) as data:
        meta = json.loads(bytes(data["__topology__"]).decode())
        fields = {
            f: jnp.asarray(data[f])
            for f in NetworkState._fields if f in data
        }
        for hi, lo in (("acc_hi", "acc"), ("bak_hi", "bak")):
            if hi not in fields:  # pre-regs64 anchors were int32-exact
                fields[hi] = fields[lo] >> 31
        return meta, NetworkState(**fields)


# ---------------------------------------------------------------------------
# Shadow replay
# ---------------------------------------------------------------------------

def replayable(recs) -> list:
    """The records a shadow can drive: engine-terminated successes."""
    return [
        r for r in recs
        if r["surface"] in ("http", "plane") and r["status"] == 200
        and r["n"] > 0
    ]


def replay_records(master, recs, preview: int = 8) -> list:
    """Drive records sequentially through ``master`` (already restored
    to the anchor) and compare response bytes exactly.  Returns one diff
    dict per divergent record — empty means byte-for-byte green."""
    import numpy as np

    diffs = []
    for offset, rec in enumerate(recs):
        values = np.frombuffer(rec["vals"], dtype="<i4")
        if rec["op"] == "many":
            out = master.compute_many(values, return_array=True)
        else:
            out = master.compute_coalesced(values, return_array=True)
        actual = np.asarray(out).astype("<i4").tobytes()
        if actual != rec["resp"]:
            exp = np.frombuffer(rec["resp"], dtype="<i4")
            act = np.frombuffer(actual, dtype="<i4")
            k = min(len(exp), len(act))
            first = int(np.argmax(exp[:k] != act[:k])) if (
                k and (exp[:k] != act[:k]).any()
            ) else k
            diffs.append({
                "offset": offset,
                "seq": rec["seq"],
                "trace": rec["trace"],
                "program": rec["program"],
                "tick": rec["tick"],
                "n": rec["n"],
                "first_diff_index": first,
                "expected_len": len(exp),
                "actual_len": len(act),
                "expected_head": exp[
                    first: first + preview
                ].tolist(),
                "actual_head": act[first: first + preview].tolist(),
            })
            M_REPLAY_DIVERGENCES.inc()
    M_REPLAY_RUNS.labels(
        verdict="divergent" if diffs else "green"
    ).inc()
    return diffs


def format_diff(d: dict) -> str:
    """The loud per-request line a divergence renders."""
    return (
        f"DIVERGENCE offset={d['offset']} seq={d['seq']} "
        f"trace={d['trace'] or '-'} program={d['program']} "
        f"n={d['n']} first_diff_index={d['first_diff_index']} "
        f"expected={d['expected_head']} actual={d['actual_head']}"
    )


def verify_bundle(program: str, limit: int | None = None):
    """(anchor, records) for an in-process ?verify=replay gate — refuses
    (CaptureError) when the capture cannot soundly verify ``program``:
    no anchor, no records, or a non-contiguous stream since the anchor."""
    if _KILLED:
        raise CaptureError("capture disabled (MISAKA_CAPTURE=0)")
    a = anchor(program)
    if a is None:
        raise CaptureError(
            f"no capture anchor for program {program!r} "
            "(POST /captures/start while it serves, then retry)"
        )
    lost = dropped_since_anchor(program)
    if lost:
        raise CaptureError(
            f"capture ring evicted {lost} records for program "
            f"{program!r} since its anchor; replay would be unsound "
            "(raise MISAKA_CAPTURE_MB or shorten the window)"
        )
    recs = replayable(records(program=program))
    if not recs:
        raise CaptureError(
            f"no replayable captured requests for program {program!r}"
        )
    if limit is None:
        limit = _VERIFY_MAX
    return a, recs[-limit:]


# ---------------------------------------------------------------------------
# Load models
# ---------------------------------------------------------------------------

def fit_load_model(recs, series=None, tenant_series=None) -> dict:
    """Fit arrival-rate / batch-size / tenant-mix distributions from a
    capture into the JSON load model ``bench.py --model`` consumes.

    ``series`` optionally carries TSDB history rows
    ([(unix, requests_per_s), ...]) to widen the arrival fit beyond the
    capture window.  With the durable long-horizon tier retained (days
    of 5m slots), the same rows also yield a ``diurnal`` section — 24
    UTC hour-of-day weights normalized to mean 1.0 — and
    ``tenant_series`` ({tenant: rows}) yields per-tenant arrival rates
    (``tenants_arrival``), so --model replays a realistic day instead
    of a flat Poisson stream."""
    import numpy as np

    recs = [r for r in recs if r["surface"] in ("http", "plane")]
    if not recs:
        raise CaptureError("cannot fit a load model from zero records")
    ts = np.array(sorted(r["t"] for r in recs), dtype=np.float64)
    sizes = np.array([max(1, r["n"]) for r in recs], dtype=np.float64)
    duration = float(ts[-1] - ts[0]) if len(ts) > 1 else 0.0
    total_reqs = int(sum(r["reqs"] for r in recs))
    rate = total_reqs / duration if duration > 0 else float(total_reqs)
    if len(ts) > 2:
        gaps = np.diff(ts)
        gaps = gaps[gaps > 0]
        cv = float(gaps.std() / gaps.mean()) if len(gaps) > 1 and \
            gaps.mean() > 0 else 1.0
    else:
        cv = 1.0
    if series:
        vals = [float(v) for _, v in series if v is not None and v > 0]
        if vals:
            # TSDB history widens the fit past the capture window: blend
            # the long-run observed rate with the capture's own
            rate = 0.5 * rate + 0.5 * (sum(vals) / len(vals))
    uppers = [1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096]
    hist = []
    prev = 0
    for u in uppers:
        w = int(((sizes > prev) & (sizes <= u)).sum())
        if w:
            hist.append([u, w])
        prev = u
    over = int((sizes > uppers[-1]).sum())
    if over:
        hist.append([int(sizes.max()), over])
    tenants: dict = {}
    for r in recs:
        tenants[r["program"]] = tenants.get(r["program"], 0) + r["reqs"]
    statuses: dict = {}
    for r in recs:
        statuses[str(r["status"])] = statuses.get(str(r["status"]), 0) + 1
    diurnal = _fit_diurnal(series)
    tenants_arrival = {}
    for tenant, rows in (tenant_series or {}).items():
        vals = [float(v) for _, v in rows if v is not None and v >= 0]
        if vals:
            tenants_arrival[tenant] = round(sum(vals) / len(vals), 6)
    out = {
        "format": 1,
        "fitted_unix": round(time.time(), 3),
        "source": {"records": len(recs), "requests": total_reqs,
                   "duration_s": round(duration, 3)},
        "arrival": {"rate_rps": round(rate, 3),
                    "interarrival_cv": round(cv, 3)},
        "values": {
            "mean": round(float(sizes.mean()), 3),
            "p50": int(np.percentile(sizes, 50)),
            "p90": int(np.percentile(sizes, 90)),
            "p99": int(np.percentile(sizes, 99)),
            "max": int(sizes.max()),
            "hist": hist,
        },
        "tenants": {
            k: round(v / max(1, total_reqs), 6) for k, v in tenants.items()
        },
        "status_mix": statuses,
    }
    if diurnal:
        out["diurnal"] = diurnal
    if tenants_arrival:
        out["tenants_arrival"] = tenants_arrival
    return out


def _fit_diurnal(series) -> dict | None:
    """24 UTC hour-of-day weights (mean 1.0) from TSDB history rows, or
    None when the rows span fewer than two distinct hours — a short
    capture has no day shape worth replaying."""
    if not series:
        return None
    sums = [0.0] * 24
    counts = [0] * 24
    for t, v in series:
        if v is None or v < 0:
            continue
        hour = int(float(t) // 3600) % 24
        sums[hour] += float(v)
        counts[hour] += 1
    covered = [sums[h] / counts[h] for h in range(24) if counts[h]]
    if sum(1 for c in counts if c) < 2 or not covered:
        return None
    mean = sum(covered) / len(covered)
    if mean <= 0:
        return None
    weights = [
        round(sums[h] / counts[h] / mean, 4) if counts[h] else 1.0
        for h in range(24)
    ]
    return {
        "hour_weights_utc": weights,
        "hours_observed": sum(1 for c in counts if c),
    }
