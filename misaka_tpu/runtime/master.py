"""Master node: the reference's HTTP control surface over the TPU engine.

Route-for-route and message-for-message compatible with the Go master
(master.go:90-230): POST /run /pause /reset /load /compute, form-encoded
bodies, "Success" / JSON `{"value": N}` responses, 400 on errors, 405 with
"method GET not allowed" on non-POST.  What changes is everything beneath:
instead of broadcasting gRPC commands to node processes (master.go:269-351),
control toggles a host flag around a jitted device loop; instead of cap-1
channels bridged by per-value RPC (master.go:233-249), I/O moves through
device-resident rings synced each chunk.

Deliberate divergences (SURVEY.md quirks, each strictly better and test-pinned):
  * /compute responses are correlated — a lock serializes request pairing,
    fixing the reference's response-swap race (quirk #2, master.go:216-219).
  * /load targets the node directly in-process — the reference dials the
    wrong port and cannot actually live-load (quirk #1, master.go:178).
  * pause preserves in-flight state exactly (the reference cancels blocked
    ops with errors, program.go:196-204); resume continues where it stopped.
"""

from __future__ import annotations

import collections
import contextlib
import json
import logging
import os
import queue
import re
import ssl
import threading
import time
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

from misaka_tpu.runtime import capture as capture_mod
from misaka_tpu.runtime import usage
from misaka_tpu.runtime.topology import Topology, TopologyError
from misaka_tpu.tis.parser import TISParseError
from misaka_tpu.tis.lower import TISLowerError
from misaka_tpu.utils import faults
from misaka_tpu.utils import metrics
from misaka_tpu.utils import slo
from misaka_tpu.utils import tracespan
from misaka_tpu.utils import tsdb as tsdb_mod
from misaka_tpu.utils import watchdog as watchdog_mod
from misaka_tpu.utils import wire
from misaka_tpu.utils.httpfast import fast_parse_request as _fast_parse_request
from misaka_tpu.utils.textcodec import dec_to_ints, ints_to_dec

log = logging.getLogger("misaka_tpu.master")

# --- the metrics plane (utils/metrics.py; served at GET /metrics) ----------
# Process-global series (the Prometheus process model): every MasterNode and
# HTTP server in this process accumulates into the same registry, so tests
# and benches that build masters freely still scrape one coherent catalog.
# Per-master live values (queue depths) ride callback gauges holding
# weakrefs — last-constructed master wins, dead masters read as 0, and the
# device-loop hot path pays nothing per iteration for them.
M_TICKS = metrics.counter(
    "misaka_device_loop_ticks_total", "Network ticks advanced by the device loop"
)
M_LOOP_ITERS = metrics.counter(
    "misaka_device_loop_iterations_total",
    "Device-loop iterations by kind (serve = fed or drained, idle = nothing moved)",
    ("kind",),
)
M_CHUNK_SECONDS = metrics.histogram(
    "misaka_device_loop_chunk_seconds",
    "Wall time of one device-loop iteration (feed + chunk + drain)",
)
# children resolved once: the device loop must not pay label-lookup dict
# work per iteration (the native tier turns over iterations in ~us)
M_ITER_SERVE = M_LOOP_ITERS.labels(kind="serve")
M_ITER_IDLE = M_LOOP_ITERS.labels(kind="idle")
M_SLOT_OCCUPANCY = metrics.histogram(
    "misaka_device_loop_fed_slots",
    "Batch slots fed per serve iteration (batch-slot occupancy)",
    buckets=metrics.pow2_buckets(1, 65536),
)
M_SUBMIT_DEPTH = metrics.gauge(
    "misaka_submit_queue_depth",
    "Request chunks waiting in the submission queue (live master)",
)
M_OUT_DEPTH = metrics.gauge(
    "misaka_out_queue_depth",
    "Output chunks waiting across per-slot output queues (live master)",
)
M_WARM_TOTAL = metrics.counter(
    "misaka_engine_warm_total",
    "Engine warm-ups COMPLETED (first-call jit compiles forced)",
)
M_WARM_FAILED = metrics.counter(
    "misaka_engine_warm_failed_total",
    "Engine warm-ups that raised (the device loop then compiles under lock)",
)
M_WARM_SECONDS = metrics.histogram(
    "misaka_engine_warm_seconds",
    "Completed engine warm-up duration (jit compile + dummy chunk)",
)
M_AUTOGROW = metrics.counter(
    "misaka_stack_autogrow_total", "Successful stack-capacity doublings"
)
M_AUTOGROW_BLOCKED = metrics.counter(
    "misaka_stack_autogrow_blocked_total",
    "Stack wedges auto-grow could not repair (byte budget or engine limits)",
)
M_ENGINE_SWAPS = metrics.counter(
    "misaka_engine_swap_total",
    "Runner replacements by cause (load / restore / autogrow)",
    ("reason",),
)
M_CKPT_SAVE_SECONDS = metrics.histogram(
    "misaka_checkpoint_save_seconds", "save_checkpoint duration"
)
M_CKPT_RESTORE_SECONDS = metrics.histogram(
    "misaka_checkpoint_restore_seconds", "load_checkpoint duration (recompile + swap)"
)
M_CKPT_AGE = metrics.gauge(
    "misaka_checkpoint_age_seconds",
    "Seconds since the live master's last successful checkpoint save "
    "(-1 until one lands; alert when this exceeds the MISAKA_AUTOCKPT "
    "interval by a safety factor)",
)
M_CKPT_REJECTED = metrics.counter(
    "misaka_checkpoint_rejected_total",
    "Checkpoints that failed durability verification (truncated, checksum "
    "mismatch, CRC-corrupt) and were rejected before any state swap",
)
M_COMPUTE_REQS = metrics.counter(
    "misaka_compute_requests_total", "compute/compute_many/compute_spread calls"
)
M_COMPUTE_VALUES = metrics.counter(
    "misaka_compute_values_total", "Values submitted through the compute lanes"
)
M_COMPUTE_TIMEOUTS = metrics.counter(
    "misaka_compute_timeouts_total", "Compute calls that raised ComputeTimeout"
)
M_SERVE_COALESCED_VALUES = metrics.histogram(
    "misaka_serve_coalesced_values",
    "Values fused into one serve-scheduler pass (cross-request batching)",
    buckets=metrics.pow2_buckets(1, 1 << 20),
)
M_SERVE_COALESCED_REQS = metrics.histogram(
    "misaka_serve_coalesced_requests",
    "Requests fused into one serve-scheduler pass",
    buckets=metrics.pow2_buckets(1, 4096),
)
M_SERVE_QUEUE_DELAY = metrics.histogram(
    "misaka_serve_queue_delay_seconds",
    "Time a request waited in the serve-scheduler queue before its first "
    "dispatch (the coalescing latency tax — near zero when the engine is "
    "idle, bounded by pass time under load)",
)
M_SERVE_WAITING = metrics.gauge(
    "misaka_serve_waiting_requests",
    "Requests queued in the serve scheduler, not yet dispatched (live master)",
)
M_SERVE_PASSES = metrics.counter(
    "misaka_serve_passes_total", "Fused serve-scheduler passes dispatched"
)
M_SERVE_LANE_ENTRIES = metrics.counter(
    "misaka_serve_lane_entries_total",
    "Serve-scheduler entries by priority lane (hot = latency-class small "
    "requests, cut into passes ahead of the bulk lane's backlog)",
    ("lane",),
)
M_HTTP_REQS = metrics.counter(
    "misaka_http_requests_total", "HTTP requests by route and method",
    ("route", "method"),
)
M_HTTP_ERRORS = metrics.counter(
    "misaka_http_errors_total", "HTTP responses with status >= 400",
    ("route", "code"),
)
M_HTTP_INFLIGHT = metrics.gauge(
    "misaka_http_inflight", "HTTP requests currently being handled"
)
M_HTTP_LATENCY = metrics.histogram(
    "misaka_http_request_duration_seconds", "HTTP request handling time by route",
    ("route",),
)
# One accounting surface for every debug-plane ring: the request-trace
# recorder (r10), the native flight recorder (r18), and the capture ring
# (r20) each hold bounded memory; /healthz refreshes these on probe so a
# scrape answers "how much RAM does observability cost right now".
M_DEBUG_MEM = metrics.gauge(
    "misaka_debug_mem_bytes",
    "Debug-plane ring memory by plane (trace/flight/capture)",
    ("plane",),
)

# Bounded route-label cardinality: unknown paths collapse to "other" (an
# unauthenticated client must not be able to mint unbounded label values).
_METRIC_ROUTES = frozenset({
    "/run", "/pause", "/reset", "/load", "/compute", "/compute_batch",
    "/compute_raw", "/checkpoint", "/restore", "/profile/start",
    "/profile/stop", "/status", "/trace", "/metrics", "/healthz",
    "/debug/requests", "/debug/perfetto", "/debug/isa_trace",
    "/debug/usage", "/debug/alerts", "/debug/flamegraph",
    "/debug/series", "/debug/dashboard", "/debug/faults",
    "/debug/native_trace", "/debug/captures",
    "/captures/start", "/captures/stop", "/captures/export",
    "/captures/rotate", "/usage/export",
})

# The routes whose latency/error outcomes feed the per-program SLO windows
# (utils/slo.py): compute traffic only — scrapes and debug reads are not
# the service the objectives are declared over.
_SLO_ROUTES = frozenset({
    "/compute", "/compute_batch", "/compute_raw",
    "/programs/compute", "/programs/compute_batch", "/programs/compute_raw",
})

# Program-addressed compute (the registry surface): the <name> segment
# collapses out of the route label — program names are client-chosen, so
# they live in the registry's own `program`-labeled series (with their own
# cardinality guard), never in the route label.
_PROGRAM_OPS = ("compute", "compute_batch", "compute_raw")
_PROGRAM_COMPUTE_RE = re.compile(
    r"^/programs/([^/]+)/(compute|compute_batch|compute_raw)$"
)


def _route_label(path: str) -> str:
    route = path.split("?", 1)[0]
    if route.startswith("/debug/requests/"):
        return "/debug/requests"  # per-trace lookups share one label
    if route.startswith("/programs"):
        parts = route.split("/")
        if len(parts) >= 4 and parts[3] in _PROGRAM_OPS:
            return "/programs/" + parts[3]
        return "/programs"
    return route if route in _METRIC_ROUTES else "other"


class ComputeTimeout(RuntimeError):
    """The network produced no output for a /compute value in time."""


class BroadcastError(RuntimeError):
    """A control-plane fan-out failed on at least one node (master.go:288-292).

    Defined here (not in runtime.nodes, which raises it) so the shared HTTP
    surface can catch it without importing the grpc-dependent distributed
    module — the fused master must work with jax+numpy alone.
    """


class PeerUnavailable(RuntimeError):
    """A distributed compute refused fast because a peer the control plane
    tracks as DOWN cannot move values (runtime/nodes.py peer health).

    The alternative — letting the request park in the input queue until
    its full timeout — wedges every caller for 30s per request while the
    outcome is already known.  Raised only by the distributed control
    plane; the HTTP surface answers it as 503 (retryable: the request was
    refused before entering the pipeline, and service resumes without a
    master restart once the peer returns).  Defined here for the same
    grpc-free reason as BroadcastError.
    """


class CheckpointError(ValueError):
    """A checkpoint failed durability verification (truncated, checksum
    mismatch, or CRC-corrupt) and was rejected before any state swap.

    A ValueError subclass so the HTTP /restore route's existing error
    translation (400 with the reason) and every caller that treats bad
    checkpoint content as a value problem keep working unchanged.
    """


# Queue-drain sentinel: _drain_queues pushes one into every output queue
# after bumping the epoch, so a collector blocked in out_qs.get() learns of
# the wipe IMMEDIATELY instead of burning its full request timeout (a reset
# racing an in-flight request used to park that request — and its slot —
# for up to 30s).  A zero-length array so status()'s depth math reads it as
# 0 values; matched by IDENTITY, never by shape.
_WIPED = np.empty((0,), np.int32)


class _BatchEntry:
    """One request in the serve scheduler: values in, a future's worth of
    outputs back.  Counters (`taken`/`filled`) are guarded by the batcher's
    shared condition lock; `out` segments are written by exactly one pass
    each (disjoint slices), so the array itself needs no lock."""

    __slots__ = ("arr", "out", "taken", "filled", "deadline", "event",
                 "error", "enqueued", "dispatched", "cancelled", "traces")

    def __init__(self, arr: np.ndarray, deadline: float, traces=()):
        self.arr = arr
        self.out = np.empty((arr.size,), np.int32)
        self.taken = 0       # values cut into passes so far
        self.filled = 0      # values scattered back so far
        self.deadline = deadline
        self.event = threading.Event()
        self.error: BaseException | None = None
        self.enqueued = time.monotonic()
        self.dispatched = False  # first-dispatch latch (queue-delay metric)
        self.cancelled = False   # waiter gave up; skip undispatched remainder
        # request traces riding this entry (utils/tracespan.py): one for a
        # direct HTTP request, several when a compute-plane frame carries
        # many frontend requests in one entry.  serve.queue / serve.pass
        # spans are recorded into each; empty tuple = untraced (no cost).
        self.traces = traces


class _BatcherShared:
    """The scheduler queue state a parked worker thread may hold: it
    deliberately references NO master.  Workers hold a weakref to the
    batcher and this object strongly — so an idle worker never keeps a
    dead master (and its engine) alive, and exits within one poll interval
    of the master being collected."""

    __slots__ = ("cond", "pending", "hot", "inflight", "closed")

    def __init__(self):
        self.cond = threading.Condition()
        # two priority lanes: `hot` (latency-class small entries) is cut
        # into passes BEFORE `pending` (bulk) — an interactive request
        # admitted at the edge never queues behind a 64 MiB bulk body,
        # whose remaining stripes yield between passes
        self.pending: collections.deque[_BatchEntry] = collections.deque()
        self.hot: collections.deque[_BatchEntry] = collections.deque()
        self.inflight = 0   # passes currently executing
        self.closed = False

    def queues(self) -> tuple:
        return (self.hot, self.pending)

    def waiting(self) -> int:
        """Values enqueued but not yet cut into a pass (both lanes).
        Call under `cond`."""
        return sum(
            e.arr.size - e.taken for q in (self.hot, self.pending) for e in q
        )


def _batcher_worker(shared: _BatcherShared, ref) -> None:
    """Dispatcher/collector loop (see ServeBatcher).  Takes a strong
    batcher reference only while there is work; parks on the shared
    condition otherwise."""
    while True:
        with shared.cond:
            if shared.closed:
                return
            if not shared.pending and not shared.hot:
                shared.cond.wait(0.5)
            if shared.closed:
                return
            empty = not shared.pending and not shared.hot
        if empty:
            if ref() is None:  # master collected: wind the pool down
                with shared.cond:
                    shared.closed = True
                    shared.cond.notify_all()
                return
            continue
        batcher = ref()
        if batcher is None:
            with shared.cond:
                shared.closed = True
                shared.cond.notify_all()
            return
        try:
            batcher._pass_once()
        except Exception:  # pragma: no cover — a crashed pass must not
            log.exception("serve-scheduler pass crashed")  # kill the pool
        del batcher


class ServeBatcher:
    """Cross-request micro-batching between the HTTP handlers and the engine.

    The multi-tenant serving problem (ROADMAP: heavy traffic from millions
    of users): many concurrent clients each posting a handful of values.
    Before this scheduler, every such request exclusively claimed one of B
    instance slots and one submit/out queue round trip, so a 64-value
    request paid the same slot-and-queue toll as a 16k-value one and the
    engine ran nearly empty (6% ring fill measured at 64 clients).  This
    is the dynamic-batching layer every inference-serving stack grows:
    coalesce what's waiting, never wait when idle.

    Mechanics: callers enqueue (values, future) entries (`compute`); a
    small pool of dispatcher workers each repeatedly packs EVERYTHING
    currently waiting (FIFO, large entries split) into contiguous stripes
    across free instance slots — one input-ring refill per slot — submits
    the whole pass as ONE submission-queue entry, collects each stripe's
    outputs in order, and scatters contiguous output segments back to
    their entries' futures.  Per-slot FIFO plus contiguous striping makes
    the flat input order equal the flat output order, so pairing is exact
    by construction.

    Adaptive policy, no latency tax: an idle engine dispatches the first
    arrival immediately (a parked worker wakes on enqueue); coalescing
    happens only while passes are in flight, because that is when entries
    accumulate.  Knobs: MISAKA_BATCH_WINDOW_US adds an explicit coalesce
    window while a pass is in flight (default 0 — purely adaptive),
    MISAKA_BATCH_MAX caps values per fused pass (default: the machine,
    B x in_cap), MISAKA_BATCH_PASSES sets the worker count (default
    min(4, B) — enough overlap to pipeline collect against pack).

    Timeouts, stale-output accounting, and epoch invalidation all ride the
    master's existing per-slot machinery (_collect_slot): a timed-out or
    reset-wiped pass marks its uncollected stripes stale exactly like
    compute_spread, so a wiped request never pollutes a neighbor's pairing.
    """

    def __init__(self, master: "MasterNode", n_slots: int, in_cap: int):
        self._master = master
        self._n_slots = int(n_slots)
        self._in_cap = max(1, int(in_cap))
        self._max_values = int(
            os.environ.get("MISAKA_BATCH_MAX", "") or 0
        ) or self._n_slots * self._in_cap
        self._window_s = float(
            os.environ.get("MISAKA_BATCH_WINDOW_US", "") or 0
        ) / 1e6
        self._n_workers = int(
            os.environ.get("MISAKA_BATCH_PASSES", "") or 0
        ) or min(4, self._n_slots)
        # Priority-lane split (MISAKA_LANE_SMALL, values): entries at or
        # under this size ride the hot lane and preempt bulk backlog in
        # pass packing.  0 disables the split (everything is bulk).
        self._hot_max = int(os.environ.get("MISAKA_LANE_SMALL", "") or 8192)
        self._shared = _BatcherShared()
        self._started = False
        ref = weakref.ref(self)
        M_SERVE_WAITING.set_function(
            lambda: (
                len(b._shared.pending) + len(b._shared.hot)
                if (b := ref()) is not None else 0
            )
        )

    # --- the caller side ---------------------------------------------------

    def waiting_values(self) -> int:
        """Values enqueued but not yet cut into a pass (status gauge and
        the edge admission governor's live backlog signal)."""
        with self._shared.cond:
            return self._shared.waiting()

    def compute(self, arr: np.ndarray, timeout: float,
                traces=(), lane: str | None = None) -> np.ndarray:
        """Enqueue one request's value stream and wait for its outputs
        (len(arr) in, len(arr) out, order preserved).  `lane` pins the
        priority lane ("hot"/"bulk"); default classifies by size against
        MISAKA_LANE_SMALL — small latency-class entries are cut into
        passes ahead of bulk backlog."""
        self._ensure_workers()
        entry = _BatchEntry(arr, time.monotonic() + timeout, traces=traces)
        shared = self._shared
        master = self._master
        if lane is None:
            lane = "hot" if 0 < arr.size <= self._hot_max else "bulk"
        M_SERVE_LANE_ENTRIES.labels(lane=lane).inc()
        with shared.cond:
            (shared.hot if lane == "hot" else shared.pending).append(entry)
            shared.cond.notify()
        with master._waiters_lock:
            master._requests_total += 1
        M_COMPUTE_REQS.inc()
        M_COMPUTE_VALUES.inc(arr.size)
        usage.add_request(master.program_label, arr.size)
        if not entry.event.wait(timeout):
            with shared.cond:
                entry.cancelled = True  # skip the undispatched remainder
                missing = entry.arr.size - entry.filled
            M_COMPUTE_TIMEOUTS.inc()
            raise ComputeTimeout(
                f"no output for {missing}/{entry.arr.size} value(s) "
                f"after {timeout}s"
            )
        if entry.error is not None:
            if isinstance(entry.error, ComputeTimeout):
                M_COMPUTE_TIMEOUTS.inc()
            raise entry.error
        return entry.out

    def _ensure_workers(self) -> None:
        """Start the dispatcher pool on first use: tests build masters by
        the hundred, and a master that never serves coalesced traffic must
        not own threads."""
        if self._started:
            return
        with self._shared.cond:
            if self._started:
                return
            ref = weakref.ref(self)
            for i in range(self._n_workers):
                threading.Thread(
                    target=_batcher_worker, args=(self._shared, ref),
                    daemon=True, name=f"misaka-batcher-{i}",
                ).start()
            self._started = True

    # --- the dispatcher side (worker threads) ------------------------------

    def _acquire_slots(self, want: int) -> list[int]:
        """Try-acquire up to `want` free instance slots, scanning from the
        master's rotating start (no blocking: a pass never deadlocks
        against direct compute_many/compute_spread callers)."""
        master = self._master
        n = self._n_slots
        with master._rr_lock:
            start = master._rr
            master._rr = (master._rr + 1) % n
        slots: list[int] = []
        for i in range(n):
            s = (start + i) % n
            if master._compute_locks[s].acquire(blocking=False):
                slots.append(s)
                if len(slots) >= want:
                    break
        return slots

    def _pass_once(self) -> None:
        """Pack everything currently waiting into one fused pass, run it,
        scatter the outputs.  Called from a worker thread."""
        master = self._master
        shared = self._shared
        # Optional explicit coalesce window: only while another pass is in
        # flight (an idle engine must dispatch immediately — no latency tax).
        if self._window_s > 0:
            with shared.cond:
                if shared.inflight and (shared.pending or shared.hot):
                    deadline = time.monotonic() + self._window_s
                    while shared.waiting() < self._max_values:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        shared.cond.wait(remaining)
        with shared.cond:
            waiting = shared.waiting()
        if waiting <= 0:
            return
        want = min(
            self._n_slots, -(-min(waiting, self._max_values) // self._in_cap)
        )
        slots = self._acquire_slots(want)
        if not slots:
            # every instance is busy (other passes or direct compute
            # callers): wait for a release instead of spinning — pass
            # completion notifies this condition.
            with shared.cond:
                if (shared.pending or shared.hot) and not shared.closed:
                    shared.cond.wait(0.05)
            return
        # --- cut: FIFO segments off the waiting entries, splitting a large
        # tail entry so the pass fills exactly what its slots can refill.
        # The HOT lane cuts first: a bulk entry's remaining stripes yield
        # to every latency-class entry that arrived since the last pass.
        # Anti-starvation: when BOTH lanes wait, the hot lane is capped
        # at 3/4 of the pass budget — strict priority under a sustained
        # hot stream would park an already-ADMITTED bulk entry until it
        # died of ComputeTimeout, the exact death admission control
        # promises admitted work never suffers ---
        budget = min(len(slots) * self._in_cap, self._max_values)
        segs: list[tuple[_BatchEntry, int, int]] = []
        with shared.cond:
            # the queue-delay clock reads INSIDE the lock: an entry
            # enqueued between an outside read and the acquisition would
            # observe a negative delay (seen as a negative serve.queue
            # span in the Perfetto export)
            now = time.monotonic()
            reserve = (
                max(1, budget // 4) if (shared.hot and shared.pending)
                else 0
            )
            caps = (max(1, budget - reserve), budget)
            for queue, cap in zip(shared.queues(), caps):
                while queue and budget > 0 and cap > 0:
                    e = queue[0]
                    if e.cancelled:
                        queue.popleft()
                        continue
                    take = min(budget, cap, e.arr.size - e.taken)
                    if not e.dispatched:
                        e.dispatched = True
                        M_SERVE_QUEUE_DELAY.observe(now - e.enqueued)
                        usage.add_queue(
                            master.program_label, now - e.enqueued
                        )
                        for tr in e.traces:
                            tracespan.add_span(
                                tr, "serve.queue", e.enqueued,
                                now - e.enqueued
                            )
                    segs.append((e, e.taken, take))
                    e.taken += take
                    budget -= take
                    cap -= take
                    if e.taken >= e.arr.size:
                        queue.popleft()
            if segs:
                shared.inflight += 1
        if not segs:  # another worker drained the queue first
            for s in slots:
                master._compute_locks[s].release()
            return
        try:
            self._run_pass(slots, segs)
        finally:
            with shared.cond:
                shared.inflight -= 1
                shared.cond.notify_all()  # slots freed; window waiters wake

    def _run_pass(
        self,
        slots: list[int],
        segs: list[tuple[_BatchEntry, int, int]],
    ) -> None:
        """One fused engine pass: stripe, submit, collect, scatter.
        Releases every slot in `slots`."""
        master = self._master
        shared = self._shared
        if faults.armed():
            # chaos point (utils/faults.py): inject latency into this
            # program's serve path — `serve_delay` hits every pass,
            # `serve_delay:<program>` only the named tenant's (the SLO
            # chaos scenario: one tenant's alerts flip to page while its
            # neighbors stay green, tests/test_slo.py)
            delay = faults.fire("serve_delay")
            if delay is None:
                delay = faults.fire(
                    f"serve_delay:{master.program_label or usage.DEFAULT_LABEL}"
                )
            if delay is not None:
                time.sleep(max(0.0, delay))
        t_pass = time.monotonic()
        if len(segs) == 1:
            e0, s0, ln = segs[0]
            flat = e0.arr[s0:s0 + ln]  # zero-copy: the big-batch fast path
        else:
            flat = np.concatenate([e.arr[s0:s0 + ln] for e, s0, ln in segs])
        total = int(flat.size)
        n_used = min(len(slots), -(-total // self._in_cap))
        used, unused = slots[:n_used], slots[n_used:]
        for s in unused:
            master._compute_locks[s].release()
        stripes = np.array_split(flat, n_used)
        M_SERVE_COALESCED_VALUES.observe(total)
        M_SERVE_COALESCED_REQS.observe(len(segs))
        M_SERVE_PASSES.inc()
        deadline = max(e.deadline for e, _, _ in segs)
        timeout_s = max(0.0, deadline - time.monotonic())
        with master._waiters_lock:
            master._waiters += 1

        def record_pass_spans(bill: bool = True) -> None:
            # one serve.pass span per traced request in the pass — the
            # coalesced requests share identical pass timing, which is
            # exactly what makes them stack on one pass in Perfetto.
            # MUST run before any e.event.set(): a woken waiter builds
            # its Server-Timing header from the spans recorded so far,
            # and the pass phase has to be there by then.
            dur = time.monotonic() - t_pass
            # usage accounting (runtime/usage.py): this batcher serves
            # exactly ONE program (per-program engines since r11), so
            # the whole pass wall bills to it in one call — a
            # per-segment slot-share loop would re-sum to the same
            # number while paying a lock + labeled inc per request on
            # the hot path (note_pass is the independently-accumulated
            # anchor the conservation test compares against).
            # Success-only, like the direct lane: a ComputeTimeout must
            # not charge the tenant the whole timeout window as CPU, and
            # skipping note_pass with it keeps conservation exact.
            if bill:
                usage.note_pass(dur)
                usage.add_cpu(master.program_label, dur)
            attrs = {
                "requests": len(segs), "values": total, "slots": n_used,
            }
            if master.program_label is not None:
                # which registry tenant this pass served (the trace-side
                # twin of the metrics plane's `program` label)
                attrs["program"] = master.program_label
            for e, _, _ in segs:
                for tr in e.traces:
                    tracespan.add_span(tr, "serve.pass", t_pass, dur, attrs)

        # the pass's request-trace IDs, visible to the native pool call
        # that serves it (r18 flight recorder: the pool serve runs on the
        # device-loop thread, where no request contextvar exists)
        trace_token = master._trace_ids_enter(
            dict.fromkeys(
                tr.trace_id for e, _, _ in segs for tr in e.traces
            )
        )
        try:
            with master._epoch_lock:
                epoch = master._epoch
                master._submit_q.put(list(zip(used, stripes)))
            master._work_event.set()
            parts: list[np.ndarray] = []
            try:
                for i, (s, stripe) in enumerate(zip(used, stripes)):
                    parts.extend(
                        master._collect_slot(
                            s, stripe.size, deadline, epoch, timeout_s
                        )
                    )
            except ComputeTimeout:
                # the stripes never collected will surface outputs too —
                # mark those slots stale so their pairing survives (the
                # compute_spread discipline)
                with master._epoch_lock:
                    if master._epoch == epoch:
                        for s2, st2 in list(zip(used, stripes))[i + 1:]:
                            master._stale[s2] += st2.size
                raise
            flat_out = np.concatenate(parts)
            record_pass_spans()  # before any waiter wakes (see above)
            # scatter-gather: per-slot FIFO + contiguous striping means the
            # flat output order IS the flat input order — segment j's
            # outputs are flat_out[pos_j : pos_j + len_j], exactly.
            pos = 0
            done: list[_BatchEntry] = []
            with shared.cond:
                for e, s0, ln in segs:
                    e.out[s0:s0 + ln] = flat_out[pos:pos + ln]
                    pos += ln
                    e.filled += ln
                    if e.filled >= e.arr.size:
                        done.append(e)
            for e in done:
                e.event.set()
        except Exception as exc:
            record_pass_spans(bill=False)  # before the failed waiters wake
            msg = f"{exc} (coalesced pass: {len(segs)} request(s), " \
                  f"{total} values)"
            failed: list[_BatchEntry] = []
            with shared.cond:
                for e, _, _ in segs:
                    if e.error is None:
                        e.error = (
                            ComputeTimeout(msg)
                            if isinstance(exc, ComputeTimeout) else exc
                        )
                    # a failed entry's undispatched remainder must not keep
                    # claiming slots and engine passes (its caller already
                    # raised) — cancel it like a waiter timeout does
                    e.cancelled = True
                    failed.append(e)
            for e in failed:
                e.event.set()
        finally:
            master._trace_ids_exit(trace_token)
            with master._waiters_lock:
                master._waiters -= 1
            for s in used:
                master._compute_locks[s].release()


def _fsync_dir(directory: str) -> None:
    """Make a rename in `directory` durable (best-effort: some filesystems
    refuse O_RDONLY fsync on directories; the rename is still atomic)."""
    try:
        dfd = os.open(directory or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(dfd)
    except OSError:
        pass
    finally:
        os.close(dfd)


def manifest_path(path: str) -> str:
    """The durability sidecar for a checkpoint file (size + sha256)."""
    return path + ".manifest"


def _zip_intact(path: str) -> str | None:
    """None when `path` is a structurally valid zip whose every member
    passes CRC; otherwise the reason it is not.  Truncation at any offset
    fails here (a zip's central directory lives at its END), and member
    corruption fails CRC."""
    import zipfile

    try:
        with zipfile.ZipFile(path) as z:
            bad = z.testzip()
        return f"CRC mismatch in member {bad!r}" if bad is not None else None
    except zipfile.BadZipFile as e:
        return f"not a readable npz ({e})"
    except OSError as e:
        return f"unreadable ({e})"


def verify_checkpoint(path: str) -> None:
    """The durability gate: raise CheckpointError unless `path` matches its
    manifest (exact size + sha256), so a file truncated at ANY byte offset
    or bit-flipped anywhere is rejected BEFORE np.load touches it (and long
    before any engine/state swap).

    Two fallbacks ride the zip CRC walk (_zip_intact), which also rejects
    truncation and member corruption: (a) checkpoints written before the
    manifest era have no sidecar at all; (b) a STALE manifest — the save
    path commits the npz before its manifest, so a crash between the two
    renames leaves a fully valid new file described by the previous
    manifest.  A mismatched-but-intact file is therefore accepted (the
    committed data survives the crash); a mismatched file that also fails
    the CRC walk is rejected as corrupt.
    """
    import hashlib

    def reject(reason: str) -> CheckpointError:
        M_CKPT_REJECTED.inc()
        return CheckpointError(f"checkpoint {path} rejected: {reason}")

    def mismatch(reason: str) -> None:
        broken = _zip_intact(path)
        if broken is not None:
            raise reject(f"{reason}; {broken}")
        log.warning(
            "checkpoint %s: %s, but the file is an intact npz — accepting "
            "(a crash between the data and manifest renames leaves exactly "
            "this: committed data, stale sidecar)", path, reason,
        )

    mpath = manifest_path(path)
    if os.path.exists(mpath):
        try:
            with open(mpath) as f:
                manifest = json.load(f)
            want_size = int(manifest["size"])
            want_sha = str(manifest["sha256"])
        except (OSError, ValueError, KeyError, TypeError) as e:
            raise reject(f"unreadable manifest {mpath} ({e})") from e
        try:
            size = os.path.getsize(path)
        except OSError as e:
            raise reject(f"unreadable ({e})") from e
        if size != want_size:
            mismatch(
                f"{size} bytes on disk vs {want_size} in the manifest "
                f"(torn write or stale manifest)"
            )
            return
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        if h.hexdigest() != want_sha:
            mismatch("sha256 mismatch against the manifest")
        return
    broken = _zip_intact(path)
    if broken is not None:
        raise reject(broken)


class AutoCheckpointer:
    """Periodic durable snapshots with rotation, plus boot-time restore.

    MISAKA_AUTOCKPT=N seconds arms this on the serving master (app.py):
    every interval the LIVE state is checkpointed into the checkpoint
    directory as `auto-<seq>.npz` under save_checkpoint's full durability
    contract (tmp + fsync + atomic replace + manifest), and rotation keeps
    only the newest `keep` snapshots (MISAKA_AUTOCKPT_KEEP, default 4).
    `restore_latest` is the boot half: walk the auto snapshots newest-
    first and install the first that passes verify_checkpoint — one torn
    or corrupt snapshot costs one interval of history, never a boot.
    """

    PREFIX = "auto-"
    _NAME_RE = re.compile(r"^auto-(\d+)\.npz$")

    def __init__(self, master, directory: str, interval_s: float,
                 keep: int = 4):
        if interval_s <= 0:
            raise ValueError(f"interval must be > 0, got {interval_s}")
        self._master = master
        self._dir = directory
        self._interval = float(interval_s)
        self._keep = max(1, int(keep))
        existing = self.snapshots(directory)
        self._seq = (
            int(self._NAME_RE.match(os.path.basename(existing[0])).group(1))
            if existing else 0
        )
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="misaka-autockpt"
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    @classmethod
    def snapshots(cls, directory: str) -> list[str]:
        """auto-*.npz paths in `directory`, newest (highest seq) first."""
        try:
            names = os.listdir(directory)
        except OSError:
            return []
        seqs = []
        for n in names:
            m = cls._NAME_RE.match(n)
            if m:
                seqs.append((int(m.group(1)), n))
        return [os.path.join(directory, n) for _, n in sorted(seqs, reverse=True)]

    def save_once(self) -> str:
        """One durable snapshot + rotation (also the thread's body)."""
        self._seq += 1
        os.makedirs(self._dir, exist_ok=True)
        path = os.path.join(self._dir, f"{self.PREFIX}{self._seq:08d}.npz")
        self._master.save_checkpoint(path)
        for old in self.snapshots(self._dir)[self._keep:]:
            for stale in (old, manifest_path(old)):
                try:
                    os.unlink(stale)
                except OSError:
                    pass
        return path

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.save_once()
            except Exception:  # keep snapshotting: one failure is one
                log.exception(  # interval of lost history, not a dead plane
                    "auto-checkpoint failed (retrying next interval)"
                )

    @classmethod
    def restore_latest(cls, master, directory: str) -> str | None:
        """Install the newest VALID auto snapshot; returns its path, or
        None when none exists/verifies (a fresh boot)."""
        for path in cls.snapshots(directory):
            try:
                master.load_checkpoint(path)
                return path
            except Exception as e:
                log.warning(
                    "auto-restore: skipping snapshot %s (%s); falling back",
                    path, e,
                )
        return None


class MasterNode:
    """Control plane + I/O gateway for one fused network."""

    def __init__(
        self,
        topology: Topology,
        chunk_steps: int = 128,
        trace_cap: int | None = None,
        batch: int | None = None,
        engine: str = "auto",
        trace_instance: int = 0,
        data_parallel: int | None = None,
        model_parallel: int | None = None,
        stripe: int | None = None,
        stack_autogrow: bool = True,
        stack_grow_max_bytes: int = 256 * 1024 * 1024,
        native_spec_dir: str | None = None,
    ):
        """batch=None serves one network instance (every /compute strictly
        serialized — the correlated fix for quirk #2).  batch=B runs B
        independent instances in lockstep (the engine's vmap axis) and
        round-robins concurrent /compute requests across them: up to B
        requests progress in parallel, each instance's request/response
        pairing still strictly FIFO.  The reference allows concurrency only
        by racing (master.go:216-219 swaps responses); this is the
        deterministic version of that capability.

        engine selects the device-loop chunk runner:
          * "auto"  — the Pallas fused kernel (core/fused.py) when it applies
                      (batched, untraced, on TPU, within the VMEM budget),
                      the XLA scan engine otherwise;
          * "scan"  — always the XLA scan engine;
          * "fused" — require the fused kernel (raise when it can't serve);
          * "fused-interpret" — fused kernel in Pallas interpret mode (slow;
                      CI coverage of the fused serving path off-TPU);
          * "gather" — (model-parallel only) the first-generation sharded
                      kernel (parallel/sharded.py, per-tick occupancy
                      all_gather); kept for A/B measurement against the
                      default statically-routed kernel (parallel/routed.py);
          * "native" — the host C++ interpreter (core/native_serve.py):
                      ZERO device dispatches on the request path.
                      batch=None serves one instance (the interactive-
                      latency tier: a /compute costs queue hops + a ~us
                      host chunk instead of a device round trip, which on
                      a relayed chip is 72-103ms); batch=B serves B
                      replica interpreters sharded across OS threads
                      (the host THROUGHPUT tier — the fallback that keeps
                      served throughput past 1M/s with no TPU attached).
                      No tracing, no mesh; needs a C++ toolchain (raises
                      otherwise).  engine="auto" prefers this tier
                      whenever no TPU is attached (see _use_native_auto).

        trace_cap with batch traces instance `trace_instance` (instances are
        independent, so its history is exact); tracing always runs the scan
        engine — it is the debug path, not the throughput path.

        data_parallel=D / model_parallel=M serve over a jax.sharding.Mesh of
        D*M devices — the product replacement for the reference's scale-out
        by docker-compose containers (docker-compose.yml:26-74):
          * data   — the batch axis shards over D chips: D independent
                     engine replicas in one jit, zero cross-chip traffic;
          * model  — program-node lanes shard over M chips; inter-lane MOV /
                     stack / ring traffic rides ICI collectives.  The default
                     kernel is the statically-routed two-collective one
                     (parallel/routed.py); engine="gather" selects the
                     first-generation occupancy-gather kernel
                     (parallel/sharded.py) for A/B comparison.
        Tracing is single-chip-only (the debug path).
        """
        if batch is not None and batch < 1:
            raise ValueError(f"batch must be >= 1, got {batch}")
        if engine not in (
            "auto", "scan", "fused", "fused-interpret", "gather", "native"
        ):
            raise ValueError(
                f"engine must be auto|scan|fused|fused-interpret|gather|"
                f"native, got {engine!r}"
            )
        if engine == "native":
            # the host-interpreter tier (core/native_serve.py): single
            # instance (latency) or B thread-pooled replicas (throughput);
            # single chip, untraced by construction
            if trace_cap:
                raise ValueError("tracing runs the scan engine (the debug "
                                 "path), not the native engine")
            if data_parallel or model_parallel:
                raise ValueError("engine='native' is single-chip (host) "
                                 "serving")
        if engine == "gather" and not (model_parallel and model_parallel > 1):
            raise ValueError("engine='gather' requires model_parallel > 1")
        if trace_cap and not (0 <= trace_instance < (batch or 1)):
            raise ValueError(
                f"trace_instance {trace_instance} out of range [0, {batch or 1})"
            )
        self._topology = topology
        self._chunk = chunk_steps
        self._batch = batch
        self._engine = engine
        # Per-program native specialization (core/specialize.py): armed by
        # naming a compile cache dir — the registry passes one next to its
        # version store, app.py passes the shared per-user cache.  Direct
        # constructions (tests, library use) stay un-specialized so a
        # MasterNode never surprises its caller with a 2s g++ run.
        self._native_spec_dir = native_spec_dir
        # Stack auto-grow (reference parity: intStack.go:9-45 grows without
        # limit, while XLA shapes are static): when a full stack wedges the
        # network mid-request, the device loop doubles stack capacity —
        # recompile + state pad, geometric growth — up to a byte budget.
        self._grow = bool(stack_autogrow)
        self._grow_max_bytes = int(stack_grow_max_bytes)
        self._stall_iters = 0
        # warn-once latch for a wedge growth cannot fix (budget/engine);
        # cleared when anything moves again or on reset/load
        self._grow_blocked = False
        # compute_spread stripe size (values per instance per request).
        # Default = the input-ring capacity: each stripe fits one refill.
        # Larger stripes spread a request over fewer instances — less
        # per-slot host work (locks, queue hops, drain entries) at the cost
        # of device-side parallel coverage; the serve path is host-bound
        # well past B=1024, so moderate multiples win (see bench.py).
        self._stripe = int(stripe) if stripe else None
        self._mesh = None
        self._dp = self._mp = 1
        if data_parallel or model_parallel:
            dp = int(data_parallel or 1)
            mp = int(model_parallel or 1)
            if batch is None:
                raise ValueError("mesh serving requires batch=N")
            if batch % dp:
                raise ValueError(f"batch {batch} not divisible by data_parallel={dp}")
            if trace_cap:
                raise ValueError("tracing is single-chip-only (the debug path)")
            from misaka_tpu.parallel.mesh import make_mesh

            self._mesh = make_mesh(dp * mp, model_parallel=mp)
            self._dp, self._mp = dp, mp
        self._net = topology.compile(batch=batch)
        if self._mp > 1 and self._net.num_lanes % self._mp:
            raise ValueError(
                f"{self._net.num_lanes} lanes not divisible by "
                f"model_parallel={self._mp}"
            )
        self._state = self._shard(self._net.init_state())
        # Optional per-lane instruction trace ring (core/trace.py).  The debug
        # path: every tick of every lane is recorded device-side and decoded
        # on demand via self.trace() / GET /trace.  Batched masters trace one
        # selectable instance (engine.run_traced).
        self._trace_cap = trace_cap
        self._trace_instance = trace_instance
        self._trace = self._net.init_trace(trace_cap) if trace_cap else None
        self._runner = self._make_runner(self._net)
        self._batched_serve = self._make_serve_fns(self._net, self._runner)
        self._running = False
        self._loop: threading.Thread | None = None
        self._state_lock = threading.Lock()      # guards _state/_net swaps
        self._lifecycle_lock = threading.RLock() # serializes run/pause/reset/load
        # Unbatched: one global pairing lock + one queue pair.  Batched: a
        # queue pair + pairing lock + stale counter PER INSTANCE, and a
        # round-robin dispenser.  Queue payloads are int32 ARRAYS (request
        # chunks), not scalars: host cost per value must stay O(1/chunk) for
        # the served path to reach engine rates.
        n_slots = batch or 1
        self._n_slots = n_slots
        self._compute_locks = [threading.Lock() for _ in range(n_slots)]
        # ONE submission queue for all slots (payload: a list of
        # (slot, int32-array) pairs, one entry per request): the device loop
        # must never scan B per-slot queues per iteration — at B=8192 the
        # lock traffic alone dominates the serve path.
        self._submit_q = queue.Queue()
        self._out_qs = [queue.Queue() for _ in range(n_slots)]
        # Device-loop-private spillover: submitted chunks that did not fully
        # fit the device input ring yet, plus the set of slots with spillover
        # (only the loop thread and post-pause lifecycle code touch these).
        self._in_pending = [[] for _ in range(n_slots)]
        self._active: set[int] = set()
        # Surplus outputs beyond a request's expectation (non-1:1 networks),
        # held FIFO for the slot's next caller; guarded by the slot lock.
        self._out_leftover = [np.empty((0,), np.int32) for _ in range(n_slots)]
        self._rr = 0
        self._rr_lock = threading.Lock()
        # Outputs orphaned by /compute timeouts; discarded on arrival so the
        # request/response pairing stays correlated (quirk #2 stays fixed).
        # The epoch invalidates that bookkeeping across reset/load: a compute
        # whose request was wiped by a queue drain must NOT mark its missing
        # output as stale (there is no output coming — a phantom stale entry
        # would mispair every later request on the slot).  _epoch_lock makes
        # the (read epoch, enqueue) pair atomic against _drain_queues — a
        # drain between them would otherwise leave an orphan output that
        # mispairs every later request on the slot.
        self._stale = [0] * n_slots
        self._epoch = 0
        self._epoch_lock = threading.Lock()
        # Idle discipline: the loop parks on _work_event instead of polling;
        # enqueues set it.  _waiters counts in-flight compute requests — the
        # loop never sleeps while one is waiting (serve-path latency is then
        # bounded by chunk time, not a sleep quantum).
        self._work_event = threading.Event()
        self._waiters = 0
        self._waiters_lock = threading.Lock()
        # Host-side tick-rate gauge, maintained solely by the device loop
        # (readers of /status never mutate it).
        self._ticks_done = 0
        self._rate: float | None = None
        self._rate_mark_tick = 0
        self._rate_mark_time = time.monotonic()
        # Observability plane: creation time anchors /status uptime_seconds;
        # requests_total is the per-master cumulative (under _waiters_lock,
        # which both compute lanes already take).  The process-global queue-
        # depth gauges read THIS master through weakrefs at scrape time —
        # zero device-loop cost, and a collected master reads as 0.
        self._created_mono = time.monotonic()
        self._requests_total = 0
        # Which registry program this engine serves (runtime/registry.py
        # sets it; None outside the registry).  Rides serve.pass trace
        # spans and /status so multi-tenant traffic stays attributable.
        self.program_label: str | None = None
        # checkpoint freshness anchor (misaka_checkpoint_age_seconds):
        # stamped by every successful save_checkpoint on this master
        self._last_ckpt_mono: float | None = None
        # Loop-private per-slot in-flight value counts (fed minus drained):
        # the native tier's partial-fill fast path ticks only slots that
        # are fed now or still owe outputs.  Maintained solely by the
        # device loop (and _drain_queues, which runs with the loop joined).
        self._inflight = np.zeros((n_slots,), np.int64)
        # Partial-fill hot set (loop-private): a replica that retired any
        # instruction last chunk may still hold in-flight values INSIDE the
        # network (ports/registers) even when fed-minus-drained reads 0
        # (non-1:1 programs), so it keeps ticking until a whole chunk
        # retires nothing.  _retired_prev=None forces one full-batch pass
        # (boot and every lifecycle state swap).
        self._native_hot = np.zeros((n_slots,), bool)
        self._retired_prev: np.ndarray | None = None
        # _build_feed's reusable buffers (loop thread only)
        self._feed_vals: np.ndarray | None = None
        self._feed_counts: np.ndarray | None = None
        # Restore-flush: a checkpoint/snapshot can carry values that were
        # in flight when it was taken; reinstalling it resurrects them,
        # and their outputs belong to requests that no longer exist.  The
        # device loop runs the restored network to quiescence DISCARDING
        # outputs before it ingests new work, so an orphan can never
        # mispair a post-restore request (see _device_loop_inner).
        self._restore_flush = False
        self._flush_iters = 0
        self._flush_quiet = 0
        # Active pass-trace registry (r18 native flight recorder): the
        # request-trace IDs of every submit->collect window currently in
        # flight on this master.  The serve scheduler and the direct
        # compute lanes register their traced requests here; the native
        # pool (whose serve call runs on the DEVICE-LOOP thread — the
        # caller's contextvar never reaches it) reads the union per pool
        # call and stamps it onto its flight-recorder correlation window,
        # which is what lets /debug/perfetto hang worker-thread unit
        # spans under the same trace ID as http.parse.
        self._pass_traces_lock = threading.Lock()
        self._pass_traces: dict[int, tuple] = {}
        self._pass_trace_next = 0
        # The serve scheduler (cross-request micro-batching): concurrent
        # compute/compute_raw/compute_batch callers coalesce into fused
        # engine passes instead of each claiming an instance slot.
        # MISAKA_SERVE_BATCH=0 restores the direct slot-per-request
        # behavior (MISAKA_BATCH is the instance count, app.py).
        self._batcher = None
        if os.environ.get("MISAKA_SERVE_BATCH", "1") != "0":
            self._batcher = ServeBatcher(self, n_slots, self._net.in_cap)
        ref = weakref.ref(self)
        M_SUBMIT_DEPTH.set_function(
            lambda: m._submit_q.qsize() if (m := ref()) is not None else 0
        )
        M_OUT_DEPTH.set_function(
            lambda: sum(q.qsize() for q in m._out_qs)
            if (m := ref()) is not None else 0
        )
        M_CKPT_AGE.set_function(
            lambda: time.monotonic() - m._last_ckpt_mono
            if (m := ref()) is not None and m._last_ckpt_mono is not None
            else -1.0
        )

    def _shard(self, state):
        """Place a state pytree onto the serving mesh (no-op off-mesh)."""
        if self._mesh is None:
            return state
        from misaka_tpu.parallel.mesh import shard_state

        return shard_state(state, self._mesh, batched=True)

    @staticmethod
    def _owned_device_state(state):
        """Every leaf as an XLA-OWNED buffer (device copy).

        jnp.asarray of a host numpy array (np.load'ed checkpoints, native-
        engine exports, snapshot copies) can be a ZERO-COPY alias of the
        numpy buffer on CPU.  The serve jits DONATE their state argument,
        and donating a borrowed buffer lets XLA reuse memory the numpy
        owner later frees — observed on jax 0.4.x CPU as flaky stale-ring
        outputs and heap corruption after /restore.  One copy per
        lifecycle event (restore/load_checkpoint only) is cheap insurance
        on every version."""
        import jax
        import jax.numpy as jnp

        return jax.tree.map(lambda x: jnp.asarray(x).copy(), state)

    def _use_native_auto(self) -> bool:
        """Should engine="auto" serve through the host C++ tier?

        Yes whenever no TPU is attached (the XLA scan engines measured
        ~0.2-0.3M served inputs/s on CPU while the native tier clears the
        1M north star), the toolchain can build the interpreter, and the
        configuration is one the native tier supports: no tracing, no
        mesh, and a batch small enough that per-replica bookkeeping stays
        cheap (MISAKA_NATIVE_AUTO_MAX_BATCH, default 4096 — an explicit
        engine="native" accepts any batch).  Disable outright with
        MISAKA_NATIVE_AUTO=0.
        """
        if self._trace_cap or self._mesh is not None:
            return False
        if os.environ.get("MISAKA_NATIVE_AUTO", "1") == "0":
            return False
        import jax

        if jax.devices()[0].platform == "tpu":
            return False
        from misaka_tpu.core import native_serve

        if not native_serve.available():
            return False
        max_batch = int(
            os.environ.get("MISAKA_NATIVE_AUTO_MAX_BATCH", "") or "4096"
        )
        return self._batch is None or self._batch <= max_batch

    def _make_runner(self, net):
        """Bind the device-loop chunk runner for `net` (see __init__ docstring).

        Returns fn(state) -> state advancing exactly self._chunk ticks via the
        fused Pallas kernel or the mesh-sharded engine, a native host engine
        (NativeServe / NativeServePool — dispatched on .is_native), or None
        to run the XLA scan engine.  This is the round-2 closure of the
        round-1 gaps: the fast kernel and the multi-chip path now serve the
        product HTTP surface, not just the bench/test harnesses.
        """
        eng = self._engine
        if eng == "auto" and self._use_native_auto():
            eng = "native"
        if eng == "native":
            # __init__ already rejected trace/mesh combinations; the serve
            # loop dispatches on the returned object's .serve_chunk
            # (unbatched) or the (serve, idle) twin pair (batched pool)
            from misaka_tpu.core import specialize
            from misaka_tpu.core.native_serve import NativeServe, NativeServePool

            if self._batch is None:
                runner = NativeServe(net)
            else:
                # The native tick ladder, top rung first (r21): try the
                # copy-and-patch JIT splice (stencil library compiled once
                # per toolchain version, per-program activation is pure
                # splice/patch — no g++ on the hot path), then per-program
                # specialized tick functions (compile-once per content
                # hash, cached on disk).  Every rung falls back gracefully
                # on ANY failure, and both are only worth it when at least
                # one full SIMD group exists (kGroupW = 8).  The same
                # cache-dir gate keeps direct constructions (tests,
                # library use) from surprising their caller with a g++
                # run.
                spec_so = None
                jit_prog = None
                if self._native_spec_dir is not None and self._batch >= 8:
                    from misaka_tpu.core import jit as jit_mod

                    if jit_mod.enabled() and jit_mod.supported():
                        jit_prog = jit_mod.prepare(
                            net, cache_dir=self._native_spec_dir
                        )
                    if jit_prog is None and specialize.enabled():
                        spec_so = specialize.build(
                            net, cache_dir=self._native_spec_dir
                        )
                runner = NativeServePool(
                    net, chunk_steps=self._chunk, specialized=spec_so,
                    jit_program=jit_prog,
                )
            # usage attribution: the runner bills its measured native time
            # to THIS master's program.  Read through a weakref at call
            # time — the registry names engines (program_label) after
            # construction, and the lambda must not keep a closed master
            # alive through its runner.
            mref = weakref.ref(self)
            runner.usage_label = lambda: (
                (m.program_label or usage.DEFAULT_LABEL)
                if (m := mref()) is not None else usage.DEFAULT_LABEL
            )
            if hasattr(runner, "active_trace_ids"):
                # flight-recorder correlation (r18): the pool reads the
                # trace IDs of this master's in-flight passes per serve
                # call — same weakref discipline as usage_label
                runner.active_trace_ids = lambda: (
                    m.active_pass_trace_ids()
                    if (m := mref()) is not None else ()
                )
            return runner
        if self._mp > 1:
            # Lane-sharded serving: the statically-routed two-collective
            # kernel (parallel/routed.py) is THE model-parallel path;
            # engine="gather" selects the first-generation occupancy-gather
            # kernel (parallel/sharded.py) for A/B measurement.
            if eng in ("fused", "fused-interpret"):
                raise ValueError(
                    "model-parallel serving uses the routed engine "
                    "(engine='auto', 'scan', or 'gather')"
                )
            if eng == "gather":
                from misaka_tpu.parallel.sharded import make_sharded_runner

                return make_sharded_runner(
                    net.code, net.prog_len, self._mesh,
                    num_steps=self._chunk, batched=True,
                )
            from misaka_tpu.parallel.routed import make_routed_runner

            return make_routed_runner(
                net.code, net.prog_len, self._mesh, num_steps=self._chunk,
                batched=True,
            )
        if self._trace_cap or self._batch is None:
            if eng in ("fused", "fused-interpret"):
                raise ValueError(
                    "fused engine requires batch=N and no trace_cap "
                    "(tracing runs the scan engine)"
                )
            return None
        if eng == "scan":
            return None
        if eng == "auto":
            import jax

            if jax.devices()[0].platform != "tpu":
                return None
        if self._mesh is not None:
            try:
                return self._make_dp_fused_runner(net)
            except ValueError:
                if eng == "auto":
                    return None
                raise
        # Big-cap topologies (e.g. the engine-default 1024-deep rings) can
        # exceed the kernel's VMEM budget at the default batch block; a
        # smaller block trades grid iterations for residency, so walk down
        # before giving up — the chunked storage mode plus a 128-wide block
        # serves everything the scan engine does.
        try:
            runner, _ = net.fused_runner_walk(
                self._chunk, interpret=(eng == "fused-interpret")
            )
            return runner
        except ValueError:
            if eng == "auto":
                # nothing fits (or non-TPU shapes): the scan engine serves
                # everything the kernel can't
                return None
            raise

    def _make_serve_fns(self, net, runner):
        """The batched one-dispatch (serve, idle) jit pair, or None where
        the piecewise loop must run (unbatched or tracing).

        Mesh serving fuses too: the sharded chunk's un-jitted body
        (runner.inner) is inlined into the combined serve jit, so a mesh
        iteration costs one dispatch + one packed read exactly like the
        single-chip batched path — XLA propagates the state shardings
        through the feed/snapshot ops around the shard_map'd chunk.
        """
        if self._batch is None or self._trace_cap:
            return None
        if getattr(runner, "is_native", False):
            # the host pool IS the batched serve pair: same signatures,
            # same packed layout, zero dispatches (core/native_serve.py)
            return runner.serve, runner.idle
        if self._mesh is not None:
            inner = getattr(runner, "inner", None)
            if inner is None:  # a runner shape without a fusable body
                return None
            return net.make_batched_serve(inner, self._chunk)
        return net.make_batched_serve(runner, self._chunk)

    def _make_dp_fused_runner(self, net):
        """The fused Pallas kernel under shard_map over the `data` axis: each
        chip runs the whole kernel on its batch shard (pure DP — pallas_call
        cannot be auto-partitioned, so the mesh split is explicit)."""
        import jax

        from misaka_tpu.core.fused import make_fused_runner
        from misaka_tpu.parallel.mesh import shard_map_compat, state_specs

        local = make_fused_runner(
            net.code,
            net.prog_len,
            num_stacks=net.num_stacks,
            stack_cap=net.stack_cap,
            in_cap=net.in_cap,
            out_cap=net.out_cap,
            batch=self._batch // self._dp,
            num_steps=self._chunk,
            interpret=(self._engine == "fused-interpret"),
        )
        specs = state_specs(batched=True)
        inner = shard_map_compat(
            local, mesh=self._mesh, in_specs=(specs,), out_specs=specs,
        )
        jitted = jax.jit(inner, donate_argnums=(0,))
        jitted.inner = inner  # fusable into the one-dispatch serve jit
        return jitted

    @property
    def engine_name(self) -> str:
        if self._mp > 1:
            return "gather" if self._engine == "gather" else "routed"
        if getattr(self._runner, "is_native", False):
            return "native"
        if self._runner is not None:
            return "fused"
        if self._trace_cap:
            return "scan-traced"
        # which arbitration kernel the scan engine auto-selected (platform-
        # dependent since r5: CPU always compact, TPU wide nets chained) —
        # observability for the crossover, not a distinct engine
        from misaka_tpu.core.engine import compact_auto_lanes, wide_engine

        kernel = (
            wide_engine()
            if self._net.num_lanes >= compact_auto_lanes()
            else "dense"
        )
        return f"scan-{kernel}"

    @staticmethod
    def _close_runner(runner) -> None:
        """Release a replaced engine's native resources promptly: the C++
        interpreter/pool handles otherwise wait for GC __del__ — prompt on
        CPython, unspecified on other runtimes or under reference cycles.
        Jitted runners have no close(); no-op for them."""
        close = getattr(runner, "close", None)
        if close is None:
            return
        try:
            close()
        except Exception:  # pragma: no cover — best-effort cleanup
            log.warning("closing replaced runner failed", exc_info=True)

    # --- lifecycle (the broadcastCommand surface, master.go:269-351) -------

    def run(self) -> None:
        with self._lifecycle_lock:
            if self._running:
                log.info("network is already running")
                return
            self._running = True
            self._loop = threading.Thread(target=self._device_loop, daemon=True)
            self._loop.start()
            log.info("network was run")

    def pause(self) -> None:
        with self._lifecycle_lock:
            if not self._running:
                log.info("network is already paused")
                return
            self._running = False
            self._work_event.set()  # wake a parked loop so join is immediate
            if self._loop:
                self._loop.join()
            self._rate = None
            log.info("network was paused")

    def close(self) -> None:
        """Stop serving and release native resources promptly (the program
        registry's eviction/retire path; harmless elsewhere).  The master
        stays constructible-state consistent — run() after close() would
        recompile nothing but serve on a closed native handle, so treat a
        closed master as done."""
        with self._lifecycle_lock:
            self.pause()
            self._drain_queues()
            if self._batcher is not None:
                with self._batcher._shared.cond:
                    self._batcher._shared.closed = True
                    self._batcher._shared.cond.notify_all()
            self._close_runner(self._runner)

    def reset(self) -> None:
        """Stop + zero all state and queues (stopNode/resetNode, master.go:252-266)."""
        with self._lifecycle_lock:
            self.pause()
            with self._state_lock:
                self._state = self._shard(self._net.init_state())
                if self._trace_cap:
                    self._trace = self._net.init_trace(self._trace_cap)
            self._drain_queues()
            log.info("network was reset")

    def load(self, target: str, program: str) -> None:
        """Reprogram one node; resets the whole network (master.go:145-195).

        COMPILE-FIRST (the registry discipline, runtime/registry.py): the
        new program is validated, lowered, and its engine built BEFORE
        anything stops — a parse/lower/runner error leaves the running
        network completely untouched, old programs and in-flight state
        intact.  This is a deliberate divergence from the reference, which
        discovers a bad program only after resetting (program.go:178-193,
        leaving the network stopped) — and strictly better: the pre-r10
        port of that ordering wiped the live state on every typo'd /load.
        Target validation still precedes everything (master.go:158-163).
        """
        with self._lifecycle_lock:
            new_topology = self._topology.with_program(target, program)  # validates target
            # Compile + build the runner against the still-running network:
            # both are pure w.r.t. the live net/state/runner triple, so a
            # failure here (parse, lower, fused VMEM budget) propagates
            # with the old network still serving.
            new_net = new_topology.compile(batch=self._batch)
            new_runner = self._make_runner(new_net)
            self.pause()
            with self._state_lock:
                old_runner = self._runner
                self._topology = new_topology
                self._net = new_net
                self._state = self._shard(new_net.init_state())
                if self._trace_cap:
                    self._trace = new_net.init_trace(self._trace_cap)
                self._runner = new_runner
                self._batched_serve = self._make_serve_fns(new_net, new_runner)
            self._close_runner(old_runner)
            self._drain_queues()
            M_ENGINE_SWAPS.labels(reason="load").inc()
            log.info("successfully loaded program")

    def compute(self, value: int, timeout: float = 30.0) -> int:
        """One value in, one value out — correlated (fixes quirk #2)."""
        return self.compute_many([value], timeout=timeout)[0]

    def compute_many(self, values, timeout: float = 30.0,
                     return_array: bool = False):
        """A FIFO stream of values through ONE instance: len(values) in,
        len(values) out, pairing strictly ordered.

        The throughput shape of /compute: one request chunk costs one queue
        hop each way regardless of its size, so the serve path amortizes to
        engine rates (the reference moves one value per HTTP round trip,
        master.go:197-224).

        Batched masters prefer a FREE instance (try-acquire scan from a
        rotating start) so one slow request can't head-of-line block traffic
        while other instances idle; only when every instance is busy does
        the caller block on one.  On timeout the request's missing outputs
        are recorded as stale and discarded when they surface, so later
        calls on that instance stay correctly paired — unless a reset/load
        wiped the request (epoch bump), in which case no output is coming
        and nothing is marked stale.
        """
        arr = np.asarray(values, dtype=np.int32)
        if arr.ndim != 1:
            raise ValueError(f"values must be a flat sequence, got shape {arr.shape}")
        if arr.size == 0:
            return np.empty((0,), np.int32) if return_array else []
        n = self._n_slots
        tr = tracespan.current()
        t_q = time.monotonic()  # queue clock: slot-lock wait (usage + trace)
        with self._rr_lock:
            start = self._rr
            self._rr = (self._rr + 1) % n
        slot = None
        for i in range(n):
            cand = (start + i) % n
            if self._compute_locks[cand].acquire(blocking=False):
                slot = cand
                break
        if slot is None:  # all instances busy: wait on the rotating one
            slot = start
            self._compute_locks[slot].acquire()
        with self._waiters_lock:
            self._waiters += 1
            self._requests_total += 1
        M_COMPUTE_REQS.inc()
        M_COMPUTE_VALUES.inc(arr.size)
        usage.add_request(self.program_label, arr.size)
        usage.add_queue(self.program_label, time.monotonic() - t_q)
        try:
            if tr is not None:
                # the direct lane's queue phase is the slot-lock wait
                tracespan.add_span(
                    tr, "serve.queue", t_q, time.monotonic() - t_q
                )
            pass_attrs = {"values": int(arr.size)}
            if self.program_label is not None:
                pass_attrs["program"] = self.program_label
            t_pass = time.monotonic()
            trace_token = self._trace_ids_enter(
                (tr.trace_id,) if tr is not None else ()
            )
            try:
                with tracespan.span("serve.pass", trace=tr, **pass_attrs):
                    with self._epoch_lock:
                        epoch = self._epoch
                        self._submit_q.put([(slot, arr)])
                    self._work_event.set()
                    deadline = time.monotonic() + timeout
                    parts = self._collect_slot(
                        slot, arr.size, deadline, epoch, timeout
                    )
            finally:
                self._trace_ids_exit(trace_token)
            # the direct lane's completed submit+collect window IS its
            # pass (one request, whole share) — same conservation-anchor
            # discipline as the scheduler's fused passes.  Success-only:
            # a ComputeTimeout must not charge the tenant the full
            # timeout as CPU, and skipping note_pass with it keeps the
            # conservation invariant exact.
            dur = time.monotonic() - t_pass
            usage.note_pass(dur)
            usage.add_cpu(self.program_label, dur)
            out = np.concatenate(parts)
            return out if return_array else out.tolist()
        finally:
            with self._waiters_lock:
                self._waiters -= 1
            self._compute_locks[slot].release()

    def _collect_slot(
        self, slot: int, want: int, deadline: float, epoch: int, timeout: float
    ) -> list[np.ndarray]:
        """Collect `want` outputs from `slot` as array parts (caller holds
        its lock) — no per-value Python anywhere on this path.

        On timeout, marks the slot's missing outputs stale (unless a
        reset/load wiped the request — epoch mismatch) and raises
        ComputeTimeout."""
        parts: list[np.ndarray] = []
        got = 0
        try:
            while got < want:
                if self._out_leftover[slot].size:
                    chunk = self._out_leftover[slot]
                    self._out_leftover[slot] = chunk[:0]
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise queue.Empty
                    chunk = self._out_qs[slot].get(timeout=remaining)
                    if chunk is _WIPED:
                        # a reset/load/restore drained the queues: nothing
                        # further is coming for a pre-wipe request — fail
                        # NOW instead of burning the remaining timeout.  A
                        # sentinel from an epoch this request postdates is
                        # stale noise; discard it.
                        with self._epoch_lock:
                            if self._epoch != epoch:
                                raise queue.Empty
                        continue
                with self._epoch_lock:
                    if self._epoch != epoch:
                        # a reset/load wiped this request mid-collect: the
                        # chunk in hand predates the wipe and nothing further
                        # is coming — fail the request, pollute nothing.
                        raise queue.Empty
                    # Outputs of previously timed-out requests surface first
                    # (per-instance FIFO); drop them.  Under the epoch lock:
                    # a concurrent drain's stale/leftover wipe must not
                    # interleave with these writes.
                    if self._stale[slot]:
                        k = min(self._stale[slot], len(chunk))
                        self._stale[slot] -= k
                        chunk = chunk[k:]
                    need = want - got
                    take, extra = chunk[:need], chunk[need:]
                    if take.size:
                        parts.append(take)
                        got += take.size
                    if extra.size:
                        # more outputs than this request asked for (a non-1:1
                        # network): hold them, FIFO, for the slot's next
                        # caller (slot-lock holder + epoch lock)
                        self._out_leftover[slot] = extra
        except queue.Empty:
            with self._epoch_lock:  # atomic vs _drain_queues' epoch bump
                if self._epoch == epoch:
                    self._stale[slot] += want - got
            M_COMPUTE_TIMEOUTS.inc()
            raise ComputeTimeout(
                f"no output for {want - got}/{want} value(s) "
                f"after {timeout}s"
            )
        return parts

    def compute_coalesced(
        self, values, timeout: float = 30.0, return_array: bool = False,
        traces=None,
    ):
        """A value stream through the serve scheduler: len(values) in,
        len(values) out, order preserved — and concurrent callers fuse
        into shared engine passes (ServeBatcher).

        This is the multi-tenant serving lane the HTTP surface routes
        through: under concurrent load, many small requests pack into
        full input-ring stripes across few instances (instead of each
        claiming a nearly-empty slot), and the native tier's partial-fill
        fast path then ticks only the slots actually working.  A lone
        caller dispatches immediately (no coalesce window when the engine
        is idle) and large streams stripe across free instances exactly
        like compute_spread.  Falls back to compute_spread when the
        scheduler is disabled (MISAKA_SERVE_BATCH=0).
        """
        arr = np.asarray(values, dtype=np.int32)
        if arr.ndim != 1:
            raise ValueError(f"values must be a flat sequence, got shape {arr.shape}")
        if arr.size == 0:
            return np.empty((0,), np.int32) if return_array else []
        if traces is None:
            # the usual case: one HTTP request, its trace current on this
            # handler thread; the compute plane passes its frame's traces
            # explicitly (one entry can carry many)
            tr = tracespan.current()
            traces = (tr,) if tr is not None else ()
        if self._batcher is None:
            return self.compute_spread(
                arr, timeout=timeout, return_array=return_array
            )
        out = self._batcher.compute(arr, timeout, traces=traces)
        return out if return_array else out.tolist()

    def compute_spread(
        self, values, timeout: float = 30.0, return_array: bool = False
    ):
        """A value stream STRIPED over free instances: len(values) in,
        len(values) out, order preserved.

        Where compute_many drives one instance (strict FIFO on it), this
        splits the stream into contiguous stripes across as many free
        instances as the stream can cover (one input-ring's worth per
        instance) and runs them genuinely in parallel — one caller can keep
        the whole batch busy, which is what the served-throughput path
        needs.  Every value is still its own /compute in reference terms
        (values are independent, master.go:197-224); per-instance FIFO makes
        the reassembly exact.
        """
        arr = np.asarray(values, dtype=np.int32)
        if arr.ndim != 1:
            raise ValueError(f"values must be a flat sequence, got shape {arr.shape}")
        if arr.size == 0:
            return np.empty((0,), np.int32) if return_array else []
        n = self._n_slots
        stripe = self._stripe or max(1, self._net.in_cap)
        owned: list[int] = []
        if n > 1 and arr.size > stripe:
            want_slots = min(n, -(-arr.size // stripe))
            for s in range(n):
                if self._compute_locks[s].acquire(blocking=False):
                    owned.append(s)
                    if len(owned) >= want_slots:
                        break
        if not owned:
            return self.compute_many(arr, timeout=timeout, return_array=return_array)
        with self._waiters_lock:
            self._waiters += 1
            self._requests_total += 1
        M_COMPUTE_REQS.inc()
        M_COMPUTE_VALUES.inc(arr.size)
        usage.add_request(self.program_label, arr.size)
        t_pass = time.monotonic()
        try:
            pass_attrs = {"values": int(arr.size), "slots": len(owned)}
            if self.program_label is not None:
                pass_attrs["program"] = self.program_label
            _tr = tracespan.current()
            trace_token = self._trace_ids_enter(
                (_tr.trace_id,) if _tr is not None else ()
            )
            with tracespan.span("serve.pass", **pass_attrs):
                stripes = np.array_split(arr, len(owned))
                with self._epoch_lock:
                    epoch = self._epoch
                    self._submit_q.put(list(zip(owned, stripes)))
                self._work_event.set()
                deadline = time.monotonic() + timeout
                parts: list[np.ndarray] = []
                for i, (s, part) in enumerate(zip(owned, stripes)):
                    try:
                        parts.extend(
                            self._collect_slot(
                                s, part.size, deadline, epoch, timeout
                            )
                        )
                    except ComputeTimeout:
                        # _collect_slot marked slot s; the stripes we never
                        # collected will surface outputs too — mark those
                        # slots stale as well so their pairing survives
                        # this failure.
                        with self._epoch_lock:
                            if self._epoch == epoch:
                                for s2, part2 in list(
                                    zip(owned, stripes)
                                )[i + 1:]:
                                    self._stale[s2] += part2.size
                        raise
            out = np.concatenate(parts)
            # success-only billing — same discipline (and rationale) as
            # the compute_many lane above
            dur = time.monotonic() - t_pass
            usage.note_pass(dur)
            usage.add_cpu(self.program_label, dur)
            return out if return_array else out.tolist()
        finally:
            self._trace_ids_exit(trace_token)
            with self._waiters_lock:
                self._waiters -= 1
            for s in owned:
                self._compute_locks[s].release()

    @property
    def is_running(self) -> bool:
        return self._running

    def _sync_native_state(self) -> None:
        """Materialize resident native-engine state into self._state (r17).

        The native engines keep batch state IN C++ between serve calls,
        returning their identity anchor with stale array contents — so
        every path that READS self._state's content (checkpoint, snapshot,
        autogrow, /status, the loop's boot counters, the idle-path ring
        drain) must export first.  No-op for non-native engines and when
        residency is not armed.  Caller holds _state_lock (export and the
        serve path are thereby serialized — the pool has one caller)."""
        export = getattr(self._runner, "export_resident", None)
        if export is None:
            return
        # anchor-gated: if a lifecycle path (reset/load/restore) already
        # REPLACED self._state, the resident copy is superseded and the
        # export must not clobber the fresh state — the engine exports
        # only when self._state IS its identity anchor
        st = export(self._state)
        if st is not None:
            self._state = st

    def status(self) -> dict:
        """Live metrics (additive vs the reference, which has none —
        SURVEY.md §5: stdlib log lines were its only observability).

        All device arrays are materialized UNDER the state lock: the device
        loop donates state buffers into each jitted chunk, so touching them
        outside the lock races with invalidation on TPU.
        """
        with self._state_lock:
            self._sync_native_state()
            state = self._state
            topo = self._topology
            # Batched states carry a leading [B] axis; report totals across
            # instances (tick is lockstep-identical, take instance 0).
            tick = int(np.asarray(state.tick).flat[0])
            retired = np.asarray(state.retired)
            stack_top = np.asarray(state.stack_top)
            if self._batch is not None:
                retired = retired.sum(axis=0)
                stack_top = stack_top.sum(axis=0)
            in_depth = int(np.asarray(state.in_wr - state.in_rd).sum())
            out_depth = int(np.asarray(state.out_wr - state.out_rd).sum())
            stack_cap = self._net.stack_cap
        # Gauge-quality depth reads; each queue's internal mutex is held only
        # long enough to snapshot its deque (iterating unlocked can raise
        # "deque mutated during iteration" under concurrent traffic).
        def q_depth(q):
            with q.mutex:
                items = list(q.queue)
            return items

        host_in = sum(
            len(c) for pairs in q_depth(self._submit_q) for _, c in pairs
        ) + sum(sum(len(c) for c in pend) for pend in self._in_pending)
        if self._batcher is not None:
            # values enqueued in the serve scheduler but not yet cut into
            # a pass — part of the same "waiting to enter the engine" story
            host_in += self._batcher.waiting_values()
        host_out = sum(
            sum(len(c) for c in q_depth(q)) for q in self._out_qs
        )
        with self._waiters_lock:
            requests_total = self._requests_total
        status = {
            "running": self._running,
            "engine": self.engine_name,
            # duplicate under the /healthz key so dashboards join on one
            # name; plus uptime and the cumulative request counter — the
            # reference's /status was point-in-time gauges only
            "served_engine": self.engine_name,
            "uptime_seconds": round(time.monotonic() - self._created_mono, 3),
            "requests_total": requests_total,
            "tick": tick,
            "ticks_per_sec": self._rate,  # maintained by the device loop
            "retired_per_lane": {
                name: int(retired[i]) for name, i in topo.lane_ids().items()
            },
            "stack_depth": {
                name: int(stack_top[i]) for name, i in topo.stack_ids().items()
            },
            # current per-compile capacity — observable growth (auto-grow
            # doubles this when a full stack wedges the network)
            "stack_cap": stack_cap,
            "in_queue": host_in + in_depth,
            "out_queue": host_out + out_depth,
            "nodes": dict(topo.node_info),
        }
        if self._batch is not None:
            status["batch"] = self._batch
        if self._mesh is not None:
            status["mesh"] = {"data": self._dp, "model": self._mp}
        runner = self._runner
        if getattr(runner, "is_native", False) and hasattr(runner, "simd_info"):
            # the native execution ladder (ISSUE 12): group width / AVX2 /
            # per-program specialization, plus the process-wide
            # specialization-cache outcome counters — "is this box actually
            # running the fast paths" answered without a /metrics parse
            try:
                from misaka_tpu.core.specialize import M_SPECIALIZE

                status["native"] = {
                    **runner.simd_info(),
                    "specialize_cache": {
                        s: int(M_SPECIALIZE.labels(status=s).value)
                        for s in ("hit", "built", "error", "fallback",
                                  "disabled")
                    },
                }
            except Exception:  # status must never 500 on telemetry
                pass
        return status

    def trace(self, last: int | None = None) -> list[dict]:
        """Decoded instruction history, oldest first (requires trace_cap).

        Buffers are materialized under the state lock — the device loop
        donates the trace ring into each traced chunk.
        """
        from misaka_tpu.core.trace import TraceRing, decode_trace

        if self._trace is None:
            raise RuntimeError("tracing disabled (construct MasterNode with trace_cap)")
        with self._state_lock:
            ring = TraceRing(
                buf=np.asarray(self._trace.buf).copy(),
                wr=np.asarray(self._trace.wr).copy(),
            )
            net = self._net
            topo = self._topology
        return decode_trace(
            ring,
            net.code,
            net.prog_len,
            lane_names=list(topo.lane_ids()),
            stack_names=list(topo.stack_ids()),
            last=last,
        )

    def save_checkpoint(self, path: str, include_history: bool = True) -> None:
        """Whole-network state + topology to one .npz (SURVEY.md §5: the
        reference cannot checkpoint at all; here state is one pytree) —
        DURABLY:

          1. np.savez into a same-directory tmp file, flushed + fsync'd: a
             crash mid-write leaves only a tmp, never a truncated file at
             the target path that a later load would trust;
          2. `path`.manifest sidecar (atomic too) carrying the exact byte
             size + sha256 — verify_checkpoint's rejection evidence;
          3. os.replace(tmp, path) THEN os.replace of the manifest: the
             data file is the commit point, so a crash between the two
             renames leaves a fully valid checkpoint under a stale
             sidecar — which verify_checkpoint heals via its CRC-walk
             fallback instead of rejecting committed data (+ a directory
             fsync so the renames survive power loss).

        Arrays are materialized under the state lock (see status()).
        Fault points (utils/faults.py): `ckpt_crash` raises between the
        tmp writes and the replaces (the crash the discipline exists
        for — the target must stay intact); `ckpt_torn_write` truncates
        the final file after the swap (the legacy failure shape, which
        the manifest must then reject at load).
        """
        import hashlib

        t0 = time.perf_counter()
        with self._state_lock:
            self._sync_native_state()
            state = self._state
            topo = self._topology
            arrays = {f: np.asarray(getattr(state, f)) for f in state._fields}
        arrays["__topology__"] = np.frombuffer(
            json.dumps(
                {
                    "nodes": topo.node_info,
                    "programs": topo.programs,
                    "stack_cap": topo.stack_cap,
                    "in_cap": topo.in_cap,
                    "out_cap": topo.out_cap,
                    "batch": self._batch,
                }
            ).encode(),
            dtype=np.uint8,
        )
        # Retained metric history rides the durable-checkpoint path
        # (utils/tsdb.py): a fleet-roll replacement restores its
        # predecessor's /debug/series history instead of booting blind.
        # `include_history=False` (the registry's per-program eviction
        # checkpoints) skips the blob: history is process-global, so N
        # evicted programs would each carry a redundant copy that the
        # strictly-newer restore merge discards anyway — and the
        # whole-store snapshot walk is not worth paying on the
        # capacity-pressure path.
        if include_history:
            _tsdb_blob = tsdb_mod.snapshot_bytes()
            if _tsdb_blob:
                arrays["__tsdb__"] = np.frombuffer(
                    _tsdb_blob, dtype=np.uint8
                )
        tmp = f"{path}.tmp.{os.getpid()}"
        mtmp = f"{manifest_path(path)}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)
                f.flush()
                os.fsync(f.fileno())
            h = hashlib.sha256()
            with open(tmp, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            size = os.path.getsize(tmp)
            with open(mtmp, "w") as f:
                json.dump(
                    {
                        "format": 1,
                        "sha256": h.hexdigest(),
                        "size": size,
                        "saved_unix": round(time.time(), 3),
                        "batch": self._batch,
                    },
                    f,
                )
                f.flush()
                os.fsync(f.fileno())
            if faults.fire("ckpt_crash") is not None:
                raise OSError(
                    "injected ckpt_crash fault (crash before atomic replace)"
                )
            os.replace(tmp, path)
            os.replace(mtmp, manifest_path(path))
        except BaseException:
            for leftover in (tmp, mtmp):
                try:
                    os.unlink(leftover)
                except OSError:
                    pass
            raise
        _fsync_dir(os.path.dirname(os.path.abspath(path)))
        torn = faults.fire("ckpt_torn_write")
        if torn is not None:
            with open(path, "r+b") as f:
                f.truncate(int(size * max(0.0, min(1.0, torn))))
        self._last_ckpt_mono = time.monotonic()
        M_CKPT_SAVE_SECONDS.observe(time.perf_counter() - t0)

    def load_checkpoint(self, path: str) -> None:
        """Restore state + programs from a .npz written by save_checkpoint.

        Capacities travel in the checkpoint: a snapshot taken under different
        ring/stack caps restores those caps, keeping the state arrays and the
        compiled network consistent.

        Durability gate first: verify_checkpoint rejects a torn or corrupt
        file (CheckpointError) before np.load runs — a partial write must
        never reach the engine swap, and the live network keeps serving its
        current state when one does arrive.
        """
        import jax.numpy as jnp

        from misaka_tpu.core.state import NetworkState

        t0 = time.perf_counter()
        verify_checkpoint(path)
        with np.load(path) as data:
            meta = json.loads(bytes(data["__topology__"]).decode())
            if "__tsdb__" in data:
                # history restore is best-effort by design: a corrupt or
                # stale history blob must never fail an engine-state
                # restore (the strictly-newer merge also makes a replay
                # of the same blob a no-op)
                try:
                    tsdb_mod.restore_bytes(bytes(data["__tsdb__"]))
                except Exception:
                    log.warning("checkpoint %s: tsdb history blob "
                                "ignored (unreadable)", path)
            fields = {
                f: jnp.asarray(data[f])
                for f in NetworkState._fields if f in data
            }
            # pre-regs64 checkpoints lack the hi planes; those states were
            # int32-exact, so sign-extension reconstructs the 64-bit value
            for hi, lo in (("acc_hi", "acc"), ("bak_hi", "bak")):
                if hi not in fields:
                    fields[hi] = fields[lo] >> 31
            state = NetworkState(**fields)
        ckpt_batch = meta.get("batch")
        if ckpt_batch != self._batch:
            raise ValueError(
                f"checkpoint batch={ckpt_batch} does not match this master's "
                f"batch={self._batch} (request queues are per-instance)"
            )
        new_topology = Topology(
            node_info=meta["nodes"],
            programs=meta["programs"],
            stack_cap=int(meta.get("stack_cap", self._topology.stack_cap)),
            in_cap=int(meta.get("in_cap", self._topology.in_cap)),
            out_cap=int(meta.get("out_cap", self._topology.out_cap)),
        )
        with self._lifecycle_lock:
            self.pause()
            new_net = new_topology.compile(batch=self._batch)
            new_runner = self._make_runner(new_net)  # before any swap (a
            # failure here must leave the old net/state/runner intact)
            validate = getattr(new_runner, "validate_state", None)
            if validate is not None:
                # native engine: reject value-corrupt checkpoint content
                # (pc/top/ring violations) here, not in the device loop
                try:
                    validate(state)
                except Exception:
                    self._close_runner(new_runner)  # the reject keeps the old engine
                    raise
            with self._state_lock:
                old_runner = self._runner
                self._topology = new_topology
                self._net = new_net
                self._state = self._shard(self._owned_device_state(state))
                if self._trace_cap:
                    self._trace = new_net.init_trace(self._trace_cap)
                self._runner = new_runner
                self._batched_serve = self._make_serve_fns(new_net, new_runner)
            self._close_runner(old_runner)
            self._drain_queues()
            # a checkpoint can carry in-flight values; flush their orphan
            # outputs before serving new requests (see _device_loop_inner)
            self._flush_iters = self._flush_quiet = 0
            self._restore_flush = True
        M_ENGINE_SWAPS.labels(reason="restore").inc()
        M_CKPT_RESTORE_SECONDS.observe(time.perf_counter() - t0)
        log.info("checkpoint restored from %s", path)

    def snapshot(self):
        """Whole-network state as one pytree — checkpointing for free.

        Deep-copied: the device loop donates its state buffers into each
        jitted chunk, which would invalidate a live reference.
        """
        import jax

        with self._state_lock:
            self._sync_native_state()
            return jax.tree.map(lambda x: x.copy(), self._state)

    def restore(self, state) -> None:
        """Reinstall a snapshot() pytree.

        A snapshot is STATE only (registers, ports, stacks, rings) —
        programs are topology and do NOT roll back; use checkpoints
        (save_checkpoint/load_checkpoint) to carry programs with state.

        A snapshot taken before a stack auto-grow has narrower stack_mem
        than the live engine compiles for — pad it (zero slots above the
        restored tops are exactly the grown state's invariant).  Any other
        shape mismatch is rejected here instead of crashing the device loop
        on its next chunk.

        A RUNNING master is paused for the swap and resumed after: the
        drain/epoch/orphan-flush protections (a wiped request must fail,
        a resurrected in-flight value must never mispair a later request)
        require the device loop joined, and silently skipping them for
        live restores would reopen exactly that pollution."""
        with self._lifecycle_lock:
            resume = self._running
            if resume:
                self.pause()
            self._restore_locked(state)
            if resume:
                self.run()

    def _restore_locked(self, state) -> None:
        import jax
        import jax.numpy as jnp

        with self._state_lock:
            # owned device copies: (a) the device loop donates state buffers,
            # which would invalidate the caller's snapshot; (b) donating a
            # numpy-aliased buffer corrupts the heap on jax 0.4.x CPU (see
            # _owned_device_state)
            state = self._owned_device_state(state)
            want_cap = self._net.stack_cap
            have_cap = state.stack_mem.shape[-1]
            if have_cap < want_cap:
                pad = [(0, 0)] * (state.stack_mem.ndim - 1) \
                    + [(0, want_cap - have_cap)]
                state = state._replace(stack_mem=jnp.pad(state.stack_mem, pad))
            ref = self._net.init_state()
            mismatch = [
                f for f, a, b in zip(
                    state._fields, jax.tree.leaves(state), jax.tree.leaves(ref)
                ) if a.shape != b.shape
            ]
            if mismatch:
                raise ValueError(
                    f"snapshot shapes do not match the compiled network "
                    f"(fields {mismatch}); reset/load first"
                )
            validate = getattr(self._runner, "validate_state", None)
            if validate is not None:
                # the native engine rejects value-corrupt states (pc beyond
                # the program, stack_top beyond capacity, broken rings) at
                # import; surface that HERE as the documented ValueError —
                # inside the device loop it would stop the network instead
                # (the XLA engines clamp OOB indices and keep serving)
                validate(state)
            self._state = self._shard(state)
            # restored retired counters invalidate the partial-fill hot
            # baseline; the next native serve pass runs full-batch
            self._retired_prev = None
        # Epoch-invalidate in-flight requests (the caller paused the loop):
        # a request submitted against pre-restore state must fail as
        # ComputeTimeout, never receive outputs derived from the snapshot's
        # rings (cross-request pollution).  reset/load/load_checkpoint
        # already drain; restore was the gap.
        self._drain_queues()
        # and flush the snapshot's resurrected in-flight values before
        # serving anything new (see _device_loop_inner)
        self._flush_iters = self._flush_quiet = 0
        self._restore_flush = True

    # --- the device loop ----------------------------------------------------

    def _drain_queues(self) -> None:
        # Called with the device loop stopped (after pause()), so the
        # loop-private _in_pending spillover is safe to wipe here too.
        with self._epoch_lock:
            for q in (self._submit_q, *self._out_qs):
                while True:
                    try:
                        q.get_nowait()
                    except queue.Empty:
                        break
            for pend in self._in_pending:
                pend.clear()
            self._active.clear()
            for i in range(len(self._out_leftover)):
                self._out_leftover[i] = self._out_leftover[i][:0]
            # reset/load wipe the rings: nothing stale survives, and any
            # compute still waiting must not record its wiped request as
            # stale (epoch).  The epoch lock makes this atomic against the
            # (read epoch, enqueue) pair in compute_many — an enqueue either
            # lands before the drain (wiped; its waiter sees a new epoch) or
            # after (it survives into the fresh queues under the new epoch).
            self._stale = [0] * len(self._stale)
            self._inflight[:] = 0
            self._retired_prev = None  # next native pass runs full-batch
            self._grow_blocked = False
            self._epoch += 1
            # Wake collectors parked on the (now empty) output queues so
            # their requests fail immediately instead of timing out — see
            # _collect_slot's sentinel handling.
            for q in self._out_qs:
                q.put(_WIPED)

    def _maybe_grow_stacks(self) -> None:
        """Double stack capacity when a full stack has wedged the network.

        Reference parity: the Go stacks grow without limit (intStack.go:9-45)
        while XLA needs static shapes — so capacity grows geometrically, each
        step a recompile plus a zero-pad of stack_mem (slot indices and
        occupancy unchanged).  Bounded by `stack_grow_max_bytes`; when the
        preferred engine can't serve the new shape, `engine=auto` falls back
        (e.g. fused -> scan via _make_runner) and a forced engine logs and
        keeps the old capacity.  Called from the device loop thread only.

        Lock discipline: compile AND warm the new engine OUTSIDE _state_lock
        (a big fused network costs seconds of XLA compile — /status,
        snapshot() and request ingestion must stay responsive through it,
        intStack.go's growth never stalls the Go master), then swap the
        references under the lock (a pad + device put, milliseconds).  Safe
        because only this (device-loop) thread mutates _net outside the
        lifecycle path, and every lifecycle mutation first joins this thread
        via pause().
        """
        import dataclasses
        import time as _time

        import jax.numpy as jnp

        with self._state_lock:
            self._sync_native_state()
            net = self._net
            tops = np.asarray(self._state.stack_top)
            if not (tops >= net.stack_cap).any():
                return  # stalled for some other reason (e.g. starvation)
        new_cap = net.stack_cap * 2
        new_bytes = (self._batch or 1) * net.num_stacks * new_cap * 4
        if new_bytes > self._grow_max_bytes:
            log.warning(
                "stack at capacity %d but growing to %d would use %d "
                "bytes (> stack_grow_max_bytes=%d); leaving it parked",
                net.stack_cap, new_cap, new_bytes, self._grow_max_bytes,
            )
            self._grow_blocked = True  # warn once per wedge
            M_AUTOGROW_BLOCKED.inc()
            return

        # --- slow half: lower, build, and WARM the new engine (no lock) ----
        t0 = _time.monotonic()
        new_topology = dataclasses.replace(self._topology, stack_cap=new_cap)
        new_net = new_topology.compile(batch=self._batch)
        try:
            new_runner = self._make_runner(new_net)
        except ValueError as e:
            log.warning(
                "stack at capacity but engine=%s cannot serve "
                "stack_cap=%d: %s", self._engine, new_cap, e
            )
            self._grow_blocked = True  # warn once per wedge
            M_AUTOGROW_BLOCKED.inc()
            return
        new_serve = self._make_serve_fns(new_net, new_runner)
        self._warm_engine(new_net, new_runner, new_serve)
        compile_s = _time.monotonic() - t0

        # --- fast half: swap under the lock --------------------------------
        t0 = _time.monotonic()
        with self._state_lock:
            if self._net is not net:  # lifecycle swapped the network under us
                self._close_runner(new_runner)
                return
            self._sync_native_state()  # the pad below reads state content
            pad = [(0, 0)] * (self._state.stack_mem.ndim - 1) \
                + [(0, new_cap - net.stack_cap)]
            old_runner = self._runner
            self._topology = new_topology
            self._net = new_net
            self._state = self._shard(
                self._state._replace(stack_mem=jnp.pad(self._state.stack_mem, pad))
            )
            self._runner = new_runner
            self._batched_serve = new_serve
        self._close_runner(old_runner)
        swap_s = _time.monotonic() - t0
        M_AUTOGROW.inc()
        M_ENGINE_SWAPS.labels(reason="autogrow").inc()
        log.info(
            "grew stack capacity %d -> %d (engine=%s): compile+warm %.3fs "
            "off-lock, swap %.3fs under lock",
            net.stack_cap, new_cap, self.engine_name, compile_s, swap_s,
        )

    def _warm_engine(self, net, runner, serve_fns) -> None:
        """Force the new engine's first-call XLA compiles on a throwaway
        state so the device loop's next iteration (under _state_lock) runs
        pre-compiled.  The dummy chunk executes on garbage state and is
        discarded; the network being grown is wedged anyway, so the extra
        chunk costs idle time, not serve latency."""
        import jax

        t0 = time.perf_counter()
        try:
            dummy = self._shard(net.init_state())
            if getattr(runner, "is_native", False):
                # no XLA to warm; one throwaway chunk validates the new tables
                if self._batch is None:
                    runner.serve_chunk(
                        dummy, np.zeros((net.in_cap,), np.int32), 0, self._chunk
                    )
                else:
                    runner.serve(
                        dummy,
                        np.zeros((self._batch, net.in_cap), np.int32),
                        np.zeros((self._batch,), np.int32),
                    )
                M_WARM_TOTAL.inc()
                M_WARM_SECONDS.observe(time.perf_counter() - t0)
                return
            if serve_fns is not None:
                serve_fn, idle_fn = serve_fns
                vals = np.zeros((self._batch, net.in_cap), np.int32)
                counts = np.zeros((self._batch,), np.int32)
                dummy, packed = serve_fn(dummy, vals, counts)
                dummy, _ = idle_fn(dummy)
                jax.block_until_ready(packed)
            elif self._trace is not None:
                # the traced loop compiles a DIFFERENT jit than net.run —
                # warm the one the device loop will actually call
                trace = net.init_trace(self._trace_cap)
                dummy, trace = net.run_traced(
                    dummy, trace, self._chunk, *(
                        () if self._batch is None else (self._trace_instance,)
                    )
                )
                jax.block_until_ready(trace)
            elif self._batch is None:
                vals = np.zeros((net.in_cap,), np.int32)
                dummy, packed = net.serve_chunk(dummy, vals, 0, self._chunk)
                jax.block_until_ready(packed)
            elif runner is not None:
                dummy = runner(dummy)
                jax.block_until_ready(dummy)
            else:
                dummy = net.run(dummy, self._chunk)
                jax.block_until_ready(dummy)
            jax.block_until_ready(net.counters(dummy))
            # success only: a failed warm must NOT read as a healthy fast
            # warm — the failure series is the one worth alerting on
            M_WARM_TOTAL.inc()
            M_WARM_SECONDS.observe(time.perf_counter() - t0)
        except Exception as e:  # pragma: no cover — warm-up is best-effort
            M_WARM_FAILED.inc()
            log.warning("engine warm-up after grow failed (continuing): %s", e)

    def _mark_ticks(self) -> None:
        """Advance the tick-rate gauge by one chunk (device loop thread)."""
        self._ticks_done += self._chunk
        M_TICKS.inc(self._chunk)
        now = time.monotonic()
        if now - self._rate_mark_time > 2:
            self._rate = (self._ticks_done - self._rate_mark_tick) / (
                now - self._rate_mark_time
            )
            self._rate_mark_tick = self._ticks_done
            self._rate_mark_time = now

    def _device_loop(self) -> None:
        """Run jitted chunks; sync rings with host queues at the boundaries."""
        try:
            self._device_loop_inner()
        except Exception:
            # A crashed loop must not strand /compute callers in a silent
            # 30s timeout; stop cleanly and leave the log trail.
            log.exception("device loop crashed; network stopped")
            self._running = False

    def _ingest_submissions(self) -> None:
        """Move submitted request chunks into per-slot spillover (loop thread)."""
        while True:
            try:
                pairs = self._submit_q.get_nowait()
            except queue.Empty:
                return
            for slot, arr in pairs:
                self._in_pending[slot].append(arr)
                self._active.add(slot)

    def _cut_pending(self, slot: int, budget: int) -> np.ndarray | None:
        """Cut up to `budget` values off the front of `slot`'s spillover —
        O(chunks) host work, never O(values) (loop thread only)."""
        pend = self._in_pending[slot]
        if not pend or budget <= 0:
            return None
        take, taken = [], 0
        while pend and taken < budget:
            c = pend[0]
            if len(c) <= budget - taken:
                take.append(pend.pop(0))
                taken += len(c)
            else:
                take.append(c[: budget - taken])
                pend[0] = c[budget - taken:]
                taken = budget
        if not pend:
            self._active.discard(slot)
        return np.concatenate(take) if take else None

    def _build_feed(self, ctrs):
        """Cut pending submissions into a [B, in_cap] feed matrix + counts
        (loop thread only); shared by the one-dispatch and piecewise paths.

        Buffers are REUSED across iterations (the engines read only the
        counts[b] leading entries of each row, so stale bytes beyond them
        are dead): allocating a fresh [B, in_cap] matrix per serve
        iteration was measurable loop-thread time under load.  Only the
        previously-used rows are re-zeroed."""
        shape = (self._batch, self._net.in_cap)
        if self._feed_vals is None or self._feed_vals.shape != shape:
            self._feed_vals = np.zeros(shape, np.int32)
            self._feed_counts = np.zeros((self._batch,), np.int32)
        vals, counts = self._feed_vals, self._feed_counts
        counts[:] = 0
        free = self._net.in_cap - (ctrs[1] - ctrs[0])
        for b in list(self._active):
            got = self._cut_pending(b, int(free[b]))
            if got is not None:
                vals[b, : len(got)] = got
                counts[b] = len(got)
        # fed-minus-drained accounting for the native partial-fill path: a
        # slot owes outputs until the drain loop zeroes it back out
        self._inflight += counts
        return vals, counts

    def _native_active(self, ctrs, counts=None):
        """The native partial-fill active set for this iteration (loop
        thread only): replica indices that are fed now, hold input-ring
        content, owe outputs (fed minus drained), or retired instructions
        last chunk (internal in-flight work — non-1:1 programs can owe
        nothing by count while values still sit in ports/registers).
        None means run the full batch: the first pass after boot or a
        lifecycle state swap (no retired baseline yet), or an active set
        that covers everything anyway."""
        if self._retired_prev is None:
            return None
        mask = (self._inflight > 0) | (ctrs[1] > ctrs[0]) | self._native_hot
        if counts is not None:
            mask |= counts > 0
        active = np.flatnonzero(mask)
        return None if active.size >= self._n_slots else active

    def _native_note_progress(self, state, active) -> None:
        """Refresh the hot set from per-replica progress after a native
        chunk: a replica that retired nothing across a whole chunk is
        blocked awaiting input and safe to skip until fed again.

        The resident pool (r17) reports MEASURED per-replica progress
        flags from the C++ side — state.retired is stale while the state
        lives in C++ — and the flags are this chunk's deltas already, so
        no baseline pass is needed.  The stateless path keeps deriving
        the signal from exported retired deltas."""
        prog_fn = getattr(self._runner, "consume_progress", None)
        prog = prog_fn() if prog_fn is not None else None
        if prog is not None:
            if active is None:
                self._native_hot = prog.astype(bool)
            else:
                self._native_hot[:] = False
                self._native_hot[active] = prog[active].astype(bool)
            self._retired_prev = True  # flags mode: baseline is implicit
            return
        ret = np.asarray(state.retired).sum(axis=1)
        # a mode switch (resident -> stateless fallback) leaves the True
        # sentinel here, which is "baseline exists" but not an array
        prev = self._retired_prev \
            if isinstance(self._retired_prev, np.ndarray) else None
        if prev is None or active is None:
            # no baseline: keep everyone hot one pass so real deltas form
            self._native_hot = (
                ret > prev if prev is not None
                else np.ones((self._n_slots,), bool)
            )
        else:
            self._native_hot[:] = False
            self._native_hot[active] = ret[active] > prev[active]
        self._retired_prev = ret

    def _trace_ids_enter(self, ids) -> int | None:
        """Register a traced submit->collect window's request-trace IDs
        (None when there is nothing to register); pair with
        _trace_ids_exit in a finally."""
        ids = tuple(ids)
        if not ids:
            return None
        with self._pass_traces_lock:
            token = self._pass_trace_next
            self._pass_trace_next += 1
            self._pass_traces[token] = ids
        return token

    def _trace_ids_exit(self, token: int | None) -> None:
        if token is None:
            return
        with self._pass_traces_lock:
            self._pass_traces.pop(token, None)

    def active_pass_trace_ids(self) -> tuple:
        """The union of trace IDs across in-flight passes (native pool
        correlation read, once per pool call)."""
        with self._pass_traces_lock:
            if not self._pass_traces:
                return ()
            out: list = []
            for ids in self._pass_traces.values():
                for tid in ids:
                    if tid not in out:
                        out.append(tid)
            return tuple(out)

    def _device_loop_inner(self) -> None:
        # One device counter read per iteration (post-run), reused for the
        # next iteration's feed decisions: between chunks nothing on the
        # device moves, so post-run counters are exact — and on a relayed
        # device every extra read is a round trip on the serve path.
        # Resident native state is materialized first: this boot read is
        # the one per-run() place the loop consumes state CONTENT.
        with self._state_lock:
            self._sync_native_state()
            ctrs = self._net.counters(self._state)  # [4] or [4, B]
        while self._running:
            busy = False
            t_iter = time.perf_counter()
            # Orphan flush after restore/load_checkpoint: run WITHOUT
            # ingesting new work and discard everything the network emits
            # until it goes quiet — resurrected in-flight values must
            # never pair with a post-restore request.  New submissions
            # wait in the queue; the flush costs a few idle chunks.
            flushing = self._restore_flush
            with self._state_lock:
                state = self._state
                if not flushing:
                    self._ingest_submissions()
                if self._batch is None and self._trace is None:
                    # ONE device dispatch + ONE read for the whole iteration
                    # (feed+run+counters+drain fused, engine.serve_chunk):
                    # on a relayed device this is the difference between ~2
                    # and ~6 round trips per quiet /compute.  engine="native"
                    # swaps in the host interpreter's serve_chunk twin
                    # (core/native_serve.py) — same contract, ZERO dispatches.
                    free = self._net.in_cap - int(ctrs[1] - ctrs[0])
                    got = self._cut_pending(0, free)
                    vals = np.zeros((self._net.in_cap,), np.int32)
                    count = 0
                    if got is not None:
                        vals[: len(got)] = got
                        count = len(got)
                        busy = True
                        M_SLOT_OCCUPANCY.observe(1)
                    serve = getattr(self._runner, "serve_chunk", None) \
                        or self._net.serve_chunk
                    state, packed = serve(state, vals, count, self._chunk)
                    self._mark_ticks()
                    p = np.asarray(packed)  # the single device read
                    ctrs = p[:4]
                    rd, wr = int(p[2]), int(p[3])
                    if wr > rd:
                        idx = (rd + np.arange(wr - rd)) % self._net.out_cap
                        per_slot = [(0, p[4:][idx])]
                    else:
                        per_slot = []
                    self._state = state
                elif self._batched_serve is not None:
                    # the batched twin of the one-dispatch path: feed matrix
                    # + chunk + per-instance counter/ring snapshot in one
                    # jit, one [B, 4+out_cap] read.  The idle variant skips
                    # the feed upload AND the ring download (counters only;
                    # outputs fetched separately only if some appeared).
                    serve_fn, idle_fn = self._batched_serve
                    fed = False
                    if self._active:
                        vals, counts = self._build_feed(ctrs)
                        fed = bool(counts.any())
                    native = getattr(self._runner, "is_native", False)
                    if fed:
                        M_SLOT_OCCUPANCY.observe(int((counts > 0).sum()))
                        if native:
                            # Partial-fill fast path: the host pool ticks
                            # only slots that are fed now, hold ring
                            # content, owe outputs, or made progress last
                            # chunk — an underfilled pass must not pay
                            # full-batch cost (the 64-client workload fed
                            # ~6% of slots and paid for 100%).  First pass
                            # after boot/lifecycle swap runs everyone.
                            active = self._native_active(ctrs, counts)
                            state, packed = serve_fn(
                                state, vals, counts, active=active
                            )
                            self._native_note_progress(state, active)
                        else:
                            state, packed = serve_fn(state, vals, counts)
                        self._mark_ticks()
                        p = np.asarray(packed)  # the single device read
                        ctrs = p[:, :4].T  # the counters() orientation
                        per_slot = self._net.drain_from_snapshot(
                            p[:, 4:], p[:, 2], p[:, 3], self._net.out_cap
                        )
                        busy = True
                    else:
                        active = self._native_active(ctrs) if native else None
                        if native and active is not None and active.size == 0:
                            # fully quiescent: no ring content, no owed
                            # outputs, no replica that moved last chunk —
                            # ticking cannot change anything, so skip the
                            # engine call (an idle full-batch chunk was
                            # ~10ms the 64-client lane paid per request)
                            per_slot = []
                        else:
                            if native:
                                state, packed = idle_fn(state, active=active)
                                self._native_note_progress(state, active)
                            else:
                                state, packed = idle_fn(state)
                            self._mark_ticks()
                            p = np.asarray(packed)  # [B, 4]: counters only
                            ctrs = p.T
                            if (p[:, 3] > p[:, 2]).any():
                                if native:
                                    # resident pools: materialize before
                                    # the host-side ring drain (the state
                                    # object's out_buf is stale while the
                                    # state lives in C++); the rebuilt
                                    # drained state misses the identity
                                    # cache once — this path only fires
                                    # when an UNFED chunk emitted values
                                    exp = getattr(
                                        self._runner, "export_resident",
                                        None,
                                    )
                                    st2 = exp(state) if exp is not None \
                                        else None
                                    if st2 is not None:
                                        state = st2
                                state, per_slot = self._net.drain_batched(
                                    state, rd=p[:, 2], wr=p[:, 3]
                                )
                            else:
                                per_slot = []
                    self._state = state
                else:
                    # piecewise path: tracing and mesh serving
                    if self._batch is None:
                        free = self._net.in_cap - int(ctrs[1] - ctrs[0])
                        got = self._cut_pending(0, free)
                        if got is not None:
                            state, _ = self._net.feed(state, got)
                            busy = True
                    elif self._active:
                        # feed only when something is queued — an idle
                        # batched loop must not churn MBs/iteration
                        vals, counts = self._build_feed(ctrs)
                        if counts.any():
                            M_SLOT_OCCUPANCY.observe(int((counts > 0).sum()))
                            state = self._net.feed_batched(state, vals, counts)
                            busy = True
                    if self._trace is not None:
                        state, self._trace = self._net.run_traced(
                            state, self._trace, self._chunk,
                            **({"instance": self._trace_instance}
                               if self._batch is not None else {}),
                        )
                    elif self._runner is not None:
                        state = self._runner(state)  # fused / mesh runner
                    else:
                        state = self._net.run(state, self._chunk)
                    self._mark_ticks()
                    ctrs = self._net.counters(state)  # post-run, exact
                    if self._batch is None:
                        if ctrs[3] > ctrs[2]:
                            state, outs = self._net.drain(state)
                            per_slot = [(0, np.asarray(outs, np.int32))]
                        else:
                            per_slot = []
                    else:
                        state, per_slot = self._net.drain_batched(
                            state, rd=ctrs[2], wr=ctrs[3]
                        )
                    self._state = state
            for slot, outs in per_slot:
                if flushing:
                    busy = True  # orphan outputs: discard, keep flushing
                    continue
                self._out_qs[slot].put(outs)
                if self._inflight[slot] > 0:  # clamp: non-1:1 networks can
                    self._inflight[slot] = max(  # over- or under-produce
                        0, self._inflight[slot] - len(outs)
                    )
                busy = True
            # One observe + one labeled inc per chunk: the instrumentation
            # cost is a lock and a bisect against a chunk that advances
            # thousands of ticks — measured <<5% on the native serve path.
            iter_dur = time.perf_counter() - t_iter
            M_CHUNK_SECONDS.observe(iter_dur)
            (M_ITER_SERVE if busy else M_ITER_IDLE).inc()
            if busy:
                # engine-tier flight-recorder event (one deque append):
                # Perfetto shows serving chunks underneath the request
                # spans they carried; idle chunks are noise and skipped
                tracespan.note_tier("engine.chunk", iter_dur)
            if flushing:
                # Quiescence = several consecutive chunks with no output,
                # an empty input ring, and (native) no replica retiring
                # instructions.  Hard-capped so a generator network (or a
                # wedged restore) cannot flush forever.  Residual limit:
                # a NON-native engine whose internal value latency exceeds
                # 8 full chunks can still leak an orphan — internal
                # progress is invisible to the XLA engines' counters.
                self._flush_iters += 1
                quiet = (
                    not busy
                    and not bool(np.any(ctrs[1] > ctrs[0]))
                    and not self._native_hot.any()
                )
                self._flush_quiet = self._flush_quiet + 1 if quiet else 0
                if self._flush_quiet >= 8 or self._flush_iters >= 64:
                    self._restore_flush = False
                continue
            if busy:
                self._stall_iters = 0
                self._grow_blocked = False
                continue
            # Nothing moved this iteration.  A waiting compute means work is
            # mid-flight on the device — keep chunking (latency is then
            # bounded by chunk time, not a sleep quantum).  Otherwise park
            # on the enqueue event instead of burning host CPU (the round-1
            # 1ms sleep put a floor under every quiet-network request).
            with self._waiters_lock:
                waiting = self._waiters
            if waiting:
                # A wedged network looks exactly like this: requests in
                # flight, nothing moving, chunk after chunk.  After a few
                # strikes, check the one wedge the engine can repair —
                # a stack at capacity (the reference's are unbounded).
                self._stall_iters += 1
                if self._grow and not self._grow_blocked \
                        and self._stall_iters >= 8:
                    self._stall_iters = 0
                    self._maybe_grow_stacks()
                continue
            self._work_event.clear()
            with self._waiters_lock:
                waiting = self._waiters
            if not waiting and self._submit_q.empty():
                self._work_event.wait(0.05)


def make_http_server(
    master: MasterNode,
    port: int = 8000,
    checkpoint_dir: str | None = None,
    profile_dir: str | None = None,
    registry=None,
    tls=None,
) -> ThreadingHTTPServer:
    """The five client routes (master.go:90-224), byte-compatible, plus the
    additive /status, /trace, /checkpoint, /restore, /profile/* routes.
    (Byte compatibility covers the five reference routes; the additive
    /compute_batch emits JSON-equivalent fixed-width-padded int arrays —
    legal JSON whitespace, not byte-identical to json.dumps output.)

    `registry` (runtime/registry.ProgramRegistry) arms the multi-program
    surface: POST/GET /programs for upload/listing, program-addressed
    compute at POST /programs/<name>/compute[_batch|_raw], and the
    X-Misaka-Program header on the legacy compute routes.  Without a
    header or program path the legacy routes serve the seeded default
    program — full backward compatibility.  Unknown programs answer a
    typed 404.  registry=None (the default) keeps the pre-registry
    single-program surface exactly.

    `tls` selects transport security for THIS listener: None (default)
    reads MISAKA_TLS_CERT/MISAKA_TLS_KEY from the env (unset = plain
    HTTP), False forces plain HTTP even with the env set (the engine
    behind a TLS-terminating frontend tier listens on loopback), and an
    ssl.SSLContext is used as given.

    The edge middleware chain (runtime/edge.py) is built from the env and
    evaluated ahead of every route body: API-key auth, per-tenant quotas,
    and overload admission control fed by the LIVE ServeBatcher backlog.
    MISAKA_EDGE=0 (or the per-stage switches) disarms it — the default
    env (no key file, no MISAKA_QUOTA) keeps every existing surface
    byte-compatible.

    HTTP checkpointing is DISABLED unless `checkpoint_dir` is configured;
    when enabled, clients pass a bare checkpoint NAME (no path separators)
    resolved inside that directory — an unauthenticated form field must not
    choose arbitrary server-side filesystem paths.  The Python API
    (MasterNode.save_checkpoint/load_checkpoint) keeps full-path freedom for
    local callers.
    """
    import re
    import zipfile
    from urllib.parse import unquote

    from misaka_tpu.runtime.registry import (
        ProgramNotFound,
        RegistryError,
        ReplayDivergence,
    )
    from misaka_tpu.utils import textcodec
    from misaka_tpu.utils.profiling import Profiler, ProfilerError

    # Warm the native decimal codec at server startup: NativeLib builds the
    # .so on first use (~1s of g++ under its lock), and without this the
    # build lands inside the FIRST /compute_batch request's latency instead
    # of boot (ADVICE r5 #3).
    textcodec.native_available()

    # Always-on continuous profiler (utils/sampler.py): every serving
    # process samples its own stacks from boot, served at GET
    # /debug/flamegraph.  Process-global (one thread no matter how many
    # servers tests build); MISAKA_SAMPLER=0 is the kill switch.
    from misaka_tpu.utils import sampler as _sampler

    _sampler.ensure_started()

    # The embedded TSDB (utils/tsdb.py): every serving process retains
    # its own metric history from boot — GET /debug/series and the
    # /debug/dashboard sparklines read it, checkpoints snapshot it, and
    # the regression watchdog (utils/watchdog.py) evaluates its rules on
    # the collector's tick.  MISAKA_TSDB=0 / MISAKA_WATCHDOG=0 disarm.
    tsdb_mod.ensure_started()
    watchdog_mod.ensure_started()

    # Durable telemetry plane (MISAKA_TSDB_DIR is the master switch,
    # armed inside ensure_started above for the TSDB tier): the usage
    # ledger persists cumulative per-tenant counters under <dir>/usage,
    # and the capture spool keeps the wire recorder always-on under
    # <dir>/capture, cutting fresh per-program anchors at every
    # rotation with the same closure POST /captures/start uses.
    usage.ensure_spool()

    def _spool_anchors() -> dict:
        anchors = {}
        label = (
            registry.default_name if registry is not None else None
        ) or "default"
        a = capture_mod.anchor_from_master(label, master)
        if a is not None:
            anchors[label] = a
        if registry is not None:
            for name, m in registry.active_masters():
                if name in anchors:
                    continue
                a = capture_mod.anchor_from_master(name, m)
                if a is not None:
                    anchors[name] = a
        return anchors

    capture_mod.ensure_spool(anchor_fn=_spool_anchors)

    # Fleet-debugging stamp (utils/buildinfo.py): the misaka_build_info
    # gauge (version / git sha / runtime versions / native provenance in
    # labels, value 1) plus the /status `build` block below.
    from misaka_tpu.utils import buildinfo

    buildinfo.install_metric()

    # The production edge (runtime/edge.py): auth + quota + admission,
    # composed per route, evaluated before any route body below.  The
    # admission governor's live backlog signal is the ServeBatcher
    # waiting-values count — summed across every active per-program
    # engine when a registry is armed (the seeded default's engine IS
    # `master`, so the registry sum already covers it).
    from misaka_tpu.runtime import edge as edge_mod

    _slo_page_cache = [0.0, False]  # (last-eval monotonic, page?)
    _waiting_cache = [0.0, 0]       # (last-read monotonic, waiting values)

    def _edge_signals() -> tuple[int, bool]:
        now = time.monotonic()
        # waiting_values takes the ServeBatcher's condition lock — the
        # SAME lock the dispatcher workers hold while cutting passes —
        # so a per-request read from 64 handler threads convoys against
        # the scheduler itself.  A 25ms-stale backlog signal sheds the
        # same sustained overloads (which build over hundreds of ms)
        # without the contention.
        if now - _waiting_cache[0] > 0.025:
            _waiting_cache[0] = now
            if registry is not None:
                _waiting_cache[1] = registry.waiting_values()
            else:
                b = getattr(master, "_batcher", None)
                _waiting_cache[1] = (
                    b.waiting_values() if b is not None else 0
                )
        # burn-rate state changes on multi-second timescales but this
        # closure runs per admitted request: cache the page bit for
        # 0.25s (overall_state walks every program's windows)
        if now - _slo_page_cache[0] > 0.25:
            _slo_page_cache[0] = now
            _slo_page_cache[1] = slo.overall_state() == "page"
        return _waiting_cache[1], _slo_page_cache[1]

    # Default admission watermark: clears TWO maximum-size legal bodies
    # (MISAKA_MAX_BODY is int32 values x 4) — a request the body cap
    # admits must never be shed by the default watermark right after.
    # Real deployments tune MISAKA_ADMISSION_HIGH down to their latency
    # budget (waiting values / serving rate ~= queueing delay).
    _max_body_default = int(
        os.environ.get("MISAKA_MAX_BODY", "") or 64 * 1024 * 1024
    )
    edge_chain = edge_mod.from_env(
        signals=_edge_signals,
        cpu_reader=lambda label: usage.account(label).cpu_seconds,
        default_admission_high=max(65536, (_max_body_default // 4) * 2),
    )
    edge_mod.install(edge_chain)
    if registry is not None:
        # persisted per-program quota overrides predate this chain (the
        # registry reloads its store at construction, before any server)
        registry.install_quotas(edge_chain)

    _name_re = re.compile(r"^[A-Za-z0-9._-]{1,128}$")
    # Request-body ceiling for the bulk lanes (default 64 MiB): an
    # unauthenticated client must not be able to make the server buffer an
    # arbitrarily large body (answers 413; missing Content-Length is 411).
    max_body = int(os.environ.get("MISAKA_MAX_BODY", "") or 64 * 1024 * 1024)
    # Slow-request logging threshold (MISAKA_SLOW_REQ_MS): requests over it
    # auto-emit a structured warning carrying trace ID + program, so the
    # log <-> trace <-> tenant correlation is one grep.  Unset = off.
    _slow_ms = os.environ.get("MISAKA_SLOW_REQ_MS")
    slow_req_s = float(_slow_ms) / 1e3 if _slow_ms else None
    # Serving-plane fast request parsing (see _fast_parse_request);
    # MISAKA_FAST_HTTP=0 restores the stock stdlib parser end to end.
    fast_http = os.environ.get("MISAKA_FAST_HTTP", "1") != "0"
    profiler = Profiler()
    boot_mono = time.monotonic()  # /healthz uptime anchor (server, not master)

    def resolve_checkpoint(name: str) -> str | None:
        if not checkpoint_dir or not _name_re.match(name) or ".." in name:
            return None
        return os.path.join(checkpoint_dir, name if name.endswith(".npz") else name + ".npz")

    @contextlib.contextmanager
    def resolved_master(ref, values=0):
        """The engine a compute request serves on: the registry lease for
        a program-addressed request (activating cold programs, parking
        through hot-swaps, counting per-program metrics), the seeded
        default through the same lease when a registry is armed, or the
        bare master on a pre-registry server."""
        if registry is not None:
            with registry.lease(ref, values=values) as m:
                yield m
            return
        if ref:
            raise ProgramNotFound(
                f"program registry disabled (set MISAKA_PROGRAMS_DIR); "
                f"cannot route to program {ref!r}"
            )
        yield master

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # route through logging, not stderr
            # extra["route"] feeds the structured JSON formatter
            # (utils/jsonlog.py) so container log pipelines can group by
            # endpoint without re-parsing the request line.  getattr: a
            # malformed request line reaches send_error(400) -> here BEFORE
            # self.path is ever assigned (parse_request fails first).
            log.debug(
                fmt, *args,
                extra={"route": _route_label(getattr(self, "path", ""))},
            )

        def send_response(self, code, message=None):
            self._metrics_code = code  # read by the _observed wrapper
            super().send_response(code, message)

        def handle_one_request(self):
            """The stock request loop with the serving-plane fast parser
            (_fast_parse_request) swapped in; the stock parser remains
            the fallback for request shapes the fast path declines.
            MISAKA_FAST_HTTP=0 restores the stock loop outright."""
            if not fast_http:
                return super().handle_one_request()
            try:
                self.raw_requestline = self.rfile.readline(65537)
                if len(self.raw_requestline) > 65536:
                    self.requestline = ""
                    self.request_version = ""
                    self.command = ""
                    self.send_error(414, "Request-URI Too Long")
                    return
                if not self.raw_requestline:
                    self.close_connection = True
                    return
                # http.parse span timing starts AFTER the request line
                # arrives: on a keep-alive connection the readline above
                # blocks across idle time between requests, which is not
                # parsing
                t_parse = time.monotonic()
                parsed = _fast_parse_request(self)
                if parsed is None:  # answered an error during parsing
                    return
                if not parsed and not self.parse_request():
                    return
                self._parse_mark = (t_parse, time.monotonic() - t_parse)
                mname = "do_" + self.command
                if not hasattr(self, mname):
                    self.send_error(
                        501, f"Unsupported method ({self.command!r})"
                    )
                    return
                getattr(self, mname)()
                self.wfile.flush()  # send the response, if not already done
            except TimeoutError as e:
                # a read or write timed out: discard this connection
                self.log_error("Request timed out: %r", e)
                self.close_connection = True
            except ssl.SSLError as e:
                # deferred TLS handshake (edge.wrap_server_tls) fails on
                # the handler thread's first read: plaintext probers and
                # bad clients must cost one closed connection, not a
                # stderr traceback per attempt
                self.log_error("TLS handshake failed: %r", e)
                self.close_connection = True

        def _observed(self, method: str, inner) -> None:
            """Per-route request counter + error counter by status code +
            in-flight gauge + latency histogram around every handler —
            plus the request trace (utils/tracespan.py): begun here from
            the inbound X-Misaka-Trace header (minted otherwise), current
            on this handler thread for the whole request so the compute
            lanes and jsonlog pick it up, ended into the flight recorder
            with the response status."""
            route = _route_label(self.path)
            self._metrics_code = None  # reset: keep-alive reuses the handler
            self._extra_headers = []   # per-request; keep-alive reuse
            self._misaka_program = None  # set by _handle_post's resolution
            self._misaka_tenant = None   # set by the edge check
            trace = tracespan.begin(
                self.headers.get(tracespan.TRACE_HEADER), route=route
            )
            self._misaka_trace = trace
            mark = getattr(self, "_parse_mark", None)
            self._parse_mark = None
            if trace is not None and mark is not None:
                tracespan.add_span(trace, "http.parse", mark[0], mark[1])
            M_HTTP_INFLIGHT.inc()
            t0 = time.perf_counter()
            try:
                inner()
            finally:
                dur = time.perf_counter() - t0
                M_HTTP_LATENCY.labels(route=route).observe(dur)
                M_HTTP_REQS.labels(route=route, method=method).inc()
                code = self._metrics_code or 500
                if code >= 400:
                    M_HTTP_ERRORS.labels(route=route, code=str(code)).inc()
                M_HTTP_INFLIGHT.dec()
                if route in _SLO_ROUTES and slo.armed() and (
                    code < 400 or code >= 500
                ):
                    # edge-observed latency/error into the per-program SLO
                    # windows: the whole handler window, so queue time
                    # ahead of the engine is part of the objective.  5xx
                    # are service errors; 4xx are the client's own and
                    # count neither way.
                    slo.observe(
                        self._misaka_program, dur, error=code >= 500
                    )
                if slow_req_s is not None and dur >= slow_req_s:
                    # slow-request structured log line: trace_id rides the
                    # contextvar, program the explicit extra — with
                    # MISAKA_LOG_JSON the grep joins log <-> trace <->
                    # tenant in one line (utils/jsonlog.py)
                    log.warning(
                        "slow request: %s %.1fms (threshold %.0fms)",
                        route, dur * 1e3, slow_req_s * 1e3,
                        extra={
                            "route": route,
                            "program": self._misaka_program,
                            "trace_id": trace.trace_id
                            if trace is not None else None,
                        },
                    )
                self._misaka_trace = None
                tracespan.end(trace, status=code)

        def do_GET(self):
            self._observed("GET", self._handle_get)

        def do_POST(self):
            self._observed("POST", self._handle_post)

        def _trace_headers(self) -> None:
            """Per-request extra headers, written between send_response
            and end_headers on every response path: deprecation notices
            queued by a route, then the trace ID + Server-Timing phases
            (queue/pass from the serve spans recorded so far, total) —
            the contract client.py parses into result.timings."""
            for k, v in getattr(self, "_extra_headers", ()) or ():
                self.send_header(k, v)
            tr = getattr(self, "_misaka_trace", None)
            if tr is not None:
                self.send_header(tracespan.TRACE_HEADER, tr.trace_id)
                st = tracespan.server_timing(tr)
                if st:
                    self.send_header("Server-Timing", st)

        def _text(self, code: int, body: str) -> None:
            data = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", "text/plain; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self._trace_headers()
            self.end_headers()
            self.wfile.write(data)

        def _edge_check(self, route: str, method: str,
                        values: int = 1) -> bool:
            """Evaluate the edge chain for this request; True = admitted.
            A rejection answers the typed status (Retry-After /
            WWW-Authenticate headers included) and records an
            `edge.reject` span on the request trace so tenant + reason
            ride the flight recorder."""
            if not edge_chain.armed:
                return True
            decision = edge_chain.check(
                route, method,
                key=edge_mod.key_from_headers(self.headers),
                program=self._misaka_program,
                values=values,
            )
            self._misaka_tenant = decision.tenant
            rej = decision.reject
            if rej is None:
                return True
            if method == "POST":
                edge_mod.drain_or_close(self)  # keep-alive discipline
            for k, v in rej.headers():
                self._extra_headers.append((k, v))
            tr = getattr(self, "_misaka_trace", None)
            if tr is not None:
                tracespan.add_span(
                    tr, "edge.reject", time.monotonic(), 0.0,
                    {"tenant": decision.tenant, "reason": rej.reason},
                )
            self._text(rej.status, rej.message)
            return False

        def _form(self) -> dict[str, str]:
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(length).decode()
            return {k: v[0] for k, v in parse_qs(raw, keep_blank_values=True).items()}

        def _json(self, obj) -> None:
            self._bytes_json((json.dumps(obj) + "\n").encode())

        def _send(self, data: bytes, ctype: str) -> None:
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self._trace_headers()
            self.end_headers()
            self.wfile.write(data)

        def _bytes(self, data: bytes) -> None:
            self._send(data, "application/octet-stream")

        def _bytes_json(self, data: bytes) -> None:
            """Pre-encoded JSON body (the vectorized /compute_batch path)."""
            self._send(data, "application/json")

        def _json_status(self, code: int, obj) -> None:
            """JSON body on a non-200 status (the replay-divergence 409
            carries structured per-request diffs, not a prose line)."""
            data = (json.dumps(obj) + "\n").encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self._trace_headers()
            self.end_headers()
            self.wfile.write(data)

        def _capture_note(self, m, vals: bytes, resp: bytes,
                          op: str) -> None:
            """Cut a capture record for a request this route served
            (surface \"http\" — the engine terminated it).  The inbound
            X-Misaka-Trace header, when valid, bypasses sampling so a
            traced request is always captured."""
            inbound_id = tracespan.sanitize_id(
                self.headers.get(tracespan.TRACE_HEADER)
            )
            tr = getattr(self, "_misaka_trace", None)
            capture_mod.note(
                "http",
                program=self._misaka_program,
                trace=tr.trace_id if tr is not None else inbound_id,
                inbound=inbound_id is not None,
                vals=vals,
                resp=resp,
                status=200,
                tick=int(getattr(m, "_ticks_done", 0)),
                op=op,
            )

        def _handle_get(self):
            # /status, /trace, /metrics, /healthz are additive; the
            # reference's routes reject GET ("method GET not allowed",
            # master.go:104).
            try:
                parsed = urlparse(self.path)
                if not self._edge_check(parsed.path, "GET", values=0):
                    return
                if parsed.path == "/metrics":
                    # Prometheus text exposition v0.0.4 from the process
                    # registry: HTTP surface, device loop, native pool,
                    # distributed counters — whatever this process runs.
                    # The misaka_slo_* gauges are evaluation RESULTS, not
                    # callbacks — refresh them so a scrape always carries
                    # current burn rates (cached, cheap; no-op disarmed).
                    slo.refresh_metrics()
                    self._send(
                        metrics.render().encode(), metrics.CONTENT_TYPE
                    )
                    return
                if parsed.path == "/healthz":
                    # Cheap liveness for load balancers: no state lock, no
                    # device-array materialization (probing /status
                    # materializes device arrays under the state lock on
                    # every call — exactly wrong for a 1s-interval probe).
                    payload = {
                        "ok": True,
                        "engine": getattr(
                            master, "engine_name", "distributed-grpc"
                        ),
                        "running": master.is_running,
                        "uptime_seconds": round(
                            time.monotonic() - boot_mono, 3
                        ),
                        # capability flag for the client's wire
                        # auto-negotiation (utils/wire.py): a client must
                        # never send the headered binary form to a server
                        # that would compute on the header as payload
                        "wire_binary": True,
                    }
                    # The frontend supervisor (runtime/frontends.py, armed
                    # by app.py via server.misaka_supervisor): a shrunk or
                    # crash-looping worker pool must NEVER be silent — the
                    # probe carries an explicit degraded flag and the pool
                    # counts, while ok stays a pure liveness bit.
                    degraded = None
                    sup = getattr(self.server, "misaka_supervisor", None)
                    if sup is not None:
                        fs = sup.state()
                        payload["frontends"] = fs
                        degraded = fs["degraded"]
                    # The native C++ edge (r19, armed by app.py via
                    # server.misaka_native_edge): its counters ride the
                    # same probe so one scrape shows which tier owns the
                    # public port.
                    ne = getattr(self.server, "misaka_native_edge", None)
                    if ne is not None:
                        payload["native_edge"] = ne.state()
                    # The SLO engine (utils/slo.py): a paging burn rate is
                    # the service being unhealthy BY DECLARED OBJECTIVE —
                    # it rides the same degraded flag the PR 9 supervisor
                    # introduced, while ok stays pure liveness.
                    slo_state = slo.overall_state()
                    if slo_state is not None:
                        payload["slo"] = slo_state
                        degraded = bool(degraded) or slo_state == "page"
                    # The regression watchdog (utils/watchdog.py): a
                    # paging rule (canary failing, p99 drift) raises the
                    # SAME degraded flag — one bit for every machinery
                    # that can declare the box unwell.
                    wd_state = watchdog_mod.overall_state()
                    if wd_state is not None:
                        payload["watchdog"] = wd_state
                        degraded = bool(degraded) or wd_state == "page"
                    # The synthetic canary (runtime/canary.py), when this
                    # process runs one: last cycle's per-tier outcomes +
                    # first-failing-tier attribution.
                    from misaka_tpu.runtime import canary as canary_mod

                    cst = canary_mod.state_payload()
                    if cst is not None:
                        payload["canary"] = {
                            "failing_tier": cst["failing_tier"],
                            "consecutive_full_failures":
                                cst["consecutive_full_failures"],
                            "tiers": {
                                t: v.get("ok")
                                for t, v in cst["tiers"].items()
                            },
                        }
                    # Debug-plane memory budget (the r18 flight-recorder
                    # ring and the r20 capture ring share this accounting
                    # surface with the request-trace recorder): per-ring
                    # bytes + total, mirrored into misaka_debug_mem_bytes.
                    try:
                        from misaka_tpu.core import native_serve

                        flight_b = native_serve.flight_mem_bytes()
                    except Exception:
                        flight_b = 0
                    trace_b = tracespan.mem_bytes()
                    cap_b = capture_mod.mem_bytes()
                    M_DEBUG_MEM.labels(plane="trace").set(trace_b)
                    M_DEBUG_MEM.labels(plane="flight").set(flight_b)
                    M_DEBUG_MEM.labels(plane="capture").set(cap_b)
                    payload["debug_mem"] = {
                        "trace_bytes": trace_b,
                        "flight_bytes": flight_b,
                        "capture_bytes": cap_b,
                        "total_bytes": trace_b + flight_b + cap_b,
                    }
                    if degraded is not None:
                        payload["degraded"] = degraded
                    if edge_chain.armed:
                        # which edge stages guard this listener (and the
                        # live admission watermark) — the ops view of
                        # the door
                        payload["edge"] = edge_chain.debug_payload()
                    self._json(payload)
                    return
                if parsed.path == "/status":
                    payload = master.status()
                    payload["build"] = buildinfo.info()
                    sup = getattr(self.server, "misaka_supervisor", None)
                    if sup is not None:
                        payload["frontends"] = sup.state()
                    if registry is not None:
                        payload["programs"] = registry.summary()
                    self._json(payload)
                    return
                if parsed.path == "/programs":
                    if registry is None:
                        self._text(
                            404,
                            "program registry disabled "
                            "(set MISAKA_PROGRAMS_DIR)",
                        )
                        return
                    self._json(registry.list_programs())
                    return
                if parsed.path.startswith("/programs/"):
                    if _PROGRAM_COMPUTE_RE.match(parsed.path):
                        self._text(405, "method GET not allowed")
                        return
                    if registry is None:
                        self._text(
                            404,
                            "program registry disabled "
                            "(set MISAKA_PROGRAMS_DIR)",
                        )
                        return
                    name = unquote(parsed.path[len("/programs/"):])
                    try:
                        self._json(registry.info(name))
                    except ProgramNotFound as e:
                        self._text(404, str(e))
                    return
                if parsed.path == "/debug/usage":
                    # the per-program resource ledger (runtime/usage.py):
                    # values/requests served, CPU-seconds (fused-pass wall
                    # split by slot share), measured native-pool seconds,
                    # and queue-delay seconds, per program
                    self._json(usage.debug_payload())
                    return
                if parsed.path == "/usage/export":
                    # billing-grade export: HMAC-signed JSONL periods of
                    # cumulative per-tenant counters from the durable
                    # ledger (runtime/usage.py).  ?since= (unix seconds)
                    # bounds the window; the ledger flushes before
                    # answering so every exported number is on disk.
                    q = parse_qs(parsed.query)
                    try:
                        since = float((q.get("since") or ["0"])[0])
                    except ValueError:
                        self._text(400, "bad since= (unix seconds)")
                        return
                    try:
                        lines = usage.export_lines(since=since)
                    except usage.UsageExportError as e:
                        self._text(409, str(e))
                        return
                    body = "".join(
                        json.dumps(line, separators=(",", ":")) + "\n"
                        for line in lines
                    ).encode()
                    self._send(body, "application/x-ndjson")
                    return
                if parsed.path == "/debug/alerts":
                    # the SLO burn-rate engine (utils/slo.py): per-program
                    # ok/warning/page states with per-window burn rates
                    # and latency quantiles — plus the regression
                    # watchdog's findings (utils/watchdog.py; same
                    # surface, not a parallel one), and exemplar trace
                    # IDs from the flight recorder on anything firing:
                    # alert -> /debug/requests/<id> in one click/curl
                    payload = slo.debug_payload()
                    for prog, row in payload.get("programs", {}).items():
                        if row.get("state") != "ok":
                            row["exemplars"] = (
                                tracespan.slowest_exemplars(program=prog)
                                or tracespan.slowest_exemplars()
                            )
                    wd = watchdog_mod.debug_payload()
                    for rule in wd.get("rules", ()):
                        if rule.get("state") != "ok":
                            prog = (rule.get("labels") or {}).get("program")
                            rule["exemplars"] = (
                                tracespan.slowest_exemplars(program=prog)
                                if prog else tracespan.slowest_exemplars()
                            )
                    payload["watchdog"] = wd
                    self._json(payload)
                    return
                if parsed.path == "/debug/series":
                    # the embedded TSDB (utils/tsdb.py): retained metric
                    # history — ?name=<series>[&label=k=v...][&window=5m]
                    # queries one family; bare GET lists the catalog
                    try:
                        name, labels, window_s = tsdb_mod.parse_query(
                            parse_qs(parsed.query)
                        )
                    except tsdb_mod.TSDBError as e:
                        self._text(400, str(e))
                        return
                    if name is None:
                        self._json(tsdb_mod.index_payload())
                        return
                    self._json(
                        tsdb_mod.query_payload(name, labels, window_s)
                    )
                    return
                if parsed.path == "/debug/dashboard":
                    # the observatory (utils/dashboard.py): golden-signal
                    # sparklines over the TSDB, one self-contained page
                    from misaka_tpu.runtime import canary as canary_mod
                    from misaka_tpu.utils import dashboard as dash_mod

                    q = {k: v[0] for k, v in parse_qs(parsed.query).items()}
                    try:
                        window_s = tsdb_mod.parse_window(
                            q.get("window", "1h")
                        )
                    except tsdb_mod.TSDBError as e:
                        self._text(400, str(e))
                        return
                    extra = {"watchdog": watchdog_mod.debug_payload()}
                    cst = canary_mod.state_payload()
                    if cst is not None:
                        extra["canary"] = cst
                    html = dash_mod.render_html(
                        lambda n, w: tsdb_mod.query(n, window_s=w),
                        window_s, extra,
                    )
                    self._send(html.encode(), "text/html; charset=utf-8")
                    return
                if parsed.path == "/debug/faults":
                    # the chaos harness's live view (utils/faults.py):
                    # what is armed right now (POST re-arms; see
                    # _handle_post — the observatory drill's entry point)
                    self._json({"armed": sorted(faults.active())})
                    return
                if parsed.path == "/debug/flamegraph":
                    # the continuous profiler (utils/sampler.py): folded
                    # CPython stacks + the native busy/idle split;
                    # ?html=1 answers the self-contained viewer
                    from misaka_tpu.utils import sampler

                    q = {k: v[0] for k, v in parse_qs(parsed.query).items()}
                    if q.get("html") == "1":
                        self._send(
                            sampler.render_html().encode(),
                            "text/html; charset=utf-8",
                        )
                    else:
                        self._json(sampler.debug_payload())
                    return
                if parsed.path == "/debug/requests":
                    # the request-trace flight recorder: recent ring +
                    # slowest-K reservoir summaries (?slowest=1 for the
                    # reservoir alone — the "histogram says p99 is bad,
                    # which request was it" entry point)
                    payload = tracespan.debug_payload()
                    q = {k: v[0] for k, v in parse_qs(parsed.query).items()}
                    if q.get("slowest") == "1":
                        payload.pop("recent", None)
                    self._json(payload)
                    return
                if parsed.path.startswith("/debug/requests/"):
                    tid = parsed.path[len("/debug/requests/"):]
                    tr = tracespan.RECORDER.get(tid)
                    if tr is None:
                        self._text(404, f"no completed trace {tid!r} in "
                                        f"the flight recorder")
                        return
                    self._json(tr.to_dict())
                    return
                if parsed.path == "/debug/perfetto":
                    # Chrome trace-event JSON of the recorder contents —
                    # load in https://ui.perfetto.dev or chrome://tracing
                    self._json(tracespan.perfetto())
                    return
                if parsed.path == "/debug/captures":
                    # the capture ring's recent records (payload heads
                    # only — raw value bytes stay out of the debug JSON);
                    # ?n=100 caps the listing
                    q = {
                        k: v[0] for k, v in parse_qs(parsed.query).items()
                    }
                    try:
                        limit = int(q.get("n", "100"))
                    except ValueError:
                        limit = 100
                    self._json(capture_mod.debug_payload(limit))
                    return
                if parsed.path == "/debug/native_trace":
                    # the native flight recorder's raw per-thread rings
                    # (core/native_serve.flight_payload): serve lifecycle,
                    # dispenser phases, per-unit rung-tagged tick spans,
                    # residency events — ?n=100 caps records per ring
                    try:
                        from misaka_tpu.core import native_serve
                    except Exception:
                        self._json({"enabled": False, "pools": []})
                        return
                    q = {
                        k: v[0] for k, v in parse_qs(parsed.query).items()
                    }
                    try:
                        max_records = int(q["n"]) if "n" in q else None
                    except ValueError:
                        max_records = None
                    self._json(native_serve.flight_payload(max_records))
                    return
                if parsed.path in ("/trace", "/debug/isa_trace"):
                    # the INSTRUCTION-history listing (core/trace.py),
                    # renamed to /debug/isa_trace: "/trace" collided with
                    # the request-tracing namespace above.  The old path
                    # answers the same body plus a Deprecation header.
                    if parsed.path == "/trace":
                        self._extra_headers.append(("Deprecation", "true"))
                        self._extra_headers.append(
                            ("Link",
                             '</debug/isa_trace>; rel="successor-version"')
                        )
                    if not hasattr(master, "trace"):
                        # the distributed control plane (runtime/nodes.py)
                        # has no fused trace ring
                        self._text(404, "not found")
                        return
                    q = {k: v[0] for k, v in parse_qs(parsed.query).items()}
                    try:
                        last = int(q["last"]) if "last" in q else None
                    except ValueError:
                        self._text(400, "cannot parse last")
                        return
                    try:
                        entries = master.trace(last=last)
                    except RuntimeError as e:
                        # 409 (state conflict), not 403: tracing is a server
                        # configuration state, not an authorization denial
                        self._text(
                            409,
                            f"{e} (start the server with MISAKA_TRACE_CAP=N "
                            f"to enable tracing)",
                        )
                        return
                    self._json({"entries": entries})
                    return
                self._text(405, "method GET not allowed")
            except Exception as e:  # defensive: a handler crash must not kill the server
                log.exception("handler error")
                try:
                    self._text(500, f"internal error: {e}")
                except Exception:
                    pass

        def _handle_post(self):
            try:
                # Program addressing (the registry surface): a
                # /programs/<name>/<op> path or an X-Misaka-Program header
                # names the serving program; the op then runs the SAME
                # route body as its legacy twin against the leased engine.
                # Neither given -> the seeded default program (legacy
                # behavior, byte-compatible).
                path = self.path.split("?", 1)[0]
                pm = _PROGRAM_COMPUTE_RE.match(path)
                if pm:
                    prog_ref = unquote(pm.group(1))
                    path = "/" + pm.group(2)
                else:
                    prog_ref = self.headers.get("X-Misaka-Program") or None
                # which program this request bills to (SLO windows, slow-
                # request log lines): the addressed name, or the seeded
                # default when a registry is armed (None collapses to the
                # "default" ledger label on pre-registry servers)
                self._misaka_program = (
                    prog_ref.partition("@")[0] if prog_ref
                    else registry.default_name if registry is not None
                    else None
                )
                # The edge chain, BEFORE any route body: auth, quota, and
                # admission reject at the door — typed 401/403/429 with
                # Retry-After — while the plane still has headroom.  The
                # value estimate for quota/admission comes from the wire
                # size (raw int32s are 4 bytes each; decimal text ~8) —
                # exact enough for fair-share, and free.
                try:
                    _clen = int(self.headers.get("Content-Length") or 0)
                except ValueError:
                    _clen = 0
                _est = (
                    max(1, _clen // 4) if path == "/compute_raw"
                    else max(1, _clen // 8) if path == "/compute_batch"
                    else 1
                )
                if not self._edge_check(path, "POST", values=_est):
                    return
                if path == "/run":
                    self._form()  # drain any body (keep-alive sync)
                    try:
                        master.run()
                    except BroadcastError as e:
                        self._text(400, f"error running network: {e}")
                        return
                    self._text(200, "Success")
                elif path == "/pause":
                    self._form()  # drain any body (keep-alive sync)
                    try:
                        master.pause()
                    except BroadcastError as e:
                        self._text(400, f"error pausing network: {e}")
                        return
                    self._text(200, "Success")
                elif path == "/reset":
                    self._form()  # drain any body (keep-alive sync)
                    try:
                        master.reset()
                    except BroadcastError as e:
                        self._text(400, f"error resetting network: {e}")
                        return
                    self._text(200, "Success")
                elif path == "/load":
                    form = self._form()
                    target = form.get("targetURI", "")
                    try:
                        master.load(target, form.get("program", ""))
                    except (
                        TopologyError,
                        TISParseError,
                        TISLowerError,
                        BroadcastError,
                    ) as e:
                        self._text(
                            400, f"error loading program on node {target}: {e}"
                        )
                        return
                    self._text(200, "Success")
                elif path == "/compute":
                    # body FIRST, even on the error paths: an early return
                    # that leaves the body unread desynchronizes a
                    # keep-alive connection (the next request line would be
                    # parsed out of this request's body)
                    form = self._form()
                    try:
                        with resolved_master(prog_ref, values=1) as m:
                            if not m.is_running:
                                self._text(400, "network is not running")
                                return
                            try:
                                value = int(form.get("value", ""))
                            except ValueError:
                                self._text(400, "cannot parse value")
                                return
                            # through the serve scheduler: concurrent
                            # /compute callers coalesce into fused passes
                            # (MasterNode only — the distributed control
                            # plane keeps its per-value path)
                            coalesced = getattr(m, "compute_coalesced", None)
                            if coalesced is not None:
                                result = int(coalesced([value])[0])
                            else:
                                result = m.compute(value)
                    except ProgramNotFound as e:
                        self._text(404, str(e))
                        return
                    except ComputeTimeout as e:
                        self._text(500, str(e))
                        return
                    except PeerUnavailable as e:
                        # typed fast-fail (distributed peer down): 503 =
                        # retryable, nothing entered the pipeline
                        self._text(503, str(e))
                        return
                    if capture_mod.RECORDING:
                        self._capture_note(
                            m,
                            np.asarray([value], "<i4").tobytes(),
                            np.asarray([result], "<i4").tobytes(),
                            "coalesced" if coalesced is not None else "many",
                        )
                    self._json({"value": result})
                elif path == "/compute_batch":
                    # additive: a FIFO stream of values through one instance
                    # in a single HTTP round trip — the throughput shape of
                    # /compute (the reference moves one value per request).
                    # Body field `values`: comma/whitespace-separated ints.
                    # `spread=1` stripes the stream over free instances
                    # (order preserved) so one request can load the batch.
                    form = self._form()  # body first (keep-alive: see /compute)
                    try:
                        # vectorized decimal parse — the per-value Python of
                        # round 2 capped this route at 859k/s (textcodec.py)
                        values = dec_to_ints(form.get("values", ""))
                    except (ValueError, UnicodeEncodeError):
                        self._text(400, "cannot parse values")
                        return
                    try:
                        with resolved_master(
                            prog_ref, values=len(values)
                        ) as m:
                            if not hasattr(m, "compute_many"):
                                self._text(404, "not found")  # distributed control plane
                                return
                            if not m.is_running:
                                self._text(400, "network is not running")
                                return
                            if form.get("spread") == "1" and hasattr(
                                m, "compute_spread"
                            ):
                                # spread requests ride the serve scheduler
                                # (compute_coalesced falls back to
                                # compute_spread when MISAKA_SERVE_BATCH=0);
                                # the unspread default keeps its documented
                                # single-instance FIFO pinning.  The
                                # distributed control plane has no scheduler
                                # at all — its compute_spread is the
                                # whole-pipeline stream lane (an r8
                                # regression 500'd here)
                                coalesced = getattr(
                                    m, "compute_coalesced",
                                    m.compute_spread,
                                )
                                result = coalesced(values, return_array=True)
                            else:
                                result = m.compute_many(
                                    values, return_array=True
                                )
                    except ProgramNotFound as e:
                        self._text(404, str(e))
                        return
                    except ComputeTimeout as e:
                        self._text(500, str(e))
                        return
                    except PeerUnavailable as e:
                        self._text(503, str(e))
                        return
                    if capture_mod.RECORDING:
                        self._capture_note(
                            m,
                            np.asarray(values, "<i4").tobytes(),
                            np.asarray(result, "<i4").tobytes(),
                            "coalesced"
                            if form.get("spread") == "1"
                            and hasattr(m, "compute_spread")
                            else "many",
                        )
                    # one vectorized pass; pad spaces are legal JSON
                    # whitespace, so json.loads clients decode unchanged
                    self._bytes_json(
                        b'{"values": [' + ints_to_dec(result, b",") + b"]}\n"
                    )
                elif path == "/compute_raw":
                    # additive: the wire-efficient twin of /compute_batch —
                    # request body is raw little-endian int32 values, the
                    # response body is raw int32 outputs, order preserved.
                    # Striped over free instances by default (?spread=0 to
                    # pin one instance).  This is the fleet-client surface:
                    # at engine rates the text route's encode/parse dominates.
                    # Robust body handling for the fleet wire format: a
                    # missing Content-Length is 411 (this surface does not
                    # speak chunked bodies) and an oversized one is 413
                    # against the MISAKA_MAX_BODY cap — never an unbounded
                    # rfile.read.  Both close the connection: the unread
                    # body would desynchronize the next keep-alive request.
                    length_hdr = self.headers.get("Content-Length")
                    if length_hdr is None:
                        self.close_connection = True
                        self._text(411, "Content-Length required")
                        return
                    try:
                        length = int(length_hdr)
                    except ValueError:
                        self.close_connection = True
                        self._text(400, "cannot parse Content-Length")
                        return
                    if length > max_body:
                        self.close_connection = True
                        self._text(
                            413,
                            f"body of {length} bytes exceeds the "
                            f"{max_body}-byte cap (MISAKA_MAX_BODY)",
                        )
                        return
                    raw = self.rfile.read(length)
                    # post-body checks (body consumed: keep-alive stays
                    # synchronized through these early returns)
                    if wire.is_binary(self.headers.get("Content-Type")):
                        # the headered binary protocol (utils/wire.py):
                        # validated framing, same zero-copy payload
                        try:
                            raw = wire.unpack(raw)
                        except wire.WireError as e:
                            self._text(400, f"bad binary body: {e}")
                            return
                    if len(raw) % 4:
                        self._text(400, "body must be raw int32 values")
                        return
                    values = np.frombuffer(raw, dtype="<i4")
                    q = {
                        k: v[0]
                        for k, v in parse_qs(urlparse(self.path).query).items()
                    }
                    try:
                        with resolved_master(
                            prog_ref, values=int(values.size)
                        ) as m:
                            if not hasattr(m, "compute_spread"):
                                self._text(404, "not found")  # distributed control plane
                                return
                            if not m.is_running:
                                self._text(400, "network is not running")
                                return
                            if q.get("spread", "1") == "1":
                                # the serve scheduler lane (falls back to
                                # compute_spread when MISAKA_SERVE_BATCH=0,
                                # and to the distributed control plane's
                                # stream lane, which has no scheduler — an
                                # r8 regression 500'd every distributed
                                # /compute_raw until r9)
                                coalesced = getattr(
                                    m, "compute_coalesced",
                                    m.compute_spread,
                                )
                                result = coalesced(values, return_array=True)
                            else:
                                result = np.asarray(
                                    m.compute_many(values), np.int32
                                )
                    except ProgramNotFound as e:
                        self._text(404, str(e))
                        return
                    except ComputeTimeout as e:
                        self._text(500, str(e))
                        return
                    except PeerUnavailable as e:
                        self._text(503, str(e))
                        return
                    payload = result.astype("<i4").tobytes()
                    if capture_mod.RECORDING:
                        self._capture_note(
                            m,
                            values.tobytes(),
                            payload,
                            "coalesced"
                            if q.get("spread", "1") == "1"
                            else "many",
                        )
                    if wire.accepts_binary(self.headers.get("Accept")):
                        self._send(wire.header(len(payload) // 4) + payload,
                                   wire.CONTENT_TYPE)
                    else:
                        self._bytes(payload)  # legacy headerless raw
                elif path == "/programs":
                    # the registry upload surface: publish one program
                    # version (TIS source, topology JSON, or compose YAML)
                    # under a name; publishing a NEW version over a live
                    # engine hot-swaps it with zero client-visible errors
                    # (runtime/registry.py)
                    form = self._form()  # body first (keep-alive)
                    if registry is None:
                        self._text(
                            404,
                            "program registry disabled "
                            "(set MISAKA_PROGRAMS_DIR)",
                        )
                        return
                    # ?verify=replay (or form field): gate the hot-swap on
                    # a green shadow replay of the last captured requests —
                    # deploy-didn't-happen on divergence (409 with the
                    # per-request diffs)
                    q = {
                        k: v[0]
                        for k, v in parse_qs(urlparse(self.path).query).items()
                    }
                    verify = q.get("verify") or form.get("verify") or None
                    try:
                        result = registry.publish(
                            form.get("name", ""),
                            tis=form.get("program"),
                            topology_json=form.get("topology"),
                            compose=form.get("compose"),
                            slo_spec=form.get("slo"),
                            quota_spec=form.get("quota"),
                            verify=verify,
                        )
                    except ReplayDivergence as e:
                        # typed: the candidate answered captured traffic
                        # differently — the registry refused the swap
                        self._json_status(409, {
                            "error": str(e),
                            "diffs": e.diffs,
                        })
                        return
                    except (
                        RegistryError,
                        TopologyError,
                        TISParseError,
                        TISLowerError,
                    ) as e:
                        self._text(400, f"error publishing program: {e}")
                        return
                    self._json(result)
                elif path == "/captures/start":
                    # arm the wire-level recorder, anchoring a pre-capture
                    # state snapshot per live program so the capture
                    # replays from a known starting checkpoint
                    self._form()  # drain any body (keep-alive sync)
                    anchors = {}
                    label = (
                        registry.default_name
                        if registry is not None else None
                    ) or "default"
                    a = capture_mod.anchor_from_master(label, master)
                    if a is not None:
                        anchors[label] = a
                    if registry is not None:
                        for name, m in registry.active_masters():
                            if name in anchors:
                                continue
                            a = capture_mod.anchor_from_master(name, m)
                            if a is not None:
                                anchors[name] = a
                    try:
                        capture_mod.start(anchors=anchors)
                    except capture_mod.CaptureError as e:
                        self._text(409, str(e))
                        return
                    self._json(capture_mod.status())
                elif path == "/captures/stop":
                    self._form()  # drain any body (keep-alive sync)
                    capture_mod.stop()
                    self._json(capture_mod.status())
                elif path == "/captures/rotate":
                    # deterministic spool cut: finalize the current ring
                    # as the next spool-<seq>.mskcap segment (anchors +
                    # manifest) and re-arm with fresh anchors — the same
                    # rotation the always-on daemon performs on size/age
                    self._form()  # drain any body (keep-alive sync)
                    try:
                        result = capture_mod.rotate_now()
                    except capture_mod.CaptureError as e:
                        self._text(409, str(e))
                        return
                    self._json(result if result is not None
                               else {"rotated": False, "reason": "empty ring"})
                elif path == "/captures/export":
                    # spill the ring to a durable segment file (+ anchor
                    # checkpoints); admin-gated, so a caller-chosen path is
                    # an operator decision, not an open write primitive
                    form = self._form()
                    try:
                        result = capture_mod.export(form.get("path") or None)
                    except capture_mod.CaptureError as e:
                        self._text(409, str(e))
                        return
                    self._json(result)
                elif path == "/fleet/drain":
                    # Fleet-roll drain control (runtime/fleet.py): arm or
                    # disarm drain on this replica's compute plane and
                    # report quiescence.  While draining, the plane
                    # answers new frames with the reroute status (the
                    # fleet router shifts them to siblings with zero
                    # client-visible errors); the roll polls this route
                    # until both in-flight counts reach zero before
                    # checkpointing and replacing the process.
                    form = self._form()  # body first (keep-alive)
                    plane = getattr(self.server, "misaka_plane", None)
                    if plane is None:
                        self._text(
                            404,
                            "no compute plane on this server (a fleet "
                            "replica runs with MISAKA_PLANE_SERVE=1)",
                        )
                        return
                    on = form.get("state", "on") != "off"
                    plane.set_draining(on)
                    # the in-flight gauge counts THIS request too
                    self._json({
                        "draining": on,
                        "inflight": plane.inflight(),
                        "http_inflight": max(
                            0, int(M_HTTP_INFLIGHT.value) - 1
                        ),
                    })
                elif path == "/checkpoint":
                    # additive routes: the reference cannot checkpoint
                    name = self._form().get("name", "")  # body first
                    if not checkpoint_dir:
                        self._text(403, "checkpointing disabled (no checkpoint_dir configured)")
                        return
                    path = resolve_checkpoint(name)
                    if path is None:
                        self._text(400, "invalid checkpoint name")
                        return
                    os.makedirs(checkpoint_dir, exist_ok=True)
                    master.save_checkpoint(path)
                    self._text(200, "Success")
                elif path == "/restore":
                    name = self._form().get("name", "")  # body first
                    if not checkpoint_dir:
                        self._text(403, "checkpointing disabled (no checkpoint_dir configured)")
                        return
                    path = resolve_checkpoint(name)
                    if path is None:
                        self._text(400, "invalid checkpoint name")
                        return
                    try:
                        master.load_checkpoint(path)
                    except (OSError, KeyError, ValueError, zipfile.BadZipFile) as e:
                        self._text(400, f"error restoring checkpoint: {e}")
                        return
                    self._text(200, "Success")
                elif path == "/profile/start":
                    # additive: capture a jax.profiler trace of the live
                    # device loop (SURVEY.md §5 — the reference has nothing)
                    name = self._form().get("name", "profile")  # body first
                    if not profile_dir:
                        self._text(403, "profiling disabled (no profile_dir configured)")
                        return
                    if not _name_re.match(name) or ".." in name:
                        self._text(400, "invalid profile name")
                        return
                    os.makedirs(profile_dir, exist_ok=True)
                    try:
                        profiler.start(os.path.join(profile_dir, name))
                    except ProfilerError as e:
                        self._text(409, str(e))
                        return
                    self._text(200, "Success")
                elif path == "/profile/stop":
                    if not profile_dir:
                        self._text(403, "profiling disabled (no profile_dir configured)")
                        return
                    try:
                        out = profiler.stop()
                    except ProfilerError as e:
                        self._text(409, str(e))
                        return
                    self._text(200, out)
                elif path == "/debug/faults":
                    # (re-)arm the chaos harness on a RUNNING server —
                    # the observatory drill's entry point: a fleet fans
                    # this out to every replica, so a scoped
                    # serve_delay:<program> fault can be injected (and
                    # cleared, spec="") across subprocess boundaries
                    # where an in-process faults.configure cannot reach.
                    # ADMIN-scoped at the edge (runtime/edge.py): fault
                    # injection is an operator mutation.
                    form = self._form()
                    try:
                        faults.configure(form.get("spec") or None)
                    except faults.FaultSpecError as e:
                        self._text(400, str(e))
                        return
                    self._json({"armed": sorted(faults.active())})
                elif path == "/edge/token":
                    # Mint a signed short-lived tenant token (runtime/
                    # edge.py): HMAC over {tenant, expiry, scope}, verified
                    # locally by EVERY replica sharing the secret — no
                    # lookup table to distribute, no coordination.  ADMIN-
                    # scoped at the edge: minting is credential issuance.
                    form = self._form()  # body first (keep-alive)
                    if edge_chain.token_secret is None:
                        self._text(
                            503,
                            "token minting disabled (set "
                            "MISAKA_TOKEN_SECRET or MISAKA_PLANE_SECRET)",
                        )
                        return
                    tenant = (form.get("tenant") or "").strip()
                    if not tenant:
                        self._text(400, "missing tenant")
                        return
                    try:
                        ttl = float(form.get("ttl") or 300.0)
                    except ValueError:
                        self._text(400, "cannot parse ttl")
                        return
                    ttl = min(max(ttl, 1.0), 86400.0)
                    programs = [
                        p.strip()
                        for p in (form.get("programs") or "").split(",")
                        if p.strip()
                    ] or None
                    token, exp = edge_mod.mint_tenant_token(
                        edge_chain.token_secret, tenant, ttl_s=ttl,
                        admin=(form.get("admin") or "")
                        in ("1", "true", "on"),
                        programs=programs,
                    )
                    self._json({
                        "token": token,
                        "tenant": tenant,
                        "expires_at": exp,
                        "ttl_s": ttl,
                    })
                elif path == "/edge/gossip":
                    # Usage-gossip ingress (runtime/fleet.py gossip hub):
                    # drain local token buckets by the remote fleet-wide
                    # admissions since the sender's last round, answer
                    # with this replica's own cumulative snapshot.
                    # ADMIN-scoped: quota reconciliation is an operator
                    # (hub) mutation, not a tenant surface.
                    length = int(self.headers.get("Content-Length") or 0)
                    raw = self.rfile.read(length) if length else b""
                    try:
                        payload = json.loads(raw or b"{}")
                        drained = edge_chain.apply_remote_usage(
                            payload.get("usage") or {},
                            source=str(payload.get("source") or "peer"),
                        )
                    except (ValueError, TypeError) as e:
                        edge_mod.M_EDGE_GOSSIP_ROUNDS.labels(
                            status="error"
                        ).inc()
                        self._text(400, f"bad gossip payload: {e}")
                        return
                    edge_mod.M_EDGE_GOSSIP_ROUNDS.labels(
                        status="ok" if drained else "stale"
                    ).inc()
                    self._json({
                        "drained": drained,
                        "usage": edge_chain.usage_snapshot(),
                    })
                else:
                    # unknown POST: the body (arbitrary size) is unread —
                    # close instead of desynchronizing the connection
                    self.close_connection = True
                    self._text(404, "not found")
            except Exception as e:  # defensive: a handler crash must not kill the server
                log.exception("handler error")
                try:
                    self.close_connection = True  # request state unknown
                    self._text(500, f"internal error: {e}")
                except Exception:
                    pass

    class _Server(ThreadingHTTPServer):
        # socketserver's default listen backlog of 5 RSTs simultaneous
        # connection bursts (64 keep-alive clients dialing at once lose
        # a third of their dials on a loaded box); 128 is what real
        # serving tiers ask for and the kernel clamps to somaxconn
        request_queue_size = 128

    httpd = _Server(("0.0.0.0", port), Handler)
    if tls is False:
        ctx = None
    elif tls is None:
        ctx = edge_mod.tls_context_from_env()
    else:
        ctx = tls
    return edge_mod.wrap_server_tls(httpd, ctx)
