"""Master node: the reference's HTTP control surface over the TPU engine.

Route-for-route and message-for-message compatible with the Go master
(master.go:90-230): POST /run /pause /reset /load /compute, form-encoded
bodies, "Success" / JSON `{"value": N}` responses, 400 on errors, 405 with
"method GET not allowed" on non-POST.  What changes is everything beneath:
instead of broadcasting gRPC commands to node processes (master.go:269-351),
control toggles a host flag around a jitted device loop; instead of cap-1
channels bridged by per-value RPC (master.go:233-249), I/O moves through
device-resident rings synced each chunk.

Deliberate divergences (SURVEY.md quirks, each strictly better and test-pinned):
  * /compute responses are correlated — a lock serializes request pairing,
    fixing the reference's response-swap race (quirk #2, master.go:216-219).
  * /load targets the node directly in-process — the reference dials the
    wrong port and cannot actually live-load (quirk #1, master.go:178).
  * pause preserves in-flight state exactly (the reference cancels blocked
    ops with errors, program.go:196-204); resume continues where it stopped.
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

from misaka_tpu.runtime.topology import Topology, TopologyError
from misaka_tpu.tis.parser import TISParseError
from misaka_tpu.tis.lower import TISLowerError

log = logging.getLogger("misaka_tpu.master")


class ComputeTimeout(RuntimeError):
    """The network produced no output for a /compute value in time."""


class BroadcastError(RuntimeError):
    """A control-plane fan-out failed on at least one node (master.go:288-292).

    Defined here (not in runtime.nodes, which raises it) so the shared HTTP
    surface can catch it without importing the grpc-dependent distributed
    module — the fused master must work with jax+numpy alone.
    """


class MasterNode:
    """Control plane + I/O gateway for one fused network."""

    def __init__(
        self,
        topology: Topology,
        chunk_steps: int = 128,
        trace_cap: int | None = None,
        batch: int | None = None,
    ):
        """batch=None serves one network instance (every /compute strictly
        serialized — the correlated fix for quirk #2).  batch=B runs B
        independent instances in lockstep (the engine's vmap axis) and
        round-robins concurrent /compute requests across them: up to B
        requests progress in parallel, each instance's request/response
        pairing still strictly FIFO.  The reference allows concurrency only
        by racing (master.go:216-219 swaps responses); this is the
        deterministic version of that capability."""
        if batch is not None and trace_cap is not None:
            raise ValueError("tracing drives a single instance (batch=None)")
        if batch is not None and batch < 1:
            raise ValueError(f"batch must be >= 1, got {batch}")
        self._topology = topology
        self._chunk = chunk_steps
        self._batch = batch
        self._net = topology.compile(batch=batch)
        self._state = self._net.init_state()
        # Optional per-lane instruction trace ring (core/trace.py).  The debug
        # path: every tick of every lane is recorded device-side and decoded
        # on demand via self.trace() / GET /trace.
        self._trace_cap = trace_cap
        self._trace = self._net.init_trace(trace_cap) if trace_cap else None
        self._running = False
        self._loop: threading.Thread | None = None
        self._state_lock = threading.Lock()      # guards _state/_net swaps
        self._lifecycle_lock = threading.RLock() # serializes run/pause/reset/load
        # Unbatched: one global pairing lock + one queue pair.  Batched: a
        # queue pair + pairing lock + stale counter PER INSTANCE, and a
        # round-robin dispenser.
        n_slots = batch or 1
        self._compute_locks = [threading.Lock() for _ in range(n_slots)]
        self._in_qs = [queue.Queue() for _ in range(n_slots)]
        self._out_qs = [queue.Queue() for _ in range(n_slots)]
        self._in_q = self._in_qs[0]  # the unbatched device-loop path
        self._rr = 0
        self._rr_lock = threading.Lock()
        # Outputs orphaned by /compute timeouts; discarded on arrival so the
        # request/response pairing stays correlated (quirk #2 stays fixed).
        # The epoch invalidates that bookkeeping across reset/load: a compute
        # whose request was wiped by a queue drain must NOT mark its missing
        # output as stale (there is no output coming — a phantom stale entry
        # would mispair every later request on the slot).
        self._stale = [0] * n_slots
        self._epoch = 0
        # Host-side tick-rate gauge, maintained solely by the device loop
        # (readers of /status never mutate it).
        self._ticks_done = 0
        self._rate: float | None = None
        self._rate_mark_tick = 0
        self._rate_mark_time = time.monotonic()

    # --- lifecycle (the broadcastCommand surface, master.go:269-351) -------

    def run(self) -> None:
        with self._lifecycle_lock:
            if self._running:
                log.info("network is already running")
                return
            self._running = True
            self._loop = threading.Thread(target=self._device_loop, daemon=True)
            self._loop.start()
            log.info("network was run")

    def pause(self) -> None:
        with self._lifecycle_lock:
            if not self._running:
                log.info("network is already paused")
                return
            self._running = False
            if self._loop:
                self._loop.join()
            self._rate = None
            log.info("network was paused")

    def reset(self) -> None:
        """Stop + zero all state and queues (stopNode/resetNode, master.go:252-266)."""
        with self._lifecycle_lock:
            self.pause()
            with self._state_lock:
                self._state = self._net.init_state()
                if self._trace_cap:
                    self._trace = self._net.init_trace(self._trace_cap)
            self._drain_queues()
            log.info("network was reset")

    def load(self, target: str, program: str) -> None:
        """Reprogram one node; resets the whole network (master.go:145-195).

        Ordering parity: target validation happens BEFORE anything stops
        (master.go:158-163 — a bad target leaves the network running), while a
        program that fails to compile is discovered after the reset, leaving
        the network stopped with its old programs (LoadProgram errors before
        overwriting p.asm, program.go:178-193).
        """
        with self._lifecycle_lock:
            new_topology = self._topology.with_program(target, program)  # validates target
            self.pause()
            try:
                new_net = new_topology.compile(batch=self._batch)  # may raise parse/lower errors
            except Exception:
                with self._state_lock:
                    self._state = self._net.init_state()
                self._drain_queues()
                raise
            with self._state_lock:
                self._topology = new_topology
                self._net = new_net
                self._state = new_net.init_state()
                if self._trace_cap:
                    self._trace = new_net.init_trace(self._trace_cap)
            self._drain_queues()
            log.info("successfully loaded program")

    def compute(self, value: int, timeout: float = 30.0) -> int:
        """One value in, one value out — correlated (fixes quirk #2).

        Batched masters prefer a FREE instance (try-acquire scan from a
        rotating start) so one slow request can't head-of-line block traffic
        while other instances idle; only when every instance is busy does
        the caller block on one.  On timeout the in-flight value's eventual
        output is recorded as stale and discarded when it surfaces, so later
        calls on that instance stay correctly paired — unless a reset/load
        wiped the request (epoch bump), in which case no output is coming
        and nothing is marked stale.
        """
        n = len(self._in_qs)
        with self._rr_lock:
            start = self._rr
            self._rr = (self._rr + 1) % n
        slot = None
        for i in range(n):
            cand = (start + i) % n
            if self._compute_locks[cand].acquire(blocking=False):
                slot = cand
                break
        if slot is None:  # all instances busy: wait on the rotating one
            slot = start
            self._compute_locks[slot].acquire()
        try:
            epoch = self._epoch
            self._in_qs[slot].put(value)
            deadline = time.monotonic() + timeout
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    if self._epoch == epoch:
                        self._stale[slot] += 1
                    raise ComputeTimeout(f"no output for value {value} after {timeout}s")
                try:
                    out = self._out_qs[slot].get(timeout=remaining)
                except queue.Empty:
                    if self._epoch == epoch:
                        self._stale[slot] += 1
                    raise ComputeTimeout(f"no output for value {value} after {timeout}s")
                if self._stale[slot]:
                    self._stale[slot] -= 1
                    continue  # a previously timed-out request's output; drop it
                return out
        finally:
            self._compute_locks[slot].release()

    @property
    def is_running(self) -> bool:
        return self._running

    def status(self) -> dict:
        """Live metrics (additive vs the reference, which has none —
        SURVEY.md §5: stdlib log lines were its only observability).

        All device arrays are materialized UNDER the state lock: the device
        loop donates state buffers into each jitted chunk, so touching them
        outside the lock races with invalidation on TPU.
        """
        with self._state_lock:
            state = self._state
            topo = self._topology
            # Batched states carry a leading [B] axis; report totals across
            # instances (tick is lockstep-identical, take instance 0).
            tick = int(np.asarray(state.tick).flat[0])
            retired = np.asarray(state.retired)
            stack_top = np.asarray(state.stack_top)
            if self._batch is not None:
                retired = retired.sum(axis=0)
                stack_top = stack_top.sum(axis=0)
            in_depth = int(np.asarray(state.in_wr - state.in_rd).sum())
            out_depth = int(np.asarray(state.out_wr - state.out_rd).sum())
        status = {
            "running": self._running,
            "tick": tick,
            "ticks_per_sec": self._rate,  # maintained by the device loop
            "retired_per_lane": {
                name: int(retired[i]) for name, i in topo.lane_ids().items()
            },
            "stack_depth": {
                name: int(stack_top[i]) for name, i in topo.stack_ids().items()
            },
            "in_queue": sum(q.qsize() for q in self._in_qs) + in_depth,
            "out_queue": sum(q.qsize() for q in self._out_qs) + out_depth,
            "nodes": dict(topo.node_info),
        }
        if self._batch is not None:
            status["batch"] = self._batch
        return status

    def trace(self, last: int | None = None) -> list[dict]:
        """Decoded instruction history, oldest first (requires trace_cap).

        Buffers are materialized under the state lock — the device loop
        donates the trace ring into each traced chunk.
        """
        from misaka_tpu.core.trace import TraceRing, decode_trace

        if self._trace is None:
            raise RuntimeError("tracing disabled (construct MasterNode with trace_cap)")
        with self._state_lock:
            ring = TraceRing(
                buf=np.asarray(self._trace.buf).copy(),
                wr=np.asarray(self._trace.wr).copy(),
            )
            net = self._net
            topo = self._topology
        return decode_trace(
            ring,
            net.code,
            net.prog_len,
            lane_names=list(topo.lane_ids()),
            stack_names=list(topo.stack_ids()),
            last=last,
        )

    def save_checkpoint(self, path: str) -> None:
        """Whole-network state + topology to one .npz (SURVEY.md §5: the
        reference cannot checkpoint at all; here state is one pytree).

        Arrays are materialized under the state lock (see status()).
        """
        with self._state_lock:
            state = self._state
            topo = self._topology
            arrays = {f: np.asarray(getattr(state, f)) for f in state._fields}
        arrays["__topology__"] = np.frombuffer(
            json.dumps(
                {
                    "nodes": topo.node_info,
                    "programs": topo.programs,
                    "stack_cap": topo.stack_cap,
                    "in_cap": topo.in_cap,
                    "out_cap": topo.out_cap,
                    "batch": self._batch,
                }
            ).encode(),
            dtype=np.uint8,
        )
        np.savez(path, **arrays)

    def load_checkpoint(self, path: str) -> None:
        """Restore state + programs from a .npz written by save_checkpoint.

        Capacities travel in the checkpoint: a snapshot taken under different
        ring/stack caps restores those caps, keeping the state arrays and the
        compiled network consistent.
        """
        import jax.numpy as jnp

        from misaka_tpu.core.state import NetworkState

        with np.load(path) as data:
            meta = json.loads(bytes(data["__topology__"]).decode())
            state = NetworkState(
                **{f: jnp.asarray(data[f]) for f in NetworkState._fields}
            )
        ckpt_batch = meta.get("batch")
        if ckpt_batch != self._batch:
            raise ValueError(
                f"checkpoint batch={ckpt_batch} does not match this master's "
                f"batch={self._batch} (request queues are per-instance)"
            )
        new_topology = Topology(
            node_info=meta["nodes"],
            programs=meta["programs"],
            stack_cap=int(meta.get("stack_cap", self._topology.stack_cap)),
            in_cap=int(meta.get("in_cap", self._topology.in_cap)),
            out_cap=int(meta.get("out_cap", self._topology.out_cap)),
        )
        with self._lifecycle_lock:
            self.pause()
            new_net = new_topology.compile(batch=self._batch)
            with self._state_lock:
                self._topology = new_topology
                self._net = new_net
                self._state = state
                if self._trace_cap:
                    self._trace = new_net.init_trace(self._trace_cap)
            self._drain_queues()
        log.info("checkpoint restored from %s", path)

    def snapshot(self):
        """Whole-network state as one pytree — checkpointing for free.

        Deep-copied: the device loop donates its state buffers into each
        jitted chunk, which would invalidate a live reference.
        """
        import jax

        with self._state_lock:
            return jax.tree.map(lambda x: x.copy(), self._state)

    def restore(self, state) -> None:
        import jax

        with self._state_lock:
            self._state = jax.tree.map(lambda x: x.copy(), state)

    # --- the device loop ----------------------------------------------------

    def _drain_queues(self) -> None:
        for q in (*self._in_qs, *self._out_qs):
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
        # reset/load wipe the rings: nothing stale survives, and any compute
        # still waiting must not record its wiped request as stale (epoch).
        self._stale = [0] * len(self._stale)
        self._epoch += 1

    def _device_loop(self) -> None:
        """Run jitted chunks; sync rings with host queues at the boundaries."""
        try:
            self._device_loop_inner()
        except Exception:
            # A crashed loop must not strand /compute callers in a silent
            # 30s timeout; stop cleanly and leave the log trail.
            log.exception("device loop crashed; network stopped")
            self._running = False

    def _device_loop_inner(self) -> None:
        while self._running:
            busy = False
            with self._state_lock:
                state = self._state
                if self._batch is None:
                    pending = []
                    free = self._net.in_cap - int(state.in_wr - state.in_rd)
                    while len(pending) < free:
                        try:
                            pending.append(self._in_q.get_nowait())
                        except queue.Empty:
                            break
                    if pending:
                        state, _ = self._net.feed(state, pending)
                        busy = True
                elif any(not q.empty() for q in self._in_qs):
                    # allocate the [B, in_cap] feed matrix only when there is
                    # actually something queued — an idle batched loop must
                    # not churn 256KB/iteration
                    vals = np.zeros((self._batch, self._net.in_cap), np.int32)
                    counts = np.zeros((self._batch,), np.int32)
                    free = self._net.in_cap - (
                        np.asarray(state.in_wr) - np.asarray(state.in_rd)
                    )
                    for b in range(self._batch):
                        while counts[b] < free[b]:
                            try:
                                vals[b, counts[b]] = self._in_qs[b].get_nowait()
                                counts[b] += 1
                            except queue.Empty:
                                break
                    if counts.any():
                        state = self._net.feed_batched(state, vals, counts)
                        busy = True
                if self._trace is not None:
                    state, self._trace = self._net.run_traced(
                        state, self._trace, self._chunk
                    )
                else:
                    state = self._net.run(state, self._chunk)
                self._ticks_done += self._chunk
                now = time.monotonic()
                if now - self._rate_mark_time > 2:
                    self._rate = (self._ticks_done - self._rate_mark_tick) / (
                        now - self._rate_mark_time
                    )
                    self._rate_mark_tick = self._ticks_done
                    self._rate_mark_time = now
                if self._batch is None:
                    state, outs = self._net.drain(state)
                    per_slot = [outs]
                else:
                    state, per_slot = self._net.drain_batched(state)
                self._state = state
            for slot, outs in enumerate(per_slot):
                for v in outs:
                    self._out_qs[slot].put(v)
                if outs:
                    busy = True
            if not busy:
                # Nothing moved: the network is parked on empty queues.  Idle
                # gently instead of burning host CPU on no-op chunks.
                time.sleep(0.001)


def make_http_server(
    master: MasterNode,
    port: int = 8000,
    checkpoint_dir: str | None = None,
    profile_dir: str | None = None,
) -> ThreadingHTTPServer:
    """The five client routes (master.go:90-224), byte-compatible, plus the
    additive /status, /trace, /checkpoint, /restore, /profile/* routes.

    HTTP checkpointing is DISABLED unless `checkpoint_dir` is configured;
    when enabled, clients pass a bare checkpoint NAME (no path separators)
    resolved inside that directory — an unauthenticated form field must not
    choose arbitrary server-side filesystem paths.  The Python API
    (MasterNode.save_checkpoint/load_checkpoint) keeps full-path freedom for
    local callers.
    """
    import os
    import re
    import zipfile

    from misaka_tpu.utils.profiling import Profiler, ProfilerError

    _name_re = re.compile(r"^[A-Za-z0-9._-]{1,128}$")
    profiler = Profiler()

    def resolve_checkpoint(name: str) -> str | None:
        if not checkpoint_dir or not _name_re.match(name) or ".." in name:
            return None
        return os.path.join(checkpoint_dir, name if name.endswith(".npz") else name + ".npz")

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # route through logging, not stderr
            log.debug(fmt, *args)

        def _text(self, code: int, body: str) -> None:
            data = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", "text/plain; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _form(self) -> dict[str, str]:
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(length).decode()
            return {k: v[0] for k, v in parse_qs(raw, keep_blank_values=True).items()}

        def _json(self, obj) -> None:
            data = (json.dumps(obj) + "\n").encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            # /status and /trace are additive; the reference's routes reject
            # GET ("method GET not allowed", master.go:104).
            try:
                parsed = urlparse(self.path)
                if parsed.path == "/status":
                    self._json(master.status())
                    return
                if parsed.path == "/trace":
                    if not hasattr(master, "trace"):
                        # the distributed control plane (runtime/nodes.py)
                        # has no fused trace ring
                        self._text(404, "not found")
                        return
                    q = {k: v[0] for k, v in parse_qs(parsed.query).items()}
                    try:
                        last = int(q["last"]) if "last" in q else None
                    except ValueError:
                        self._text(400, "cannot parse last")
                        return
                    try:
                        entries = master.trace(last=last)
                    except RuntimeError as e:
                        self._text(403, str(e))
                        return
                    self._json({"entries": entries})
                    return
                self._text(405, "method GET not allowed")
            except Exception as e:  # defensive: a handler crash must not kill the server
                log.exception("handler error")
                try:
                    self._text(500, f"internal error: {e}")
                except Exception:
                    pass

        def do_POST(self):
            try:
                if self.path == "/run":
                    try:
                        master.run()
                    except BroadcastError as e:
                        self._text(400, f"error running network: {e}")
                        return
                    self._text(200, "Success")
                elif self.path == "/pause":
                    try:
                        master.pause()
                    except BroadcastError as e:
                        self._text(400, f"error pausing network: {e}")
                        return
                    self._text(200, "Success")
                elif self.path == "/reset":
                    try:
                        master.reset()
                    except BroadcastError as e:
                        self._text(400, f"error resetting network: {e}")
                        return
                    self._text(200, "Success")
                elif self.path == "/load":
                    form = self._form()
                    target = form.get("targetURI", "")
                    try:
                        master.load(target, form.get("program", ""))
                    except (
                        TopologyError,
                        TISParseError,
                        TISLowerError,
                        BroadcastError,
                    ) as e:
                        self._text(
                            400, f"error loading program on node {target}: {e}"
                        )
                        return
                    self._text(200, "Success")
                elif self.path == "/compute":
                    if not master.is_running:
                        self._text(400, "network is not running")
                        return
                    form = self._form()
                    try:
                        value = int(form.get("value", ""))
                    except ValueError:
                        self._text(400, "cannot parse value")
                        return
                    try:
                        result = master.compute(value)
                    except ComputeTimeout as e:
                        self._text(500, str(e))
                        return
                    self._json({"value": result})
                elif self.path == "/checkpoint":
                    # additive routes: the reference cannot checkpoint
                    if not checkpoint_dir:
                        self._text(403, "checkpointing disabled (no checkpoint_dir configured)")
                        return
                    name = self._form().get("name", "")
                    path = resolve_checkpoint(name)
                    if path is None:
                        self._text(400, "invalid checkpoint name")
                        return
                    os.makedirs(checkpoint_dir, exist_ok=True)
                    master.save_checkpoint(path)
                    self._text(200, "Success")
                elif self.path == "/restore":
                    if not checkpoint_dir:
                        self._text(403, "checkpointing disabled (no checkpoint_dir configured)")
                        return
                    name = self._form().get("name", "")
                    path = resolve_checkpoint(name)
                    if path is None:
                        self._text(400, "invalid checkpoint name")
                        return
                    try:
                        master.load_checkpoint(path)
                    except (OSError, KeyError, ValueError, zipfile.BadZipFile) as e:
                        self._text(400, f"error restoring checkpoint: {e}")
                        return
                    self._text(200, "Success")
                elif self.path == "/profile/start":
                    # additive: capture a jax.profiler trace of the live
                    # device loop (SURVEY.md §5 — the reference has nothing)
                    if not profile_dir:
                        self._text(403, "profiling disabled (no profile_dir configured)")
                        return
                    name = self._form().get("name", "profile")
                    if not _name_re.match(name) or ".." in name:
                        self._text(400, "invalid profile name")
                        return
                    os.makedirs(profile_dir, exist_ok=True)
                    try:
                        profiler.start(os.path.join(profile_dir, name))
                    except ProfilerError as e:
                        self._text(409, str(e))
                        return
                    self._text(200, "Success")
                elif self.path == "/profile/stop":
                    if not profile_dir:
                        self._text(403, "profiling disabled (no profile_dir configured)")
                        return
                    try:
                        out = profiler.stop()
                    except ProfilerError as e:
                        self._text(409, str(e))
                        return
                    self._text(200, out)
                else:
                    self._text(404, "not found")
            except Exception as e:  # defensive: a handler crash must not kill the server
                log.exception("handler error")
                try:
                    self._text(500, f"internal error: {e}")
                except Exception:
                    pass

    return ThreadingHTTPServer(("0.0.0.0", port), Handler)
