"""HTTP frontend workers + the engine compute plane: the multi-process
serving tier.

Why this exists: one CPython process tops out near ~3.5k HTTP requests/s
no matter how fast the engine is — request parsing, handler dispatch, and
response writes are pure Python, and they all share one GIL.  The r08
serve-scheduler work made an engine pass cost microseconds, at which
point the 64-client small-request lane was ENTIRELY GIL-bound.  The fix
is the same one every production serving stack uses: scale the
per-request work across processes and keep the engine's work per-FRAME.

    clients ──HTTP/1.1 keep-alive──▶ N frontend processes (SO_REUSEPORT,
                                     one public port, kernel-balanced)
        each frontend coalesces its concurrent requests locally
                    │  one persistent unix-socket connection pair
                    ▼  carrying fused frames (len-prefixed raw int32)
              engine process ──ServeBatcher──▶ native pool / XLA engine

Two levels of batching: a frontend packs every request it has in hand
into one frame; the engine's ServeBatcher fuses frames from all
frontends into shared engine passes.  The engine's per-request Python
cost drops to ~amortized microseconds, and HTTP throughput scales with
frontend count.

The tier is OPT-IN and additive: `make_http_server` alone is unchanged
(tests, single-process deployments).  A frontend accelerates the hot
compute routes (POST /compute_raw with spread, POST /compute) and
transparently PROXIES every other route — lifecycle, /status, /metrics,
checkpoints — to the engine's own HTTP server, so the public port speaks
the full surface.  `?spread=0` (pinned single-instance FIFO) also
proxies: its ordering contract is per-connection, which local coalescing
would not preserve.

This module imports stdlib only — a frontend process must never pay the
jax import (or its memory) just to shovel bytes.
"""

from __future__ import annotations

import ctypes
import hashlib
import json
import logging
import os
import re
import socket
import ssl
import struct
import subprocess
import sys
import threading
import time
from collections import deque
from urllib.parse import unquote
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from misaka_tpu.runtime import capture as capture_mod
from misaka_tpu.runtime import edge as edge_mod
from misaka_tpu.utils import faults
from misaka_tpu.utils import metrics
from misaka_tpu.utils import slo
from misaka_tpu.utils import tracespan
from misaka_tpu.utils import wire
from misaka_tpu.utils.backoff import Backoff
from misaka_tpu.utils.nativelib import NativeLib
from misaka_tpu.utils.httpfast import fast_parse_request

log = logging.getLogger("misaka_tpu.frontends")

M_FE_RESTARTS = metrics.counter(
    "misaka_frontend_restarts_total",
    "Frontend worker processes respawned by the supervisor",
)
M_FE_ALIVE = metrics.gauge(
    "misaka_frontend_workers_alive",
    "Frontend worker processes currently alive (live supervisor)",
)
M_FE_CONFIGURED = metrics.gauge(
    "misaka_frontend_workers_configured",
    "Frontend worker processes the pool is configured for (live supervisor)",
)
M_PLANE_FRAMES = metrics.counter(
    "misaka_plane_frames_total",
    "Compute-plane frames served by this engine replica",
)
M_PLANE_HEDGED = metrics.counter(
    "misaka_plane_hedged_requests_total",
    "Requests served here after being hedged away from a failed sibling "
    "replica (fleet router failover)",
)
M_PLANE_DRAIN_REROUTES = metrics.counter(
    "misaka_plane_drain_reroutes_total",
    "Compute-plane frames answered with the drain reroute status "
    "(the fleet router re-dispatches them to a sibling)",
)
M_PLANE_PIPELINED = metrics.counter(
    "misaka_plane_pipelined_frames_total",
    "Compute frames accepted while an earlier frame from the same plane "
    "connection was still in flight (MISAKA_PLANE_PIPELINE > 1) — zero "
    "under load means the plane is running single-outstanding-frame",
)
M_PLANE_SHM_FRAMES = metrics.counter(
    "misaka_plane_shm_frames_total",
    "Compute-plane frames whose payload rode a shared-memory segment "
    "instead of the socket (MISAKA_PLANE_SHM=1) — zero here with the "
    "flag set means the zero-copy plane silently fell back to sockets",
)
# Pipeline DEPTH (r18): the engagement counter above says pipelining
# happened; these say how deep the overlap actually runs — in-flight
# frames on one plane connection at each dispatch (histogram) and the
# deepest overlap seen in the last ~5s (windowed gauge, the dashboard's
# sparkline).  Observed on pipelined connections only
# (MISAKA_PLANE_PIPELINE > 1).
M_PLANE_PIPE_DEPTH = metrics.histogram(
    "misaka_plane_pipeline_depth",
    "In-flight frames on one compute-plane connection at frame dispatch "
    "(1 = no overlap; MISAKA_PLANE_PIPELINE bounds it)",
)


class _DepthWindow:
    """Max pipeline depth over a rolling ~5s window: two rotating
    buckets so the reported max covers the last 5-10s — a depth spike is
    visible to every scraper inside the window instead of only the one
    that races it."""

    def __init__(self, window_s: float = 5.0):
        self._lock = threading.Lock()
        self._window_s = window_s
        self._t0 = 0.0
        self._cur = 0
        self._prev = 0

    def note(self, depth: int) -> None:
        now = time.monotonic()
        with self._lock:
            if now - self._t0 >= self._window_s:
                self._prev, self._cur = self._cur, 0
                self._t0 = now
            if depth > self._cur:
                self._cur = depth

    def read(self) -> float:
        now = time.monotonic()
        with self._lock:
            if now - self._t0 >= 2 * self._window_s:
                return 0.0
            if now - self._t0 >= self._window_s:
                return float(self._cur)
            return float(max(self._cur, self._prev))


_PIPE_DEPTH_WINDOW = _DepthWindow()
G_PLANE_PIPE_DEPTH = metrics.gauge(
    "misaka_plane_pipeline_depth_max",
    "Deepest per-connection frame overlap observed on the compute plane "
    "in the last ~5s (0 = no pipelined traffic)",
)
G_PLANE_PIPE_DEPTH.set_function(_PIPE_DEPTH_WINDOW.read)

# Compute-plane wire format (SOCK_STREAM — a unix socket path, or
# `host:port` for the multi-host TCP transport (parse_plane_addr), with
# mTLS wrapping TCP when MISAKA_PLANE_TLS_CERT/KEY/CA are set; one frame
# in flight per connection — pipelining comes from running several
# connections):
#   request:  <I n_values> <I n_meta_bytes>
#             <n_values * 4 bytes little-endian int32>
#             <n_meta_bytes of UTF-8 JSON metadata — absent (0) when the
#              frame is untraced AND addressed to the default program>
#   response: <i status> <I length> <payload>
#     status == 200 -> payload is length*4 bytes of int32 outputs
#     otherwise     -> payload is `length` bytes of utf-8 error body,
#                      status is the HTTP code the frontend should answer
#
# When MISAKA_PLANE_SECRET is set, every plane connection opens with a
# 32-byte HMAC handshake (runtime/edge.py plane_handshake) BEFORE the
# first frame; the engine side closes any connection whose handshake is
# absent or wrong.  Unset = open plane, exactly as before.
#
# The metadata is a JSON object {"program": name-or-null, "key":
# api-key-or-null, "traces": [...],
# "edge": [t0_mono, ...]} (a bare JSON list is accepted as
# traces-only, the pre-registry form).  "key" is the API key every
# request in the frame presented (frames pack per (program, key), so one
# frame = one tenant): the ENGINE-side edge chain (runtime/edge.py)
# authenticates it and applies per-tenant quota/admission per frame —
# quota state must be global, which N worker processes are not.  "edge" appears only while the SLO
# engine is armed (utils/slo.py): one frontend-receive monotonic
# timestamp per request, so the engine's
# per-program SLO windows measure latency from the moment the request hit
# the EDGE — frontend queueing ahead of the engine is part of the
# objective, not invisible to it.  CLOCK_MONOTONIC is host-wide and the
# plane is a unix socket, so the timestamps need no translation.
# "program" is the registry address every request in the frame shares —
# the frontend coalescer packs frames PER PROGRAM, so engine-side
# coalescing (one ServeBatcher per program engine) stays per-program by
# construction.  Each traces entry covers one TRACED request in the
# frame: {"id": trace_id, "off": value offset, "len": value count,
# "spans": [[name, start_monotonic_s, dur_s], ...]} — the spans the
# frontend has already completed (http.parse, frontend.coalesce) ride
# along so the engine-side trace tells the whole cross-process story.
# CLOCK_MONOTONIC is host-wide, and the plane is a unix socket, so the
# timestamps need no translation.  Both sides of the plane ship in one
# build; there is no cross-version frame compatibility to keep.
_REQ_HDR = struct.Struct("<II")
_RESP_HDR = struct.Struct("<iI")

# Plane-private response status for a draining replica: not an HTTP code
# on purpose — the FLEET ROUTER absorbs it by re-dispatching the frame's
# requests onto a healthy sibling (zero client-visible errors during a
# rolling restart); it must never leak to a client, and a single-replica
# PlaneClient maps it to 503 if it ever sees one.  The frame metadata
# may additionally carry {"probe": 1} (a zero-value health probe the
# router's prober sends — answered 200/PLANE_DRAINING without touching
# the engine) and {"hedged": k} (k requests in this frame were re-routed
# here after a sibling failed — counted on
# misaka_plane_hedged_requests_total so failovers are visible in the
# aggregated fleet /metrics).
PLANE_DRAINING = 599

# Plane-private ack for a shared-memory arming frame (MISAKA_PLANE_SHM=1,
# the zero-copy plane): deliberately NOT 200, so a client talking to a
# pre-shm engine (which would treat the arming frame as an empty compute
# and answer 200) keeps shipping payload bytes on the socket instead of
# writing into a segment nobody reads.
PLANE_SHM_OK = 298

# One frame's value budget.  Big enough that a frontend's whole in-hand
# backlog ships at once; small enough to bound engine-side buffering.
MAX_FRAME_VALUES = 1 << 20


def plane_shm_enabled() -> bool:
    """MISAKA_PLANE_SHM=1 swaps per-frame unix-socket payload copies for
    one shared-memory segment per plane connection (the frame header and
    metadata stay on the socket — handshake, drain, probe, and hedge
    semantics are transport-independent).  Default off: the shipped
    socket plane."""
    return os.environ.get("MISAKA_PLANE_SHM", "0") == "1"


def _attach_shm(name: str, size: int):
    """Engine-side attach to a frontend-owned segment.  The resource
    tracker is told to forget it immediately: the FRONTEND owns the
    segment's lifetime, and Python 3.10's tracker would otherwise unlink
    it (and warn) when THIS process exits (bpo-39959)."""
    from multiprocessing import resource_tracker, shared_memory

    seg = shared_memory.SharedMemory(name=name)
    try:
        resource_tracker.unregister(seg._name, "shared_memory")
    except Exception:
        pass  # tracker internals shifted: worst case is an exit warning
    if seg.size < 2 * size:
        seg.close()
        raise ValueError(
            f"segment {name} is {seg.size} bytes; arming promised "
            f"{2 * size}"
        )
    return seg

# Program-addressed compute (the registry surface, runtime/registry.py):
# /programs/<name>/<op> — the frontend accelerates the same ops it does on
# the legacy paths, with the program threaded through the plane frames.
_PROGRAM_COMPUTE_RE = re.compile(
    r"^/programs/([^/]+)/(compute|compute_batch|compute_raw)$"
)


def parse_plane_addr(path: str) -> tuple[str, str, int | None]:
    """One plane address -> ("tcp", host, port) | ("unix", path, None).

    The multi-host transport rides the SAME env surface as the socket
    paths: a `host:port` (a ':' and no '/') is a TCP plane, anything
    else a unix socket path.  The MSK1 frame codec, handshake, drain,
    probe, and hedge semantics are byte-identical on both."""
    if ":" in path and "/" not in path:
        host, _, port_s = path.rpartition(":")
        try:
            return "tcp", host or "127.0.0.1", int(port_s)
        except ValueError:
            pass
    return "unix", path, None


def _plane_partitioned(path: str) -> bool:
    """The plane_partition chaos point (utils/faults.py): armed bare it
    black-holes every plane; scoped `plane_partition:<substr>` only the
    planes whose address contains that substring — the multi-host
    partition drill's selector."""
    if faults.fire("plane_partition") is not None:
        return True
    for point in faults.active():
        if (point.startswith("plane_partition:")
                and point[len("plane_partition:"):] in path):
            return faults.fire(point) is not None
    return False


def _classify_tls_reject(e: BaseException) -> str:
    """Map a failed plane-TLS handshake to its typed close reason:
    "plaintext" (a peer speaking raw MSK1/HTTP at a TLS listener),
    "bad_cert" (certificate outside the pinned CA, or none), else
    "handshake"."""
    s = str(e).upper()
    if "CERTIFICATE" in s or "UNKNOWN_CA" in s or "ALERT" in s:
        return "bad_cert"
    if ("WRONG_VERSION" in s or "UNKNOWN_PROTOCOL" in s
            or "HTTP_REQUEST" in s or "RECORD" in s
            or "PACKET_LENGTH" in s):
        return "plaintext"
    return "handshake"


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly n bytes or raise ConnectionError."""
    parts = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("compute plane connection closed")
        parts.append(chunk)
        n -= len(chunk)
    return b"".join(parts)


# --- engine side ------------------------------------------------------------


class _NotRunning(Exception):
    """Internal control flow: the resolved engine is paused (the frame
    answers the compute route's legacy 400 body)."""


class _BadMeta(Exception):
    """A plane frame's metadata blob failed to decode.  Must fail the
    frame: the blob carries the PROGRAM address, and serving an
    undecodable frame on the default tenant would return the wrong
    network's outputs with a 200."""


class ComputePlane:
    """The engine-side unix-socket listener serving fused compute frames.

    One daemon thread per frontend connection: read a frame, run it
    through master.compute_coalesced (ONE scheduler submission for the
    whole frame — the frontend already coalesced its requests), write the
    outputs back.  Ping-pong per connection keeps the code trivial;
    frontends hold several connections for overlap.
    """

    def __init__(self, master, path: str, timeout: float = 30.0,
                 registry=None, replica_label: str | None = None):
        self._master = master
        # which fleet replica this plane serves (scopes the
        # replica_blackhole:<idx> chaos point; None outside a fleet)
        self._replica_label = (
            replica_label if replica_label is not None
            else os.environ.get("MISAKA_FLEET_REPLICA") or None
        )
        # the program registry (runtime/registry.py) when multi-program
        # serving is armed: frames then resolve their engine through a
        # registry lease (activating cold programs, parking through
        # hot-swaps); None keeps the single-engine plane exactly.  This
        # module never imports the registry — an unknown program surfaces
        # as the lease's KeyError (ProgramNotFound), answered as 404.
        self._registry = registry
        self._timeout = timeout
        # shared-secret plane handshake (MISAKA_PLANE_SECRET,
        # runtime/edge.py): when armed, a connection must open with the
        # 32-byte HMAC before its first frame or it is closed
        self._secret = edge_mod.plane_secret()
        self.path = path
        self._family, bind_host, bind_port = parse_plane_addr(path)
        if self._family == "tcp":
            # the multi-host transport: same frame codec, TCP listener.
            # mTLS (MISAKA_PLANE_TLS_CERT/KEY/CA) wraps accepted
            # connections per-connection in _serve_conn; unset runs the
            # plaintext+HMAC single-box posture (bench/dev only).
            self._tls = edge_mod.plane_tls_from_env()
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_REUSEADDR, 1
            )
            self._sock.bind((bind_host, bind_port))
        else:
            self._tls = None  # unix planes never leave the host
            if os.path.exists(path):
                os.unlink(path)
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.bind(path)
        self._sock.listen(64)
        self._closed = False
        # Fleet drain support (runtime/fleet.py): while draining, new
        # compute frames answer PLANE_DRAINING (the router re-dispatches
        # them to a sibling) and `inflight` counts frames still being
        # served — the roll waits for it to reach zero before replacing
        # this replica.
        self._draining = False
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="misaka-plane-accept"
        )
        self._accept_thread.start()

    def close(self) -> None:
        self._closed = True
        # closing (or even shutting down) the listening socket does NOT
        # wake a thread already blocked in accept() on Linux — without a
        # nudge every closed plane leaks its accept thread for the life
        # of the process (enough of them measurably perturbed the
        # timing-sensitive SLO suite).  A self-connect pops accept(),
        # the loop re-checks _closed and exits.
        try:
            fam, host, port = parse_plane_addr(self.path)
            wake = socket.socket(
                socket.AF_INET if fam == "tcp" else socket.AF_UNIX,
                socket.SOCK_STREAM,
            )
            wake.settimeout(0.5)
            try:
                wake.connect((host, port) if fam == "tcp" else self.path)
            finally:
                wake.close()
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        if self._family != "tcp":
            try:
                os.unlink(self.path)
            except OSError:
                pass
        # sever live frontend connections too: a closed plane must look
        # exactly like a dead replica (in-process chaos tests kill a
        # replica this way; a real SIGKILL drops the sockets itself)
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def set_draining(self, on: bool) -> None:
        self._draining = bool(on)

    @property
    def draining(self) -> bool:
        return self._draining

    def inflight(self) -> int:
        """Compute frames currently being served (0 = plane quiescent)."""
        return self._inflight

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # closed
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
                name="misaka-plane-conn",
            ).start()

    def _tls_accept(self, raw: socket.socket) -> socket.socket | None:
        """Wrap one accepted TCP connection in server-side mTLS.  The
        handshake runs HERE, on the per-connection thread (never in the
        accept loop — the wrap_server_tls slow-loris lesson).  A peer
        that fails it — plaintext bytes, a cert outside the pinned CA —
        gets a typed, counted close and never reaches protocol state.
        Returns the wrapped socket, or None when the connection was
        refused."""
        conn: socket.socket = raw
        try:
            conn = self._tls.server_context().wrap_socket(
                raw, server_side=True, do_handshake_on_connect=False
            )
            conn.do_handshake()
        except (ssl.SSLError, ConnectionError, OSError) as e:
            if not self._closed:  # close()'s wake-connect is not a peer
                reason = _classify_tls_reject(e)
                edge_mod.count_plane_tls_reject(reason)
                log.warning(
                    "compute plane: refused %s peer at the mTLS gate: %s",
                    reason, e,
                )
            with self._conns_lock:
                self._conns.discard(raw)
            for s in (conn, raw):
                try:
                    s.close()
                except OSError:
                    pass
            return None
        # the accept loop registered the RAW socket; the wrapped one now
        # owns the fd, and close() must be able to sever it
        with self._conns_lock:
            self._conns.discard(raw)
            self._conns.add(conn)
        return conn

    def _serve_conn(self, conn: socket.socket) -> None:
        if self._tls is not None:
            conn = self._tls_accept(conn)
            if conn is None:
                return
        master = self._master
        registry = self._registry

        def parse_meta(blob: bytes) -> tuple[str | None, str | None, int,
                                             list, list, bool, int, list,
                                             dict | None, int | None,
                                             dict | None]:
            """(program, key, reqs, traces, edge, probe, hedged, shed,
            shm_arm, shm_vals, cap) from the frame's JSON metadata.

            `cap` (only materialized while the capture plane records) is
            {"segs": per-request slices of the fused frame for the
            capture recorder — trace ID, inbound flag, value offset/len —
            and "rej": worker-side locally-terminated rejects shipped for
            central recording}.  Lenient like the trace segments: a
            malformed entry costs the capture record, never the frame.

            `shm_arm` ({name, size}) is a shared-memory arming request
            (MISAKA_PLANE_SHM, see _PlaneShm below); `shm_vals` marks a
            frame whose payload lives in the connection's armed segment
            instead of on the socket.  Both are FATAL when malformed,
            like the program address: guessing would compute on the
            wrong bytes.

            The program address must decode even with tracing killed; an
            UNDECODABLE blob raises _BadMeta and fails the frame (it may
            name a program, and guessing "default" would silently serve
            the wrong tenant's network).  Trace rebuilding (honor each
            frontend-minted ID, replay the forwarded frontend spans, hand
            the traces to the serve scheduler so serve.queue / serve.pass
            land on them) only runs when tracing is enabled —
            MISAKA_TRACE_REQUESTS=0 skips it — and stays lenient:
            malformed trace SEGMENTS are dropped, never fatal.  `edge`
            entries (one receive timestamp per request) feed the SLO
            windows —
            also lenient: a malformed edge list costs the observation,
            never the frame.  "key" (the frame's API key — one per frame,
            frames pack per tenant) and "reqs" (how many client requests
            the frame fused) feed the engine-side edge chain; a
            malformed key is FATAL like a malformed program — guessing
            "no key" would turn an authentication failure into the
            anonymous tenant's quota."""
            if not blob:
                return None, None, 1, [], [], False, 0, [], None, None, None
            import json as _json

            probe = False
            hedged = 0
            key = None
            reqs = 1
            shed: list = []
            shm_arm = None
            shm_vals = None
            try:
                obj = _json.loads(blob.decode())
                if isinstance(obj, dict):
                    program = obj.get("program") or None
                    key = obj.get("key") or None
                    segs = obj.get("traces", ())
                    edge_raw = obj.get("edge", ())
                    probe = bool(obj.get("probe"))
                    hedged = int(obj.get("hedged") or 0)
                    reqs = max(1, int(obj.get("reqs") or 1))
                    shed = obj.get("shed") or []
                    if obj.get("shm") is not None:
                        shm_arm = obj["shm"]
                        if not (isinstance(shm_arm, dict)
                                and isinstance(shm_arm.get("name"), str)
                                and isinstance(shm_arm.get("size"), int)
                                and shm_arm["size"] > 0):
                            raise ValueError("shm arming must carry "
                                             "{name: str, size: int > 0}")
                    if obj.get("shm_vals") is not None:
                        shm_vals = int(obj["shm_vals"])
                        if shm_vals < 0:
                            raise ValueError("shm_vals must be >= 0")
                elif isinstance(obj, list):
                    # the pre-registry traces-only list form
                    program, segs, edge_raw = None, obj, ()
                else:
                    raise ValueError("metadata must be an object or list")
                if program is not None and not isinstance(program, str):
                    raise ValueError("program must be a string")
                if key is not None and not isinstance(key, str):
                    raise ValueError("key must be a string")
            except (ValueError, TypeError, UnicodeDecodeError, KeyError) as e:
                raise _BadMeta(str(e)) from e
            traces = []
            if tracespan.enabled():
                try:
                    for seg in segs:
                        tr = tracespan.begin(
                            seg.get("id"), route="/compute_raw",
                            activate=False,
                        )
                        if tr is None:
                            continue
                        for name, start, dur in seg.get("spans", ()):
                            tracespan.add_span(
                                tr, str(name), float(start), float(dur)
                            )
                        traces.append(tr)
                except (ValueError, TypeError, KeyError, AttributeError):
                    log.debug("dropping malformed plane trace metadata")
            edge = []
            if slo.armed():
                try:
                    edge = [float(t0) for t0 in edge_raw]
                except (ValueError, TypeError):
                    log.debug("dropping malformed plane edge metadata")
            cap = None
            if capture_mod.RECORDING:
                try:
                    cap = {
                        "segs": [
                            {
                                "id": tracespan.sanitize_id(s.get("id")),
                                "in": bool(s.get("in")),
                                "off": int(s.get("off", 0)),
                                "len": int(s.get("len", 0)),
                            }
                            for s in segs if isinstance(s, dict)
                        ],
                        "rej": (
                            obj.get("caprej") or []
                            if isinstance(obj, dict) else []
                        ),
                    }
                except (ValueError, TypeError, AttributeError):
                    log.debug("dropping malformed plane capture metadata")
            return (program, key, reqs, traces, edge, probe, hedged, shed,
                    shm_arm, shm_vals, cap)

        def slo_record(program, edge, t_recv, error: bool) -> None:
            """Feed the frame's outcome into the per-program SLO windows:
            per request when the frontend shipped edge timestamps (the
            clock starts at the EDGE, so frontend queueing counts), one
            frame-level observation otherwise.  4xx outcomes never reach
            here — they are the client's, not the service's."""
            if not slo.armed():
                return
            # getattr: duck-typed registries (tests) may not carry a
            # default name — label None = the default program's windows.
            # Load-bearing under pipelining: an exception here would kill
            # the whole connection's in-flight frames, not just one.
            label = (
                program.partition("@")[0] if program
                else getattr(registry, "default_name", None)
            )
            now = time.monotonic()
            if edge:
                for t0 in edge:
                    slo.observe(label, max(0.0, now - t0), error=error)
            else:
                slo.observe(label, now - t_recv, error=error)

        # Per-request pipelining (r17): up to MISAKA_PLANE_PIPELINE frames
        # from ONE connection may be in flight through the serve scheduler
        # at once — the reader keeps reading while earlier frames compute,
        # so a connection stops being single-outstanding-frame
        # queueing-bound (the 64-client p50's measured wall, BENCH_HISTORY
        # r16).  Responses ship in FRAME ORDER via a done-event chain (the
        # wire carries no frame ids — FIFO pairing is the protocol), and
        # anything order- or state-sensitive (probes, shm arming, frames
        # whose payload rides the shm double buffer, error replies from
        # the reader) first drains the pipeline by waiting on the chain
        # tail.  MISAKA_PLANE_PIPELINE=1 restores the r16 ping-pong.
        pipe_depth = max(
            1, int(os.environ.get("MISAKA_PLANE_PIPELINE", "") or 4)
        )
        send_lock = threading.Lock()
        conn_dead = [False]
        conn_depth = [0]  # in-flight pipelined frames on THIS connection
        pipe_sem = threading.Semaphore(pipe_depth)
        executor = [None]  # lazy ThreadPoolExecutor, pipelined frames only
        tail = [None]      # done event of the most recently accepted frame

        def send_ordered(prev, data: bytes) -> None:
            if prev is not None:
                prev.wait()
            if conn_dead[0]:
                raise ConnectionError("plane connection is closed")
            with send_lock:
                conn.sendall(data)

        def drain_pipeline() -> None:
            t = tail[0]
            if t is not None:
                t.wait()

        def process_frame(n, parsed, get_values, reply) -> None:
            """Everything past metadata parsing for one compute frame:
            drain check, chaos, edge chain, lease resolution, the
            scheduler submission, and the ordered response via `reply`.
            Runs inline (reader thread) or on the pipeline executor; the
            in-flight count was taken by the caller and is released
            here."""
            (program, key, reqs, traces, edge, probe, hedged, shed,
             _shm_arm, shm_vals, cap) = parsed
            try:
                if self._draining:
                    # rolling restart: hand this frame back to the
                    # router, which re-dispatches it onto a healthy
                    # sibling — the client never sees an error
                    M_PLANE_DRAIN_REROUTES.inc()
                    body = b"replica draining; reroute"
                    reply(_RESP_HDR.pack(PLANE_DRAINING, len(body)) + body)
                    for tr in traces:
                        tracespan.end(tr, status=PLANE_DRAINING)
                    return
                bh = faults.fire("replica_blackhole")
                if bh is None and self._replica_label is not None:
                    bh = faults.fire(
                        f"replica_blackhole:{self._replica_label}"
                    )
                if bh is not None:
                    # chaos (utils/faults.py): hold the frame unanswered —
                    # the router's frame deadline must fire and hedge the
                    # requests onto a sibling
                    log.warning(
                        "replica_blackhole fault: holding frame %.1fs", bh
                    )
                    time.sleep(max(0.0, bh))
                M_PLANE_FRAMES.inc()
                if shm_vals is not None:
                    M_PLANE_SHM_FRAMES.inc()
                if hedged:
                    M_PLANE_HEDGED.inc(hedged)
                if shed:
                    # worker-local shed-cache hits since the last frame:
                    # book them here so the headline rejected counter
                    # covers the whole door, not just the decisions this
                    # process made (lenient: malformed rows cost the
                    # count, never the frame)
                    try:
                        for t, r, cnt in shed:
                            edge_mod.count_shed(
                                t if isinstance(t, str) else None,
                                str(r), int(cnt),
                            )
                    except (ValueError, TypeError):
                        log.debug("dropping malformed shed metadata")
                if cap is not None and cap.get("rej"):
                    # worker-side locally-terminated rejects (shed cache):
                    # recorded centrally so the capture covers the whole
                    # door, partitioned exactly-once by terminating surface
                    capture_mod.ingest("worker", cap["rej"])
                # The edge chain, per frame (runtime/edge.py): the
                # frontend workers terminate TLS and ship the API key
                # along; auth + per-tenant quota + admission run HERE,
                # where the state is global — one frame is one
                # (program, tenant), so a frame-level decision is a
                # tenant-level decision.  Rejections ship the typed
                # status with a JSON body the worker unpacks back into
                # Retry-After.
                chain = edge_mod.current()
                if chain.armed:
                    decision = chain.check(
                        "/compute_raw", "POST", key=key,
                        program=program or getattr(
                            registry, "default_name", None
                        ),
                        values=int(n), requests=reqs,
                    )
                    if decision.reject is not None:
                        rej = decision.reject
                        # the worker's shed cache reports under this
                        # tenant when it honors the Retry-After
                        rej.tenant = decision.tenant
                        body = rej.to_wire()
                        reply(_RESP_HDR.pack(rej.status, len(body)) + body)
                        if capture_mod.RECORDING:
                            # engine-side termination: this surface owns
                            # the record (the worker only relayed)
                            capture_mod.ingest("plane", [{
                                "program": program,
                                "trace": None,
                                "in": 0,
                                "status": rej.status,
                                "reason": rej.reason,
                            }])
                        for tr in traces:
                            tracespan.end(tr, status=rej.status)
                        return
                t_recv = time.monotonic()
                values = get_values()
                # Lease resolution FIRST, in its own try: only this step
                # may answer 404 (ProgramNotFound is a KeyError subclass —
                # this module stays registry-import-free).  A KeyError
                # escaping the compute itself must stay a 500:
                # classifying an engine bug as "program not found" would
                # hide it from 5xx alerting.
                lease_ctx = None
                try:
                    if registry is not None:
                        # the registry lease: resolves the program (the
                        # seeded default for None), activates cold
                        # engines, parks through hot-swaps, and counts
                        # the per-program metric series
                        lease_ctx = registry.lease(
                            program, values=int(values.size)
                        )
                        m = lease_ctx.__enter__()
                    elif program:
                        raise KeyError(
                            f"program registry disabled; cannot "
                            f"route to program {program!r}"
                        )
                    else:
                        m = master
                except KeyError as e:
                    # args[0] dodges KeyError's repr-quoting of its
                    # message
                    msg = e.args[0] if e.args and isinstance(
                        e.args[0], str
                    ) else str(e)
                    body = msg.encode()
                    reply(_RESP_HDR.pack(404, len(body)) + body)
                    for tr in traces:
                        tracespan.end(tr, status=404)
                    return
                except Exception as e:
                    # activation failure (RegistryError, compile error...)
                    body = str(e).encode()
                    reply(_RESP_HDR.pack(500, len(body)) + body)
                    slo_record(program, edge, t_recv, error=True)
                    for tr in traces:
                        tracespan.end(tr, status=500)
                    return
                try:
                    if not m.is_running:
                        raise _NotRunning()
                    out = m.compute_coalesced(
                        values, timeout=self._timeout,
                        return_array=True, traces=tuple(traces),
                    )
                except _NotRunning:
                    # the route's 400 body
                    body = b"network is not running"
                    reply(_RESP_HDR.pack(400, len(body)) + body)
                    for tr in traces:
                        tracespan.end(tr, status=400)
                    return
                except Exception as e:
                    body = str(e).encode()
                    reply(_RESP_HDR.pack(500, len(body)) + body)
                    slo_record(program, edge, t_recv, error=True)
                    for tr in traces:
                        tracespan.add_span(
                            tr, "plane.recv", t_recv,
                            time.monotonic() - t_recv,
                        )
                        tracespan.end(tr, status=500)
                    return
                finally:
                    if lease_ctx is not None:
                        lease_ctx.__exit__(None, None, None)
                payload = out.astype("<i4").tobytes()
                if shm_vals is not None:
                    # response payload rides the segment's second half;
                    # the socket carries only the 8-byte header (shm
                    # frames run INLINE with the pipeline drained, so the
                    # double buffer is never shared)
                    shm_state[0].buf[
                        shm_state[1]:shm_state[1] + len(payload)
                    ] = payload
                    reply(_RESP_HDR.pack(200, len(payload) // 4))
                else:
                    reply(
                        _RESP_HDR.pack(200, len(payload) // 4) + payload
                    )
                if capture_mod.RECORDING:
                    # one record per fused frame (surface "plane"): the
                    # raw int32 comparands for byte-for-byte replay, plus
                    # the per-request slices so a diff names the request
                    cap_segs = (cap or {}).get("segs") or None
                    first = cap_segs[0] if cap_segs else None
                    capture_mod.note(
                        "plane",
                        program=program or getattr(
                            registry, "default_name", None
                        ),
                        trace=first["id"] if first else None,
                        inbound=any(s["in"] for s in cap_segs)
                        if cap_segs else False,
                        vals=values.tobytes(),
                        resp=payload,
                        status=200,
                        tick=int(getattr(m, "_ticks_done", 0)),
                        reqs=reqs,
                        op="coalesced",
                        segs=cap_segs,
                    )
                slo_record(program, edge, t_recv, error=False)
                dur = time.monotonic() - t_recv
                for tr in traces:
                    tracespan.add_span(
                        tr, "plane.recv", t_recv, dur,
                        {"frame_values": int(n)},
                    )
                    tracespan.end(tr, status=200)
            finally:
                with self._inflight_lock:
                    self._inflight -= 1

        def run_pipelined(n, parsed, raw, prev, done) -> None:
            import numpy as np

            try:
                process_frame(
                    n, parsed, lambda: np.frombuffer(raw, dtype="<i4"),
                    lambda data: send_ordered(prev, data),
                )
            except (ConnectionError, OSError) as e:
                conn_dead[0] = True
                log.debug("pipelined plane frame send failed: %r", e)
            except Exception:  # pragma: no cover — must not die silently
                conn_dead[0] = True
                log.exception("pipelined compute-plane frame crashed")
            finally:
                with self._inflight_lock:
                    conn_depth[0] -= 1
                done.set()
                pipe_sem.release()

        # shared-memory plane state for THIS connection (MISAKA_PLANE_SHM):
        # the frontend owns + unlinks the segment; we attach on the arming
        # frame and only ever map it (bound before the try: the finally
        # must see it even when the handshake bails).  shm_state is
        # [segment, size], readable from process_frame.
        shm_state = [None, 0]
        try:
            if self._secret is not None:
                # shared-secret handshake BEFORE any frame: a peer that
                # cannot present the HMAC never gets protocol access.
                # On the multi-host TCP transport this runs INSIDE the
                # mTLS session (_tls_accept above) as the inner
                # authenticator — cert = fleet membership, HMAC = plane
                # access, rotated independently
                presented = _recv_exact(
                    conn, edge_mod.PLANE_HANDSHAKE_LEN
                )
                if not edge_mod.verify_plane_handshake(
                    self._secret, presented
                ):
                    log.warning(
                        "compute plane: bad handshake; closing connection"
                    )
                    return
            while not self._closed:
                n, n_meta = _REQ_HDR.unpack(_recv_exact(conn, 8))
                if n > MAX_FRAME_VALUES:
                    drain_pipeline()
                    body = b"frame exceeds MAX_FRAME_VALUES"
                    conn.sendall(_RESP_HDR.pack(413, len(body)) + body)
                    return  # protocol state is unrecoverable past this
                raw = _recv_exact(conn, n * 4)
                meta = _recv_exact(conn, n_meta) if n_meta else b""
                try:
                    parsed = parse_meta(meta)
                except _BadMeta as e:
                    drain_pipeline()  # error replies respect frame order
                    body = f"malformed plane metadata: {e}".encode()
                    conn.sendall(_RESP_HDR.pack(400, len(body)) + body)
                    continue
                (_program, _key, _reqs, _traces, _edge, probe,
                 _hedged, _shed, shm_arm, shm_vals, _cap) = parsed
                if shm_arm is not None:
                    # zero-copy plane arming: map the client's segment.
                    # PLANE_SHM_OK is deliberately NOT 200 — a pre-shm
                    # engine would answer this frame 200 (an empty
                    # compute), and the client must be able to tell the
                    # difference before it stops shipping payload bytes.
                    drain_pipeline()  # nobody may still read the old seg
                    old, shm_state[0], shm_state[1] = shm_state[0], None, 0
                    if old is not None:
                        old.close()
                    try:
                        shm_state[0] = _attach_shm(shm_arm["name"],
                                                   shm_arm["size"])
                        shm_state[1] = int(shm_arm["size"])
                        conn.sendall(_RESP_HDR.pack(PLANE_SHM_OK, 0))
                    except Exception as e:
                        body = f"shm attach failed: {e}".encode()
                        conn.sendall(
                            _RESP_HDR.pack(400, len(body)) + body
                        )
                    continue
                if shm_vals is not None:
                    # payload lives in [0, size) of the armed segment
                    if shm_state[0] is None or shm_vals * 4 > shm_state[1] \
                            or shm_vals > MAX_FRAME_VALUES:
                        drain_pipeline()
                        body = b"shm frame without a valid armed segment"
                        conn.sendall(
                            _RESP_HDR.pack(400, len(body)) + body
                        )
                        return  # transport misuse: unrecoverable
                    n = shm_vals  # the edge chain + metrics see real counts
                if probe:
                    # router health probe: liveness + drain state only,
                    # zero engine work (ordered behind in-flight frames)
                    drain_pipeline()
                    status = PLANE_DRAINING if self._draining else 200
                    conn.sendall(_RESP_HDR.pack(status, 0))
                    continue
                # In-flight accounting STARTS before the drain check: a
                # roll polls `inflight` after arming the drain, and a
                # frame that passed the check un-counted could be missed
                # by the quiescence wait.  Counted-then-drained frames
                # just reroute (process_frame decrements on every path).
                if pipe_depth > 1 and shm_vals is None:
                    # pipelined dispatch: bounded by pipe_sem, responses
                    # ordered by the done-event chain
                    pipe_sem.acquire()
                    if conn_dead[0]:
                        pipe_sem.release()
                        return
                    done = threading.Event()
                    prev, tail[0] = tail[0], done
                    if prev is not None and not prev.is_set():
                        M_PLANE_PIPELINED.inc()
                    if executor[0] is None:
                        from concurrent.futures import ThreadPoolExecutor

                        executor[0] = ThreadPoolExecutor(
                            max_workers=pipe_depth,
                            thread_name_prefix="misaka-plane-pipe",
                        )
                    with self._inflight_lock:
                        self._inflight += 1
                        conn_depth[0] += 1
                        depth = conn_depth[0]
                    M_PLANE_PIPE_DEPTH.observe(depth)
                    _PIPE_DEPTH_WINDOW.note(depth)
                    executor[0].submit(
                        run_pipelined, n, parsed, raw, prev, done
                    )
                    continue
                # inline dispatch: shm frames always land here (the
                # double buffer requires the one-frame-in-flight
                # discipline) as does MISAKA_PLANE_PIPELINE=1
                drain_pipeline()
                import numpy as np

                if shm_vals is not None:
                    # zero-copy read straight off the mapped segment: the
                    # client writes the next frame's payload only after
                    # this frame's response, and the serve scheduler
                    # consumes values into its feed buffers before
                    # completing the entries, so the view is never read
                    # after we answer (released when process_frame
                    # returns, before the next blocking read)
                    def get_values(_seg=shm_state[0], _count=shm_vals):
                        return np.frombuffer(
                            _seg.buf, dtype="<i4", count=_count
                        )
                else:
                    def get_values(_raw=raw):
                        return np.frombuffer(_raw, dtype="<i4")
                with self._inflight_lock:
                    self._inflight += 1
                process_frame(
                    n, parsed, get_values,
                    lambda data: send_ordered(None, data),
                )
        except (ConnectionError, OSError) as e:
            # frontend went away; its requests fail on their side
            log.debug("compute-plane connection closed: %r", e)
        except Exception:  # pragma: no cover — must not die silently
            log.exception("compute-plane connection handler crashed")
        finally:
            conn_dead[0] = True
            if executor[0] is not None:
                executor[0].shutdown(wait=False)
            if shm_state[0] is not None:
                try:
                    shm_state[0].close()  # unmap; the frontend owns unlink
                except (OSError, BufferError):
                    # a surviving numpy view (e.g. a timed-out entry still
                    # holding its slice) pins the mapping — it is unmapped
                    # when the last view is collected instead
                    pass
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass


def start_compute_plane(master, path: str, timeout: float = 30.0,
                        registry=None,
                        replica_label: str | None = None) -> ComputePlane:
    return ComputePlane(master, path, timeout=timeout, registry=registry,
                        replica_label=replica_label)


# --- frontend side ----------------------------------------------------------


class PlaneError(RuntimeError):
    """Engine answered a frame with an error (carries the HTTP status)."""

    def __init__(self, status: int, body: bytes):
        super().__init__(body.decode(errors="replace"))
        self.status = status
        self.body = body


class _PlaneRequest:
    __slots__ = ("body", "out", "error", "event", "cancelled", "trace",
                 "enqueued", "program", "key", "hedged", "replayed")

    def __init__(self, body: bytes, trace=None, program=None, key=None,
                 hedged: bool = False):
        self.body = body          # raw little-endian int32 values
        self.out: bytes | None = None
        self.error: PlaneError | None = None
        self.event = threading.Event()
        self.cancelled = False    # waiter gave up; never ship it
        self.trace = trace        # request trace (utils/tracespan.py) | None
        self.enqueued = time.monotonic()  # frontend.coalesce span start
        self.program = program    # registry address (None = default program)
        self.key = key            # API key (frames pack per (program, key))
        self.hedged = hedged      # re-routed here after a sibling failed
        self.replayed = False     # one stale-socket requeue per request


class _Shipment:
    """One in-flight frame on a pipelined plane connection: everything the
    receiver needs to complete (or the failure path to replay) it."""

    __slots__ = ("batch", "traced", "t_ship", "use_shm", "shed",
                 "replay_ok")

    def __init__(self, batch, traced, t_ship, use_shm, shed, replay_ok):
        self.batch = batch
        self.traced = traced
        self.t_ship = t_ship
        self.use_shm = use_shm
        self.shed = shed          # worker-local shed counts riding this frame
        self.replay_ok = replay_ok  # NOT the first frame on a fresh dial


class PlaneClient:
    """Frontend-local coalescer over persistent compute-plane connections.

    Handler threads enqueue raw int32 bodies; one dispatcher thread per
    connection packs EVERYTHING waiting into a single frame (FIFO, byte
    offsets recorded), ships it, and scatters the response back by
    offset.  The mirror of the engine's ServeBatcher, one level out.
    """

    def __init__(self, path: str, conns: int = 2, timeout: float = 60.0,
                 replica: int | None = None):
        self._path = path
        self._timeout = timeout
        # cached once, like ComputePlane: MISAKA_PLANE_SECRET_FILE must
        # not be re-read from disk on every reconnect
        self._secret = edge_mod.plane_secret()
        self._family = parse_plane_addr(path)[0]
        # client-side mTLS only for TCP planes (unix never leaves the
        # host); the reloader re-stats the cert files so a rotation
        # reaches new dials without restarting the worker
        self._tls = (
            edge_mod.plane_tls_from_env() if self._family == "tcp"
            else None
        )
        # Dial-storm guard: dispatcher threads hitting a DEAD TCP peer
        # must not re-dial it in a tight loop (SYN floods + log spam at
        # the far host's conntrack; the unix path fails in microseconds,
        # a remote dial burns a full RTO).  Failed dials push the next
        # allowed dial out on the shared backoff curve; the router's
        # prober owns rediscovery.  Benign races: both fields are
        # GIL-atomic floats, and an extra dial costs one RTO.
        self._dial_backoff = Backoff(base=0.05, cap=2.0)
        self._next_dial = 0.0
        # captured HERE, not in the dispatcher thread: the decision must
        # be fixed at construction (tests toggle the env around it)
        self._shm_enabled = plane_shm_enabled()
        self.replica = replica  # fleet slot index (None = single engine)
        self._cond = threading.Condition()
        self._pending: deque[_PlaneRequest] = deque()
        self._closed = False
        self._inflight = 0
        # worker-local shed counts awaiting delivery: the shed cache
        # rejects WITHOUT a plane round trip, so its counts ride the
        # NEXT frame's metadata to the engine's misaka_edge_rejected
        # series (eventual: a fully-shed quiet worker delivers when the
        # hold expires and a request goes through)
        self._shed: dict[tuple[str, str], int] = {}
        # worker-local capture rows (locally-terminated rejects) awaiting
        # delivery to the engine's capture ring, same eventual-delivery
        # contract as the shed counts; bounded — capture is observability,
        # so overflow drops rows rather than growing the worker
        self._caprej: list[dict] = []
        # Adaptive coalesce window, the engine scheduler's policy applied
        # one level out: a frame dispatches immediately when no frame is
        # in flight; while one IS, waiting a few hundred microseconds
        # gathers more concurrent requests into the next frame — fewer,
        # bigger frames is exactly what keeps the engine's per-frame GIL
        # cost amortized.
        self._window_s = float(
            os.environ.get("MISAKA_PLANE_WINDOW_US", "") or 300
        ) / 1e6
        for i in range(max(1, conns)):
            threading.Thread(
                target=self._dispatch_loop, daemon=True,
                name=f"misaka-plane-client-{i}",
            ).start()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def depth(self) -> int:
        """Queued + in-flight frames on this client — the router's
        least-queue-depth signal."""
        with self._cond:
            return len(self._pending) + self._inflight

    def report_shed(self, tenant: str | None, reason: str) -> None:
        """Record one worker-local edge rejection for delivery to the
        engine's metrics on the next frame."""
        k = (tenant or "other", reason)
        with self._cond:
            self._shed[k] = self._shed.get(k, 0) + 1

    def report_capture(self, row: dict) -> None:
        """Queue one worker-terminated capture row ({t, program, trace,
        in, status, reason}) for the engine's capture ring on the next
        frame.  Bounded: past 32 waiting rows, new ones drop — capture
        rows must never grow a quiet worker."""
        with self._cond:
            if len(self._caprej) < 32:
                self._caprej.append(row)

    def compute_raw(self, body: bytes, timeout: float = 30.0,
                    program: str | None = None, key: str | None = None,
                    hedged: bool = False) -> bytes:
        """One request's raw int32 body in, raw int32 outputs out.
        `program` addresses a registry program (None = the seeded
        default); `key` is the request's API key — frames coalesce
        strictly per (program, key), so the engine-side edge chain can
        make tenant-level quota/admission decisions per frame.  `hedged`
        marks a request re-routed here after a sibling replica failed
        (rides the frame metadata into the replica's hedge counter)."""
        req = _PlaneRequest(body, trace=tracespan.current(), program=program,
                            key=key, hedged=hedged)
        with self._cond:
            self._pending.append(req)
            self._cond.notify()
        if not req.event.wait(timeout):
            with self._cond:
                # never ship a request whose caller already got a 500:
                # under overload the timed-out backlog would otherwise
                # keep burning engine capacity for nobody
                req.cancelled = True
            raise PlaneError(500, b"compute plane timed out")
        if req.error is not None:
            raise req.error
        return req.out

    def _connect(self) -> socket.socket:
        if faults.armed() and _plane_partitioned(self._path):
            raise OSError("plane partitioned (injected fault)")
        if time.monotonic() < self._next_dial:
            # inside the dial-backoff hold: fail fast instead of burning
            # a connect timeout against a peer we just found dead
            raise OSError("plane dial backoff (peer recently unreachable)")
        try:
            fam, host, port = parse_plane_addr(self._path)
            if fam == "tcp":
                sock = socket.create_connection(
                    (host, port), timeout=self._timeout
                )
                sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
                if self._tls is not None:
                    sock = self._tls.client_context().wrap_socket(
                        sock, server_hostname=host
                    )
            else:
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                sock.settimeout(self._timeout)
                sock.connect(self._path)
        except OSError:
            # ssl.SSLError is an OSError: a peer refusing our cert backs
            # off the same way a dead one does
            self._next_dial = (
                time.monotonic() + self._dial_backoff.next_delay()
            )
            raise
        self._dial_backoff.reset()
        self._next_dial = 0.0
        if self._secret is not None:
            # shared-secret handshake (MISAKA_PLANE_SECRET): the engine
            # side reads these 32 bytes before its first frame — over
            # TCP it runs INSIDE the TLS session (inner authenticator)
            sock.sendall(edge_mod.plane_handshake(self._secret))
        return sock

    def _arm_shm(self, sock: socket.socket, seg, seg_size: int) -> bool:
        """Offer this dispatcher's shared-memory segment to the engine
        over a fresh connection.  True only on the PLANE_SHM_OK ack — a
        pre-shm engine answers the frame as an empty compute (200), and
        we keep shipping payload on the socket."""
        import json as _json

        meta = _json.dumps(
            {"shm": {"name": seg.name, "size": seg_size}}
        ).encode()
        sock.sendall(_REQ_HDR.pack(0, len(meta)) + meta)
        status, length = _RESP_HDR.unpack(_recv_exact(sock, 8))
        if length:
            # drain whatever rode along (error text, or a legacy empty
            # compute's payload) so the connection stays frame-aligned
            _recv_exact(sock, length * 4 if status == 200 else length)
        return status == PLANE_SHM_OK

    def _dispatch_loop(self) -> None:
        # Zero-copy plane (MISAKA_PLANE_SHM=1): one shared-memory segment
        # per CONNECTION, offered to the engine on every fresh socket.
        # Layout: [0, seg_size) carries request payloads,
        # [seg_size, 2*seg_size) responses; the strict one-frame-in-flight
        # discipline of this loop makes the double buffer race-free for
        # the connection's lifetime — and a RECONNECT allocates a FRESH
        # segment (never reuses the old one): a stale engine handler from
        # a timed-out previous connection may still be mapped, and its
        # late read/write would corrupt the new connection's frames.
        # Creation failure (no /dev/shm) costs that connection the shm
        # path, nothing else.
        seg_box: list = [None]
        try:
            self._dispatch_loop_inner(seg_box)
        finally:
            self._drop_seg(seg_box)

    @staticmethod
    def _drop_seg(seg_box: list) -> None:
        seg, seg_box[0] = seg_box[0], None
        if seg is not None:
            try:
                seg.close()
                seg.unlink()
            except (OSError, BufferError):
                pass

    def _fresh_seg(self, seg_box: list, seg_size: int):
        """Replace the dispatcher's segment for a new connection,
        unlinking the old one — a stale mapping keeps ITS copy alive
        until its holder dies, touching nothing of ours."""
        self._drop_seg(seg_box)
        try:
            from multiprocessing import shared_memory

            seg_box[0] = shared_memory.SharedMemory(
                create=True, size=2 * seg_size
            )
        except Exception as e:
            log.warning("plane shm unavailable (%s); socket payloads", e)
            seg_box[0] = None
        return seg_box[0]

    def _dispatch_loop_inner(self, seg_box: list) -> None:
        # Pipelined dispatcher (r17): frames ship as soon as they are
        # built, up to MISAKA_PLANE_PIPELINE outstanding on this
        # connection (1 while the shm plane is armed — the double buffer
        # requires the one-frame discipline); a per-socket receiver
        # thread completes shipments in FIFO order (the wire carries no
        # frame ids — order IS the pairing).  Failure discipline mirrors
        # the r13 one-shot stale-socket replay, generalized: when a
        # socket dies before ANY response arrived on it, outstanding
        # requests are requeued (once each — _PlaneRequest.replayed) at
        # the FRONT of the pending deque and rebuilt on a fresh dial; the
        # first frame on a socket dialed FOR it never replays (a fresh
        # dial that fails is a real error, not a stale socket), and a
        # TIMEOUT never replays (the replica is slow or silent, not
        # stale).  Lock order: `cond` (connection state) before
        # self._cond (queue state), never the reverse.
        depth = max(1, int(os.environ.get("MISAKA_PLANE_PIPELINE", "") or 4))
        seg_size = MAX_FRAME_VALUES * 4 if self._shm_enabled else 0
        cond = threading.Condition()
        gen: dict = {
            "id": 0, "sock": None, "armed": False, "seg": None,
            "outstanding": deque(), "responded": 0, "dead": True,
            "inherited": False,  # a frame has shipped on this socket
        }

        def fail_requests(reqs, text: bytes, status: int = 502) -> None:
            err = PlaneError(status, text)
            for r in reqs:
                r.error = err
                r.event.set()

        def remerge_shed(shed) -> None:
            # the frame carrying these shed counts never arrived: put
            # them back for the next frame — losing them silently
            # under-reports the rejected counter during exactly the
            # floods it exists to measure
            if not shed:
                return
            with self._cond:
                for sk, cnt in shed.items():
                    self._shed[sk] = self._shed.get(sk, 0) + cnt

        def conn_failed(gen_id: int, exc: BaseException) -> None:
            """Tear down one socket generation (from the receiver or the
            dispatcher's send path): fail or requeue its outstanding
            shipments under the replay discipline above."""
            with cond:
                if gen["id"] != gen_id or gen["dead"]:
                    return
                gen["dead"] = True
                outstanding = list(gen["outstanding"])
                gen["outstanding"].clear()
                responded = gen["responded"]
                sock = gen["sock"]
                gen["sock"] = None
                cond.notify_all()
                if outstanding:
                    with self._cond:
                        self._inflight -= len(outstanding)
            try:
                if sock is not None:
                    sock.close()
            except OSError:
                pass
            replay = responded == 0 and not isinstance(exc, TimeoutError)
            requeue: list = []
            failed: list = []
            for shp in outstanding:
                ok = replay and shp.replay_ok
                for r in shp.batch:
                    if ok and not r.replayed and not r.cancelled:
                        r.replayed = True
                        requeue.append(r)
                    else:
                        failed.append(r)
                remerge_shed(shp.shed)
            fail_requests(failed, f"compute plane error: {exc}".encode())
            if requeue:
                with self._cond:
                    for r in reversed(requeue):
                        self._pending.appendleft(r)
                    self._cond.notify_all()

        def receiver(sock: socket.socket, gen_id: int, seg) -> None:
            try:
                while True:
                    # An IDLE connection parks here indefinitely: the
                    # socket's own timeout fires with nothing outstanding
                    # (the engine owes us nothing) and must not tear down
                    # a healthy generation.  With frames outstanding, a
                    # shipment gets its own full timeout budget from its
                    # ship time — only a genuinely silent replica fails.
                    while True:
                        try:
                            hdr = _recv_exact(sock, 8)
                            break
                        except TimeoutError:
                            with cond:
                                if gen["id"] != gen_id:
                                    return
                                oldest = (
                                    gen["outstanding"][0].t_ship
                                    if gen["outstanding"] else None
                                )
                            if (oldest is not None
                                    and time.monotonic() - oldest
                                    >= self._timeout):
                                raise  # silent replica mid-frame
                            continue
                    status, length = _RESP_HDR.unpack(hdr)
                    with cond:
                        if gen["id"] != gen_id:
                            return  # superseded generation
                        if not gen["outstanding"]:
                            raise struct.error(
                                "response without an outstanding frame"
                            )
                        shp = gen["outstanding"][0]
                    if status == 200:
                        payload = (
                            bytes(seg.buf[seg_size:seg_size + length * 4])
                            if shp.use_shm
                            else _recv_exact(sock, length * 4)
                        )
                        off = 0
                        for r in shp.batch:
                            r.out = payload[off:off + len(r.body)]
                            off += len(r.body)
                    else:
                        err = PlaneError(status, _recv_exact(sock, length))
                        if status == PLANE_DRAINING and self.replica is None:
                            # plane-private status: a single-engine client
                            # has no sibling to reroute to — surface as a
                            # retryable 503 (the fleet router intercepts
                            # the raw status before this mapping matters)
                            err = PlaneError(503, err.body)
                        for r in shp.batch:
                            r.error = err
                    dur = time.monotonic() - shp.t_ship
                    ship_attrs = (
                        {"replica": self.replica}
                        if self.replica is not None else None
                    )
                    for r in shp.traced:
                        tracespan.add_span(r.trace, "plane.ship",
                                           shp.t_ship, dur, ship_attrs)
                    with cond:
                        if gen["id"] != gen_id:
                            return
                        gen["outstanding"].popleft()
                        gen["responded"] += 1
                        cond.notify_all()
                    with self._cond:
                        self._inflight -= 1
                        self._cond.notify()  # a window-waiting dispatcher
                    for r in shp.batch:
                        r.event.set()
            except (ConnectionError, OSError, struct.error) as e:
                conn_failed(gen_id, e)

        try:
            self._dispatch_pipelined(seg_box, seg_size, depth, cond, gen,
                                     fail_requests, remerge_shed,
                                     conn_failed, receiver)
        finally:
            # pop the receiver out of its blocking recv: a closed client
            # must not leak a thread parked on a live engine socket for
            # the life of the process (the ComputePlane accept-leak
            # lesson, one layer out)
            with cond:
                sock = gen["sock"]
                gen["sock"] = None
                gen["dead"] = True
                cond.notify_all()
            try:
                if sock is not None:
                    sock.close()
            except OSError:
                pass

    def _dispatch_pipelined(self, seg_box, seg_size, depth, cond, gen,
                            fail_requests, remerge_shed, conn_failed,
                            receiver) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait(0.5)
                if self._closed:
                    return
                if self._inflight and self._window_s > 0:
                    # coalesce only while another frame is in flight (an
                    # idle plane dispatches immediately — no latency tax)
                    self._cond.wait(self._window_s)
                    if self._closed:
                        return
                # One frame = one (PROGRAM, KEY): the engine side runs a
                # frame through a single program's ServeBatcher, so
                # coalescing stays per-program by construction — and the
                # edge chain makes a per-TENANT quota/admission decision
                # per frame, so requests presenting different API keys
                # must never fuse.  The head request picks the frame's
                # identity; later requests for other programs/keys keep
                # their FIFO position for the next frame (other
                # dispatcher connections pick them up in parallel).
                batch: list[_PlaneRequest] = []
                skipped: deque[_PlaneRequest] = deque()
                program: str | None = None
                key: str | None = None
                total = 0
                while self._pending and total < MAX_FRAME_VALUES * 4:
                    req = self._pending[0]
                    if req.cancelled:
                        self._pending.popleft()
                        continue
                    if batch and (req.program != program
                                  or req.key != key):
                        skipped.append(self._pending.popleft())
                        continue
                    if total and total + len(req.body) > MAX_FRAME_VALUES * 4:
                        break
                    self._pending.popleft()
                    if not batch:
                        program = req.program
                        key = req.key
                    batch.append(req)
                    total += len(req.body)
                while skipped:  # restore FIFO order for other programs
                    self._pending.appendleft(skipped.pop())
                if not batch:
                    continue
                self._inflight += 1
                shed_report, self._shed = (
                    (self._shed, {}) if self._shed else (None, self._shed)
                )
                caprej_report, self._caprej = (
                    (self._caprej, [])
                    if self._caprej else (None, self._caprej)
                )
            # Trace metadata for the frame: each traced request ships its
            # ID + value offset + the spans already complete at frame
            # build (http.parse, frontend.coalesce) so the engine-side
            # trace carries the frontend half of the story.  Untraced
            # frames pay 0 extra bytes.
            meta = b""
            now = time.monotonic()
            traced = [r for r in batch if r.trace is not None]
            hedged_count = sum(1 for r in batch if r.hedged)
            # Ship edge timestamps when THIS process sees objectives OR a
            # registry is configured: per-program overrides are installed
            # engine-side (slo.set_objectives on upload) and a frontend
            # worker is a fresh subprocess that cannot see them — its own
            # armed() is False with MISAKA_SLO unset, which would starve
            # the engine's windows down to one observation per frame and
            # hide frontend queueing from the objective.  The engine-side
            # armed() check in slo_record stays authoritative; the only
            # cost of a false positive here is a few metadata bytes.
            slo_armed = slo.armed() or bool(
                os.environ.get("MISAKA_PROGRAMS_DIR")
            )
            if (traced or program is not None or key is not None
                    or slo_armed or hedged_count or len(batch) > 1
                    or shed_report or caprej_report):
                import json as _json

                entries = []
                edge = []
                off = 0
                for r in batch:
                    if r.trace is not None:
                        tracespan.add_span(
                            r.trace, "frontend.coalesce", r.enqueued,
                            now - r.enqueued,
                            {"frame_requests": len(batch)},
                        )
                        ent = {
                            "id": r.trace.trace_id,
                            "off": off,
                            "len": len(r.body) // 4,
                            "spans": [
                                [s.name, s.start, s.dur]
                                for s in r.trace.spans
                            ],
                        }
                        if getattr(r.trace, "inbound", False):
                            # the client presented this ID: the engine's
                            # capture recorder bypasses sampling for it
                            ent["in"] = 1
                        entries.append(ent)
                    if slo_armed:
                        # edge-observed SLO clock: this request's wait
                        # started when the frontend enqueued it
                        edge.append(round(r.enqueued, 6))
                    off += len(r.body) // 4
                obj = {"program": program, "traces": entries}
                if key is not None:
                    obj["key"] = key
                if len(batch) > 1:
                    # how many client requests this frame fused: the
                    # engine-side quota stage bills the rps bucket per
                    # REQUEST, not per frame
                    obj["reqs"] = len(batch)
                if edge:
                    obj["edge"] = edge
                if hedged_count:
                    obj["hedged"] = hedged_count
                if shed_report:
                    # worker-local shed cache hits since the last frame:
                    # the engine books them on misaka_edge_rejected_total
                    obj["shed"] = [
                        [t, r, n] for (t, r), n in shed_report.items()
                    ]
                if caprej_report:
                    # worker-terminated capture rows ride the same frame
                    # (lenient engine-side; dropped if this ship fails)
                    obj["caprej"] = caprej_report
                meta = _json.dumps(obj).encode()
            payload_out = b"".join(r.body for r in batch)

            # --- ship on the live socket generation, dialing as needed ---
            dials = 0
            while True:
                with cond:
                    need_dial = gen["dead"] or gen["sock"] is None
                    gen_id = gen["id"]
                if need_dial:
                    dials += 1
                    if dials > 2:
                        with self._cond:
                            self._inflight -= 1
                            self._cond.notify()
                        fail_requests(
                            batch, b"compute plane error: dial failed"
                        )
                        remerge_shed(shed_report)
                        break
                    try:
                        sock = self._connect()
                    except OSError as e:
                        with self._cond:
                            self._inflight -= 1
                            self._cond.notify()
                        fail_requests(
                            batch, f"compute plane error: {e}".encode()
                        )
                        remerge_shed(shed_report)
                        break
                    armed = False
                    seg = None
                    if self._shm_enabled:
                        seg = self._fresh_seg(seg_box, seg_size)
                    if seg is not None:
                        try:
                            armed = self._arm_shm(sock, seg, seg_size)
                        except (ConnectionError, OSError, struct.error):
                            try:
                                sock.close()
                            except OSError:
                                pass
                            continue  # one more dial, then give up
                    with cond:
                        gen["id"] += 1
                        gen_id = gen["id"]
                        gen.update(sock=sock, seg=seg, armed=armed,
                                   dead=False, responded=0,
                                   inherited=False)
                        gen["outstanding"].clear()
                    threading.Thread(
                        target=receiver, daemon=True,
                        args=(sock, gen_id, seg if armed else None),
                        name="misaka-plane-recv",
                    ).start()
                with cond:
                    if gen["id"] != gen_id or gen["dead"]:
                        continue
                    # pipeline backpressure: shm's double buffer needs
                    # strict one-in-flight; sockets take `depth`
                    eff = 1 if gen["armed"] else depth
                    while (not gen["dead"] and gen["sock"] is not None
                           and len(gen["outstanding"]) >= eff):
                        cond.wait(0.2)
                    if gen["dead"] or gen["sock"] is None:
                        continue  # the generation died while we waited
                    use_shm = gen["armed"] and total <= seg_size
                    if use_shm:
                        # payload into the segment (safe: zero frames
                        # outstanding on an armed connection); header +
                        # metadata (which must then exist, to carry the
                        # count) stay on the socket
                        import json as _json

                        gen["seg"].buf[0:total] = payload_out
                        shm_meta = _json.dumps(
                            {"program": program, "shm_vals": total // 4}
                        ).encode() if not meta else (
                            meta[:-1] + b',"shm_vals":%d}' % (total // 4)
                        )
                        frame = _REQ_HDR.pack(0, len(shm_meta)) + shm_meta
                    else:
                        frame = (
                            _REQ_HDR.pack(total // 4, len(meta))
                            + payload_out + meta
                        )
                    shp = _Shipment(
                        batch, traced, time.monotonic(), use_shm,
                        shed_report, replay_ok=gen["inherited"],
                    )
                    # enqueue BEFORE sending (a response cannot arrive
                    # before its frame's bytes do), so the send itself
                    # runs OUTSIDE the lock: a blocking sendall holding
                    # `cond` would stall the receiver's completion path —
                    # with full socket buffers both directions that is a
                    # four-way wedge only the timeout could break
                    sock_now = gen["sock"]
                    gen["outstanding"].append(shp)
                    gen["inherited"] = True
                try:
                    if faults.armed():
                        delay = faults.fire("plane_delay")
                        if delay is not None:
                            # per-frame latency injection (WAN twin of
                            # rpc_delay) — outside every lock
                            time.sleep(delay)
                        if _plane_partitioned(self._path):
                            # black-hole: the frame is never written, so
                            # the DEADLINE (not a connection error) is
                            # what trips — the grey-failure hedge path
                            break
                    sock_now.sendall(frame)
                except (ConnectionError, OSError) as send_exc:
                    # conn_failed sees this batch among the outstanding
                    # shipments and applies the replay discipline to it
                    conn_failed(gen_id, send_exc)
                break


class _RouterReplica:
    """One replica slot as the router sees it: a PlaneClient plus a
    health state the prober keeps fresh."""

    __slots__ = ("idx", "path", "client", "state", "since",
                 "suspect_until", "suspect_streak")

    def __init__(self, idx: int, path: str, client: PlaneClient):
        self.idx = idx
        self.path = path
        self.client = client
        # optimistic start: the first real frame corrects a wrong "up"
        # within one round trip, while a pessimistic start would refuse
        # traffic until the prober's first pass
        self.state = "up"          # "up" | "down" | "draining"
        self.since = time.monotonic()
        # frame-failure hold-down (see suspect()): until this instant a
        # probe success alone may not readmit the replica
        self.suspect_until = 0.0
        self.suspect_streak = 0

    def mark(self, state: str) -> None:
        if self.state != state:
            self.state = state
            self.since = time.monotonic()

    def suspect(self, hold_base: float) -> None:
        """A REAL frame failed here (transport error or frame deadline):
        mark down and hold the replica out of probe readmission on a
        doubling backoff.  The probe path touches nothing but the plane
        socket, so a wedged-but-alive engine (grey failure) still
        answers probes instantly — without this hold the prober would
        flip it back "up" every probe_s and the hash ring would keep
        handing it every sticky request's first half-deadline."""
        now = time.monotonic()
        if now < self.suspect_until:
            # Escalate once per failure EVENT, not per request: one
            # failed frame fans out to every caller it coalesced, and
            # 64 concurrent suspects would jump the doubling curve
            # (0.5s, 1s, 2s...) straight to the 30s cap on a single
            # stall.  Failures landing inside the current hold are the
            # same event; only a failure after the hold expired proves
            # the replica is still bad and doubles it.
            self.mark("down")
            return
        self.suspect_streak += 1
        hold = min(30.0, hold_base * (2 ** (self.suspect_streak - 1)))
        self.suspect_until = now + hold
        self.mark("down")

    def absolve(self) -> None:
        """Frame-failure history no longer applies: a frame was served
        here, or the plane stopped accepting (the process is dead —
        whatever accepts next is a fresh replacement)."""
        self.suspect_streak = 0
        self.suspect_until = 0.0


class FleetPlaneRouter:
    """Routes requests across N engine-replica compute planes.

    The data-parallel router of the fleet plane (runtime/fleet.py): one
    PlaneClient (local coalescer + persistent connections) per replica,
    and a policy layer deciding which replica each request rides:

      * program-addressed requests follow the consistent-hash ring on
        the program name (sticky per-program coalescing and registry
        engine state; only ~1/N of the keyspace moves when a replica
        joins or leaves);
      * stateless requests go to the healthy replica with the LEAST
        local queue depth, ties broken by lowest index (deterministic);
      * a replica that fails a frame (transport error, frame deadline,
        or the drain reroute status) is marked unhealthy and the request
        is HEDGED onto the next healthy candidate — each attempt rides
        the remaining request deadline, and re-routed requests are
        flagged in frame metadata so the serving replica's
        misaka_plane_hedged_requests_total makes failovers visible;
      * when NO replica is healthy the router keeps probing for
        `down_grace` seconds (riding out a supervisor respawn or a
        1-replica roll), then answers a typed 503 — the only way a
        client ever sees the fleet's internals fail.

    A background prober revives replicas: a zero-cost probe frame
    against each non-up replica's plane socket flips it back to "up"
    the moment a replacement binds and serves — re-admission after a
    kill or a roll needs no coordination beyond the socket itself.
    One exception: a replica marked down by a REAL frame failure sits
    out a doubling hold (`suspect_hold` base, 30s cap) before a probe
    success may readmit it, because probes cannot distinguish a healthy
    engine from a wedged-but-alive one; a probe that finds the socket
    dead resets the hold (the replacement is a fresh process), so
    crash/kill recovery readmits at probe speed.
    """

    #: plane statuses that mean "this replica cannot serve this frame,
    #: a sibling can": transport failure maps to 502 inside PlaneClient,
    #: PLANE_DRAINING is the roll's reroute signal
    REROUTE_STATUSES = frozenset({502, PLANE_DRAINING})

    def __init__(self, paths: list[str], conns: int = 2,
                 timeout: float = 60.0, probe_s: float = 0.25,
                 down_grace: float | None = None,
                 suspect_hold: float = 0.5):
        from misaka_tpu.runtime.fleet import HashRing

        if not paths:
            raise ValueError("FleetPlaneRouter needs at least one path")
        self._replicas = [
            _RouterReplica(i, p, PlaneClient(p, conns=conns,
                                             timeout=timeout, replica=i))
            for i, p in enumerate(paths)
        ]
        self._ring = HashRing(range(len(paths)))
        self._probe_s = float(probe_s)
        if down_grace is None:
            down_grace = float(
                os.environ.get("MISAKA_FLEET_DOWN_GRACE_S", "") or 5.0
            )
        self._down_grace = float(down_grace)
        self._suspect_hold = float(suspect_hold)
        # probe sockets handshake too; cached once (the probe loop runs
        # 4x/s and must not re-read MISAKA_PLANE_SECRET_FILE each time)
        self._secret = edge_mod.plane_secret()
        # probes of TCP planes present the same client cert the data
        # path does — an mTLS plane rejects bare probes like any other
        # plaintext peer
        self._tls = (
            edge_mod.plane_tls_from_env()
            if any(parse_plane_addr(p)[0] == "tcp" for p in paths)
            else None
        )
        self._closed = False
        threading.Thread(
            target=self._probe_loop, daemon=True,
            name="misaka-fleet-router-probe",
        ).start()

    def close(self) -> None:
        self._closed = True
        for r in self._replicas:
            r.client.close()

    def states(self) -> dict[int, str]:
        return {r.idx: r.state for r in self._replicas}

    def depth(self) -> int:
        """Queued + in-flight frames across every replica client — the
        worker's local backpressure signal (the edge guard in
        make_frontend_server)."""
        return sum(r.client.depth() for r in self._replicas)

    def report_shed(self, tenant: str | None, reason: str) -> None:
        """Route a worker-local shed count to a replica for metric
        delivery (any replica: the fleet /metrics aggregates them)."""
        up = [r for r in self._replicas if r.state == "up"]
        (up[0] if up else self._replicas[0]).client.report_shed(
            tenant, reason
        )

    def report_capture(self, row: dict) -> None:
        """Route a worker-terminated capture row to a replica's capture
        ring (same any-up policy as the shed counts)."""
        up = [r for r in self._replicas if r.state == "up"]
        (up[0] if up else self._replicas[0]).client.report_capture(row)

    # --- health probing -----------------------------------------------------

    def _probe_once(self, r: _RouterReplica) -> str:
        """One probe frame against `r`'s plane socket: "up", "draining",
        or "down" as observed right now."""
        if faults.armed() and _plane_partitioned(r.path):
            # a partitioned peer is unreachable to probes too — it must
            # stay out of the candidate set, not flap up/down
            return "down"
        try:
            fam, host, port = parse_plane_addr(r.path)
            if fam == "tcp":
                sock = socket.create_connection((host, port), timeout=1.0)
                if self._tls is not None:
                    sock = self._tls.client_context().wrap_socket(
                        sock, server_hostname=host
                    )
            else:
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                sock.settimeout(1.0)
                sock.connect(r.path)
            try:
                if self._secret is not None:
                    sock.sendall(edge_mod.plane_handshake(self._secret))
                meta = b'{"probe": 1}'
                sock.sendall(_REQ_HDR.pack(0, len(meta)) + meta)
                status, length = _RESP_HDR.unpack(_recv_exact(sock, 8))
                if length:
                    _recv_exact(sock, length)
            finally:
                sock.close()
        except OSError:
            return "down"
        if status == 200:
            return "up"
        if status == PLANE_DRAINING:
            return "draining"
        return "down"

    def _probe_loop(self) -> None:
        while not self._closed:
            time.sleep(self._probe_s)
            for r in self._replicas:
                if r.state == "up":
                    continue
                observed = self._probe_once(r)
                if observed == "down":
                    # an unreachable plane is a dead process: whatever
                    # accepts next is a fresh replacement, so the
                    # frame-failure hold stops applying
                    r.absolve()
                elif (observed == "up"
                        and time.monotonic() < r.suspect_until):
                    # a probe success is weaker evidence than the real
                    # frame that just failed here — hold the replica
                    # out (see _RouterReplica.suspect)
                    continue
                r.mark(observed)

    # --- routing ------------------------------------------------------------

    def _candidates(self, program: str | None,
                    tried: set[int]) -> list[_RouterReplica]:
        """Healthy replicas in preference order: hash-ring walk for a
        program-addressed request (stickiness), least-queue-depth with
        index tie-break otherwise."""
        up = [r for r in self._replicas
              if r.state == "up" and r.idx not in tried]
        if not up:
            return []
        if program:
            by_idx = {r.idx: r for r in up}
            key = program.partition("@")[0]
            return [by_idx[i] for i in self._ring.lookup(key)
                    if i in by_idx]
        return sorted(up, key=lambda r: (r.client.depth(), r.idx))

    def compute_raw(self, body: bytes, timeout: float = 30.0,
                    program: str | None = None,
                    key: str | None = None) -> bytes:
        deadline = time.monotonic() + timeout
        tried: set[int] = set()
        hedged = False
        last_err: PlaneError | None = None
        while True:
            cands = self._candidates(program, tried)
            if not cands:
                # no healthy untried replica: forget attempt history (a
                # replica the prober readmits mid-wait must be eligible
                # even though we tried it — with one replica, `tried`
                # would otherwise mask its OWN recovery forever) and
                # ride out a respawn window before answering the typed
                # fleet-down 503
                tried = set()
                grace_end = min(
                    deadline, time.monotonic() + self._down_grace
                )
                cands = self._candidates(program, tried)
                while not cands and time.monotonic() < grace_end:
                    time.sleep(0.05)
                    cands = self._candidates(program, tried)
                if not cands:
                    detail = (
                        last_err.body.decode(errors="replace")
                        if last_err is not None else "no replica up"
                    )
                    raise PlaneError(
                        503,
                        f"fleet down: no healthy engine replica "
                        f"({detail})".encode(),
                    )
            r = cands[0]
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                if last_err is None:
                    raise PlaneError(500, b"compute plane timed out")
                if last_err.status == PLANE_DRAINING:
                    # the plane-private reroute status must never reach
                    # a client — a deadline eaten by drain reroutes is a
                    # retryable unavailability, not a protocol status
                    raise PlaneError(
                        503, b"fleet draining: " + last_err.body
                    )
                raise last_err
            # Hedge budget: while another candidate remains untried, an
            # attempt only gets HALF the remaining deadline — a silent
            # (blackholed) replica must leave time to hedge the request
            # onto a sibling instead of eating the whole budget.  The
            # last candidate gets everything left.
            more = len(cands) > 1
            attempt_timeout = remaining / 2 if more else remaining
            try:
                out = r.client.compute_raw(
                    body, timeout=attempt_timeout, program=program,
                    key=key, hedged=hedged,
                )
                r.absolve()  # a served frame clears the hold-down
                return out
            except PlaneError as e:
                if e.status in self.REROUTE_STATUSES:
                    if e.status == PLANE_DRAINING:
                        # a drain reroute is ROUTINE (every roll does
                        # it) and already counted on the draining
                        # replica's misaka_plane_drain_reroutes_total —
                        # flagging it hedged too would make the hedge
                        # counter (documented as "a sibling is FAILING
                        # frames", alert-worthy) fire on every deploy
                        r.mark("draining")
                    else:
                        r.suspect(self._suspect_hold)
                        hedged = True
                    tried.add(r.idx)
                    last_err = e
                    continue
                if e.status == 500 and e.body == b"compute plane timed out":
                    # the frame deadline (a blackholed or wedged replica):
                    # hedge like a transport failure, but the retry only
                    # has whatever deadline remains
                    r.suspect(self._suspect_hold)
                    tried.add(r.idx)
                    hedged = True
                    last_err = e
                    continue
                raise  # an engine-level answer (400/404/413/500): final


class _ReusePortHTTPServer(ThreadingHTTPServer):
    """SO_REUSEPORT bind: every frontend process (and only they) binds the
    same public port; the kernel balances incoming connections."""

    daemon_threads = True

    def server_bind(self):
        self.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        super().server_bind()


def make_frontend_server(
    public_port: int,
    engine_url: str,
    plane_path: str,
    plane_conns: int = 2,
    max_body: int | None = None,
    fleet: bool | None = None,
) -> ThreadingHTTPServer:
    """Build one frontend worker's HTTP server (call serve_forever on it).

    Hot routes answer from the compute plane; everything else proxies to
    the engine's own HTTP server at `engine_url` (the fleet control
    server in fleet mode).  `plane_path` may be a comma-separated list
    of replica plane sockets — the worker then routes across them with
    the FleetPlaneRouter (health-gated least-queue-depth + program hash
    ring + hedged failover).  `fleet=True` forces the router even for a
    single path (a 1-replica fleet still needs the drain-reroute grace
    during rolls); the default infers it from the path count.
    """
    import http.client
    from urllib.parse import urlsplit

    if max_body is None:
        max_body = int(
            os.environ.get("MISAKA_MAX_BODY", "") or 64 * 1024 * 1024
        )
    paths = [p for p in plane_path.split(",") if p]
    if fleet is None:
        fleet = len(paths) > 1
    if fleet:
        plane = FleetPlaneRouter(paths, conns=plane_conns)
    else:
        plane = PlaneClient(paths[0], conns=plane_conns)
    engine = urlsplit(engine_url)
    engine_host = engine.hostname or "127.0.0.1"
    engine_port = engine.port or 8000
    local = threading.local()
    # Worker-side edge (runtime/edge.py): the workers TERMINATE TLS and
    # run one cheap local guard — a hard cap on plane backlog
    # (MISAKA_PLANE_DEPTH_MAX frames, 0 disables) so a flood cannot grow
    # this worker's queue without bound while the engine sheds.  All
    # tenant-stateful policy (auth, quota, admission fair-share) runs
    # ENGINE-side per frame: N workers each holding 1/Nth of a token
    # bucket would not be a quota.  MISAKA_EDGE=0 kills the guard too.
    plane_depth_max = (
        int(os.environ.get("MISAKA_PLANE_DEPTH_MAX", "") or 256)
        if os.environ.get("MISAKA_EDGE", "1") != "0"
        and os.environ.get("MISAKA_EDGE_ADMISSION", "1") != "0" else 0
    )
    # Negative-decision cache: when the engine sheds a (program, key)
    # frame with 429 + Retry-After, this worker honors that Retry-After
    # LOCALLY — subsequent requests of the same tenant shed in ~100us at
    # this door instead of queueing a doomed frame behind real work (a
    # flooding tenant would otherwise occupy plane round trips with
    # rejections and slow its neighbors).  Entries expire exactly when
    # the engine said to retry; 401/403 are never cached (a key-file
    # rotation must take effect at the next request).
    shed_lock = threading.Lock()
    shed_until: dict[tuple, tuple[float, "edge_mod.EdgeReject"]] = {}
    # Bodies above this ride the PROXY path instead of the compute plane:
    # the plane exists to fuse many SMALL requests, its frame cap is
    # MAX_FRAME_VALUES, and a single-client bulk body (the big-batch
    # lane) is better off striping inside the engine directly.  Half the
    # frame cap leaves room to coalesce a big body with neighbors.
    plane_body_limit = MAX_FRAME_VALUES * 2  # bytes = frame cap / 2

    class FrontendHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            log.debug(fmt, *args)

        def handle_one_request(self):
            # the same fast request loop the engine's server runs
            try:
                self.raw_requestline = self.rfile.readline(65537)
                if len(self.raw_requestline) > 65536:
                    self.requestline = ""
                    self.request_version = ""
                    self.command = ""
                    self.send_error(414, "Request-URI Too Long")
                    return
                if not self.raw_requestline:
                    self.close_connection = True
                    return
                # parse-span clock starts after the request line arrives
                # (the readline blocks across keep-alive idle time)
                t_parse = time.monotonic()
                parsed = fast_parse_request(self)
                if parsed is None:
                    return
                if not parsed and not self.parse_request():
                    return
                self._parse_mark = (t_parse, time.monotonic() - t_parse)
                mname = "do_" + self.command
                if not hasattr(self, mname):
                    self.send_error(
                        501, f"Unsupported method ({self.command!r})"
                    )
                    return
                getattr(self, mname)()
                self.wfile.flush()
            except TimeoutError as e:
                self.log_error("Request timed out: %r", e)
                self.close_connection = True
            except ssl.SSLError as e:
                # deferred TLS handshake fails on this thread's first
                # read (edge.wrap_server_tls): one closed connection,
                # not a stderr traceback per plaintext probe
                self.log_error("TLS handshake failed: %r", e)
                self.close_connection = True

        def send_response(self, code, message=None):
            self._trace_code = code  # response status for the trace record
            super().send_response(code, message)

        def _reply(self, code: int, data: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            # proxied responses carry the ENGINE's trace headers (they
            # have the queue/pass phases) via _extra_headers; otherwise
            # this worker answers with its own trace ID + total timing
            extras = getattr(self, "_extra_headers", ()) or ()
            have_trace = False
            for k, v in extras:
                if k.lower() == "x-misaka-trace":
                    have_trace = True
                self.send_header(k, v)
            tr = getattr(self, "_misaka_trace", None)
            if tr is not None and not have_trace:
                self.send_header(tracespan.TRACE_HEADER, tr.trace_id)
                st = tracespan.server_timing(tr)
                if st:
                    self.send_header("Server-Timing", st)
            self.end_headers()
            self.wfile.write(data)

        def _text(self, code: int, body: str) -> None:
            self._reply(code, body.encode(), "text/plain; charset=utf-8")

        def _plane_error(self, e: PlaneError, shed_key=None) -> None:
            """Answer a PlaneError, restoring the edge's typed headers: a
            401/403/429 frame rejection ships a JSON body with the
            reason + retry_after (EdgeReject.to_wire) — the client must
            see the same Retry-After it would on the direct surface.
            A 429 with Retry-After also arms the local shed cache for
            `shed_key`: this tenant's next requests reject at THIS door
            until the advertised backoff expires."""
            rej = edge_mod.EdgeReject.from_wire(e.status, e.body)
            if rej is not None:
                if (
                    shed_key is not None and e.status == 429
                    and rej.retry_after is not None
                ):
                    # hold at least 250ms even when the bucket's own
                    # refill estimate is tiny: a flooding tenant must
                    # not get a plane round trip every few dozen ms —
                    # its bucket accumulates during the hold, so its
                    # admitted rate still averages the quota
                    now = time.monotonic()
                    until = now + min(max(rej.retry_after, 0.25), 30.0)
                    with shed_lock:
                        if len(shed_until) >= 1024:
                            # the key is client-controlled: sweep the
                            # expired entries before the dict can grow
                            # without bound on invented keys, and cap
                            # hard if a flood outruns expiry
                            for k in [
                                k for k, (u, _) in shed_until.items()
                                if u <= now
                            ]:
                                del shed_until[k]
                            while len(shed_until) >= 4096:
                                shed_until.pop(next(iter(shed_until)))
                        shed_until[shed_key] = (until, rej)
                for k, v in rej.headers():
                    self._extra_headers.append((k, v))
                self._text(e.status, rej.message)
                return
            self._text(e.status, e.body.decode(errors="replace"))

        def _shed_cached(self, shed_key) -> bool:
            """True (and answered 429) when this tenant is inside an
            engine-advertised backoff window."""
            if not shed_until:
                return False
            with shed_lock:
                hit = shed_until.get(shed_key)
                if hit is None:
                    return False
                until, rej = hit
                remaining = until - time.monotonic()
                if remaining <= 0:
                    del shed_until[shed_key]
                    return False
            edge_mod.drain_or_close(self)  # keep-alive discipline
            self._extra_headers.append(
                ("Retry-After", str(max(1, int(-(-remaining // 1)))))
            )
            self._text(429, rej.message)
            # the cache hit never reaches the engine: ship the count on
            # the next frame so misaka_edge_rejected_total stays honest
            plane.report_shed(getattr(rej, "tenant", None), rej.reason)
            if capture_mod.available():
                # worker-terminated reject: this surface owns the capture
                # record (surface "worker", delivered via frame metadata)
                tr = tracespan.current()
                plane.report_capture({
                    "t": time.time(),
                    "program": getattr(self, "_misaka_program", None),
                    "trace": tr.trace_id if tr is not None else None,
                    "in": int(getattr(tr, "inbound", False)),
                    "status": 429,
                    "reason": rej.reason,
                })
            return True

        def _edge_guard(self) -> bool:
            """The worker's local backpressure check; True = proceed.
            A worker whose plane backlog exceeds the cap answers a typed
            429 + Retry-After WITHOUT reading the request body — the
            shed must not buffer the flood (connection closes, like the
            engine's bulk-reject path)."""
            if not plane_depth_max or plane.depth() < plane_depth_max:
                return True
            self.close_connection = True
            self._extra_headers.append(("Retry-After", "1"))
            self._text(
                429,
                f"frontend overloaded: {plane.depth()} plane frames "
                f"queued (cap {plane_depth_max}); retry after backoff",
            )
            # tenant unknown at this worker (no auth state here): the
            # backlog-cap shed books under "other"
            plane.report_shed(None, "overload")
            if capture_mod.available():
                tr = tracespan.current()
                plane.report_capture({
                    "t": time.time(),
                    "program": None,
                    "trace": tr.trace_id if tr is not None else None,
                    "in": int(getattr(tr, "inbound", False)),
                    "status": 429,
                    "reason": "overload",
                })
            return False

        def _with_trace(self, inner) -> None:
            """Begin/end the request trace around one handler dispatch —
            the frontend-worker twin of make_http_server's _observed
            (metrics live on the engine; the trace is what must start
            HERE, where the request first enters the serving plane)."""
            self._extra_headers = []
            self._trace_code = None
            trace = tracespan.begin(
                self.headers.get(tracespan.TRACE_HEADER),
                route=self.path.split("?", 1)[0],
            )
            self._misaka_trace = trace
            mark = getattr(self, "_parse_mark", None)
            self._parse_mark = None
            if trace is not None and mark is not None:
                tracespan.add_span(trace, "http.parse", mark[0], mark[1])
            try:
                inner()
            finally:
                self._misaka_trace = None
                tracespan.end(trace, status=self._trace_code)

        def _read_body(self, required: bool = True):
            """Body bytes, or None after answering 411/400/413.

            `required=False` treats a missing Content-Length as an empty
            body — the engine's own form routes are lenient (`curl -X
            POST /pause` sends no length), so the proxy and form paths
            must be too; only the raw bulk lane demands a length.
            """
            length_hdr = self.headers.get("Content-Length")
            if length_hdr is None:
                if not required:
                    return b""
                self.close_connection = True
                self._text(411, "Content-Length required")
                return None
            try:
                length = int(length_hdr)
            except ValueError:
                self.close_connection = True
                self._text(400, "cannot parse Content-Length")
                return None
            if length > max_body:
                self.close_connection = True
                self._text(
                    413,
                    f"body of {length} bytes exceeds the "
                    f"{max_body}-byte cap (MISAKA_MAX_BODY)",
                )
                return None
            return self.rfile.read(length)

        def do_POST(self):
            self._with_trace(self._do_post)

        def do_GET(self):
            self._with_trace(lambda: self._proxy("GET"))

        def _do_post(self):
            route = self.path.split("?", 1)[0]
            pm = _PROGRAM_COMPUTE_RE.match(route)
            if pm:
                # program-addressed op: run the same accelerated body
                # against the named program (the plane frame carries it)
                program = unquote(pm.group(1))
                route = "/" + pm.group(2)
            else:
                program = self.headers.get("X-Misaka-Program") or None
            key = edge_mod.key_from_headers(self.headers)
            shed_key = (program, key)
            if route == "/compute_raw" and "spread=0" not in self.path:
                if self._shed_cached(shed_key) or not self._edge_guard():
                    return
                length_hdr = self.headers.get("Content-Length", "")
                if length_hdr.isdigit() and int(length_hdr) > plane_body_limit:
                    # bulk body: the engine stripes it directly (the
                    # plane's frame cap must not shrink MISAKA_MAX_BODY)
                    self._proxy("POST")
                    return
                body = self._read_body()
                if body is None:
                    return
                if wire.is_binary(self.headers.get("Content-Type")):
                    # headered binary protocol (utils/wire.py): the
                    # worker validates framing at the edge and ships the
                    # bare payload over the plane, exactly like the
                    # legacy raw form
                    try:
                        body = wire.unpack(body)
                    except wire.WireError as e:
                        self._text(400, f"bad binary body: {e}")
                        return
                if len(body) % 4:
                    self._text(400, "body must be raw int32 values")
                    return
                try:
                    out = plane.compute_raw(body, program=program, key=key)
                except PlaneError as e:
                    self._plane_error(e, shed_key)
                    return
                if wire.accepts_binary(self.headers.get("Accept")):
                    self._reply(200, wire.header(len(out) // 4) + out,
                                wire.CONTENT_TYPE)
                else:
                    self._reply(200, out, "application/octet-stream")
                return
            if route == "/compute":
                if self._shed_cached(shed_key) or not self._edge_guard():
                    return
                body = self._read_body(required=False)
                if body is None:
                    return
                # minimal form parse for the one field the route takes
                from urllib.parse import parse_qs

                form = {
                    k: v[0]
                    for k, v in parse_qs(
                        body.decode(errors="replace"),
                        keep_blank_values=True,
                    ).items()
                }
                try:
                    value = int(form.get("value", ""))
                except ValueError:
                    self._text(400, "cannot parse value")
                    return
                raw = struct.pack("<i", value)
                try:
                    out = plane.compute_raw(raw, program=program, key=key)
                except PlaneError as e:
                    self._plane_error(e, shed_key)
                    return
                result = struct.unpack("<i", out)[0]
                self._reply(
                    200, b'{"value": %d}\n' % result, "application/json"
                )
                return
            self._proxy("POST")

        def _proxy(self, method: str) -> None:
            """Relay anything this worker does not accelerate to the
            engine's HTTP server over a per-thread keep-alive connection."""
            body = b""
            if method == "POST":
                body = self._read_body(required=False)
                if body is None:
                    return
            headers = {}
            ctype = self.headers.get("Content-Type")
            if ctype:
                headers["Content-Type"] = ctype
            prog = self.headers.get("X-Misaka-Program")
            if prog:
                # program addressing follows proxied requests (e.g. the
                # legacy /compute_batch text lane) to the engine
                headers["X-Misaka-Program"] = prog
            for h in ("X-Misaka-Key", "Authorization"):
                # credentials follow proxied requests: the engine's edge
                # chain authenticates them (this worker terminates TLS
                # but holds no auth state)
                v = self.headers.get(h)
                if v:
                    headers[h] = v
            tr = getattr(self, "_misaka_trace", None)
            if tr is not None:
                # the trace follows the request to the engine, whose
                # response headers (queue/pass phases, deprecations) come
                # back verbatim below
                headers[tracespan.TRACE_HEADER] = tr.trace_id
            if self.path.split("?", 1)[0] == "/fleet/roll":
                # a synchronous roll pays one full engine boot per
                # replica (tens of seconds each) and can far outlive the
                # pooled 60s proxy timeout — which would answer 502
                # while the roll keeps running invisibly (a retry then
                # 409s).  Give it a dedicated unpooled connection with
                # the client-side budget (client.fleet_roll passes up
                # to 480s).
                conn = http.client.HTTPConnection(
                    engine_host, engine_port, timeout=600
                )
                try:
                    conn.request(method, self.path, body or None, headers)
                    resp = conn.getresponse()
                    payload = resp.read()
                except (http.client.HTTPException, OSError) as e:
                    self._text(502, f"engine unreachable: {e}")
                    return
                finally:
                    conn.close()
                self._reply(
                    resp.status, payload,
                    resp.getheader("Content-Type") or "text/plain",
                )
                return
            for attempt in (0, 1):
                conn = getattr(local, "engine_conn", None)
                fresh = conn is None
                if fresh:
                    conn = http.client.HTTPConnection(
                        engine_host, engine_port, timeout=60
                    )
                    local.engine_conn = conn
                try:
                    conn.request(method, self.path, body or None, headers)
                    resp = conn.getresponse()
                    payload = resp.read()
                except (http.client.HTTPException, OSError) as e:
                    conn.close()
                    local.engine_conn = None
                    if fresh or attempt:
                        self._text(502, f"engine unreachable: {e}")
                        return
                    continue  # stale pooled socket: retry once, fresh
                for h in (tracespan.TRACE_HEADER, "Server-Timing",
                          "Deprecation", "Link", "Retry-After",
                          "WWW-Authenticate"):
                    v = resp.getheader(h)
                    if v:
                        self._extra_headers.append((h, v))
                self._reply(
                    resp.status, payload,
                    resp.getheader("Content-Type") or "text/plain",
                )
                return

    httpd = _ReusePortHTTPServer(("0.0.0.0", public_port), FrontendHandler)
    # TLS terminates at the workers (MISAKA_TLS_CERT/MISAKA_TLS_KEY —
    # inherited env, so every worker of the pool serves the same cert);
    # the engine/fleet proxy target behind them stays loopback HTTP.
    return edge_mod.wrap_server_tls(httpd, edge_mod.tls_context_from_env())


def frontend_main(argv=None) -> int:
    """`python -m misaka_tpu.runtime.frontends` — one worker process."""
    import argparse

    parser = argparse.ArgumentParser(
        description="misaka HTTP frontend worker (SO_REUSEPORT)"
    )
    parser.add_argument("--port", type=int, required=True)
    parser.add_argument("--engine", required=True,
                        help="engine HTTP base url (proxy target)")
    parser.add_argument("--plane", required=True,
                        help="compute-plane unix socket path (comma-"
                        "separated list in fleet mode: one per replica)")
    parser.add_argument("--plane-conns", type=int, default=2)
    parser.add_argument(
        "--fleet", action="store_true",
        help="route across the plane paths with the fleet router even "
        "when only one is given (rolling restarts need the reroute "
        "grace); implied by multiple --plane paths",
    )
    parser.add_argument(
        "--parent-pid", type=int, default=0,
        help="exit when this process disappears (spawn_frontends sets it: "
        "an orphaned worker must NOT keep the SO_REUSEPORT public port — "
        "the kernel would keep balancing real traffic onto a frontend "
        "whose engine is gone)",
    )
    args = parser.parse_args(argv)
    # Many small handler threads sharing this worker's GIL: the default
    # 5ms switch interval turns response waves into convoys.
    sys.setswitchinterval(0.001)
    exit_after = faults.fire("worker_exit")
    if exit_after is not None:
        # chaos harness (utils/faults.py): hard-exit this worker after N
        # seconds, exactly the failure the supervisor must absorb — the
        # kill(9)-without-kill lever `make chaos-smoke` pulls
        def _fault_exit(delay=max(0.0, exit_after)):
            time.sleep(delay)
            log.warning("worker_exit fault fired; frontend hard-exiting")
            os._exit(1)

        threading.Thread(target=_fault_exit, daemon=True).start()
    if args.parent_pid:
        def _watch_parent(pid=args.parent_pid):
            while True:
                # reparenting check first: a dead engine left as a zombie
                # (nothing reaped it) still answers os.kill(pid, 0), but
                # this worker is the engine's direct child, so its ppid
                # flips to the reaper the moment the engine dies
                if os.getppid() != pid:
                    log.warning("engine pid %d gone; frontend exiting", pid)
                    os._exit(0)
                try:
                    os.kill(pid, 0)
                except OSError:
                    log.warning("engine pid %d gone; frontend exiting", pid)
                    os._exit(0)
                time.sleep(2.0)

        threading.Thread(target=_watch_parent, daemon=True).start()
    httpd = make_frontend_server(
        args.port, args.engine, args.plane, plane_conns=args.plane_conns,
        fleet=True if args.fleet else None,
    )
    log.info("frontend worker on :%d (engine %s)", args.port, args.engine)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


def _worker_cmd(
    public_port: int, engine_url: str, plane_path: str, plane_conns: int,
    fleet: bool = False,
) -> list[str]:
    cmd = [
        sys.executable, "-m", "misaka_tpu.runtime.frontends",
        "--port", str(public_port),
        "--engine", engine_url,
        "--plane", plane_path,
        "--plane-conns", str(plane_conns),
        "--parent-pid", str(os.getpid()),
    ]
    if fleet:
        cmd.append("--fleet")
    return cmd


def spawn_frontends(
    n: int,
    public_port: int,
    engine_url: str,
    plane_path: str,
    plane_conns: int = 2,
    fleet: bool = False,
) -> list[subprocess.Popen]:
    """Start n UNSUPERVISED frontend worker processes sharing `public_port`
    (benches and tests that own process lifetimes themselves; production
    serving uses FrontendSupervisor, which respawns deaths).

    Workers import stdlib only (no jax), so they boot in well under a
    second.  The caller owns the Popen handles (terminate() to stop);
    wait_ready() below confirms the port actually answers.
    """
    return [
        subprocess.Popen(_worker_cmd(public_port, engine_url, plane_path,
                                     plane_conns, fleet=fleet))
        for _ in range(n)
    ]


class FrontendSupervisor:
    """Keeps the frontend worker pool at strength: spawn, watch, respawn.

    A SO_REUSEPORT pool has a failure mode plain process trees don't: when
    one worker dies, the kernel keeps balancing the SAME public port over
    the survivors — capacity silently shrinks and nothing errors.  The
    supervisor closes that hole:

      * each of the n slots holds one worker process; a monitor thread
        polls for deaths (reaping them) and respawns with exponential
        backoff + jitter (`backoff_base` doubling to `backoff_cap`);
      * a slot whose workers keep dying FAST (within `fast_crash_s` of
        spawn, `breaker_threshold` times in a row) is crash-looping — its
        circuit breaker opens and respawning pauses for `breaker_reset_s`
        before one half-open retry, so a poisoned config can't fork-bomb
        the host;
      * `state()` is the no-silent-degradation surface: alive vs
        configured, restart counts, open breakers, and an explicit
        `degraded` flag — /healthz and /status render it (make_http_server
        reads `server.misaka_supervisor`), and every respawn rides the
        misaka_frontend_restarts_total counter.
    """

    def __init__(
        self,
        n: int,
        public_port: int,
        engine_url: str,
        plane_path: str,
        plane_conns: int = 2,
        backoff_base: float = 0.5,
        backoff_cap: float = 15.0,
        fast_crash_s: float = 5.0,
        breaker_threshold: int = 5,
        breaker_reset_s: float = 60.0,
        poll_s: float = 0.2,
        fleet: bool = False,
    ):
        self._cmd = _worker_cmd(public_port, engine_url, plane_path,
                                plane_conns, fleet=fleet)
        # used statelessly (delay_for): the exponent is each slot's
        # consecutive-fast-crash streak, not a global attempt counter
        self._backoff = Backoff(base=backoff_base, cap=backoff_cap)
        self._fast_crash_s = float(fast_crash_s)
        self._breaker_threshold = max(1, int(breaker_threshold))
        self._breaker_reset_s = float(breaker_reset_s)
        self._poll_s = float(poll_s)
        self._lock = threading.Lock()
        self._closed = False
        self._restarts_total = 0
        now = time.monotonic()
        self._slots: list[dict] = [
            {
                "proc": None,          # Popen | None (None = down)
                "spawned_at": now,
                "restarts": 0,         # respawns performed on this slot
                "fast_crashes": 0,     # consecutive deaths < fast_crash_s
                "next_spawn": 0.0,     # monotonic respawn-not-before
                "breaker_until": None,  # monotonic | None (open breaker)
            }
            for _ in range(max(1, int(n)))
        ]
        for slot in self._slots:
            self._spawn(slot)
        import weakref

        ref = weakref.ref(self)
        M_FE_CONFIGURED.set_function(
            lambda: len(s._slots) if (s := ref()) is not None else 0
        )
        M_FE_ALIVE.set_function(
            lambda: s.alive() if (s := ref()) is not None else 0
        )
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name="misaka-frontend-supervisor",
        )
        self._monitor.start()

    # --- pool surface -------------------------------------------------------

    def alive(self) -> int:
        with self._lock:
            return sum(
                1 for s in self._slots
                if s["proc"] is not None and s["proc"].poll() is None
            )

    def state(self) -> dict:
        """The /healthz + /status payload: never let the pool shrink
        silently — `degraded` is True whenever any slot is down or
        crash-loop-broken."""
        now = time.monotonic()
        with self._lock:
            alive = sum(
                1 for s in self._slots
                if s["proc"] is not None and s["proc"].poll() is None
            )
            broken = sum(
                1 for s in self._slots
                if s["breaker_until"] is not None and s["breaker_until"] > now
            )
            configured = len(self._slots)
            restarts = self._restarts_total
        return {
            "configured": configured,
            "alive": alive,
            "restarts_total": restarts,
            "breaker_open": broken,
            "degraded": alive < configured or broken > 0,
        }

    def close(self) -> None:
        with self._lock:
            self._closed = True
            procs = [s["proc"] for s in self._slots if s["proc"] is not None]
        for p in procs:
            try:
                p.terminate()
            except OSError:
                pass
        for p in procs:
            # reap: the monitor (the usual reaper via poll()) is exiting
            # on the same flag, and an unreaped child is a zombie for the
            # host process's whole remaining lifetime
            try:
                p.wait(timeout=2)
            except (OSError, subprocess.TimeoutExpired):
                pass
        self._monitor.join(timeout=2)

    # also quacks like the spawn_frontends return for existing teardown code
    def terminate(self) -> None:
        self.close()

    # --- the monitor --------------------------------------------------------

    def _spawn(self, slot: dict) -> None:
        slot["proc"] = subprocess.Popen(self._cmd)
        slot["spawned_at"] = time.monotonic()

    def _monitor_loop(self) -> None:
        while True:
            time.sleep(self._poll_s)
            # Decide under the lock, fork OUTSIDE it: state() serves the
            # /healthz probe and the metric gauges off the same lock, and
            # a probe must never stall behind a batch of fork/execs.
            due: list[dict] = []
            with self._lock:
                if self._closed:
                    return
                now = time.monotonic()
                for slot in self._slots:
                    proc = slot["proc"]
                    if proc is not None and proc.poll() is not None:
                        # death observed (poll() reaps the zombie)
                        lifetime = now - slot["spawned_at"]
                        slot["proc"] = None
                        fast = lifetime < self._fast_crash_s
                        slot["fast_crashes"] = (
                            slot["fast_crashes"] + 1 if fast else 0
                        )
                        if slot["fast_crashes"] >= self._breaker_threshold:
                            slot["breaker_until"] = now + self._breaker_reset_s
                            log.error(
                                "frontend worker crash loop (%d fast deaths, "
                                "last exit %s): circuit breaker open for "
                                "%.0fs", slot["fast_crashes"],
                                proc.returncode, self._breaker_reset_s,
                            )
                        else:
                            delay = self._backoff.delay_for(
                                slot["fast_crashes"] - 1
                            )
                            slot["next_spawn"] = now + delay
                            log.warning(
                                "frontend worker died (exit %s after %.1fs); "
                                "respawn in %.2fs",
                                proc.returncode, lifetime, delay,
                            )
                    if slot["proc"] is None:
                        if slot["breaker_until"] is not None:
                            if now < slot["breaker_until"]:
                                continue
                            # half-open: one retry; a fast death re-trips
                            slot["breaker_until"] = None
                            log.warning(
                                "frontend circuit breaker half-open: "
                                "retrying one respawn"
                            )
                        elif now < slot["next_spawn"]:
                            continue
                        due.append(slot)
            spawned: list[dict] = []
            for slot in due:
                # only this thread mutates slots, so the unlocked spawn
                # cannot race another writer — just the close() check below
                try:
                    self._spawn(slot)
                except OSError as e:
                    # fork/exec failed (fd or memory exhaustion — exactly
                    # the weather workers die in): the monitor must
                    # survive it, or the one failure mode it exists to
                    # absorb would disable the supervisor itself.  Retry
                    # on the backoff curve as if this were another fast
                    # crash.
                    log.error("frontend worker spawn failed (%s); "
                              "retrying with backoff", e)
                    with self._lock:
                        slot["fast_crashes"] += 1
                        slot["next_spawn"] = time.monotonic() + \
                            self._backoff.delay_for(slot["fast_crashes"] - 1)
                    continue
                spawned.append(slot)
            if not spawned:
                continue
            with self._lock:
                if self._closed:
                    # close() ran between the spawns and here; its
                    # terminate pass missed these brand-new procs
                    for slot in spawned:
                        try:
                            slot["proc"].terminate()
                            slot["proc"].wait(timeout=2)
                        except (OSError, subprocess.TimeoutExpired):
                            pass
                    return
                for slot in spawned:
                    slot["restarts"] += 1
                    self._restarts_total += 1
                    M_FE_RESTARTS.inc()
                    log.info(
                        "frontend worker respawned (pid %d, slot "
                        "restarts %d)", slot["proc"].pid, slot["restarts"],
                    )


def wait_ready(port: int, timeout: float = 10.0,
               host: str = "127.0.0.1") -> bool:
    """Poll until a TCP connect to the public port succeeds."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with socket.create_connection((host, port), timeout=1):
                return True
        except OSError:
            time.sleep(0.05)
    return False


def pick_free_port() -> int:
    """A free TCP port for the shared SO_REUSEPORT public bind (racy by
    nature, fine for benches and tests)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]




# --- native edge (r19): the C++ epoll frontend tier -------------------------
#
# The worker pool above broke the single-process GIL wall by SCALING
# CPython; the native edge removes CPython from the hot data path
# entirely.  native/frontend.cpp runs an epoll event loop (one thread
# per core slice, SO_REUSEPORT) that terminates HTTP/1.1 keep-alive and
# the MSK1 binary protocol on the hot routes and speaks the compute
# plane's frame protocol straight into the engine.  CPython remains the
# CONTROL plane: this supervisor builds the .so (utils/nativelib.py),
# starts the loop in-process via ctypes, and pushes auth-key digests,
# quota burst caps, and the program map as JSON snapshots — exactly the
# compile-and-push discipline specialize.py uses for programs.  Anything
# the native tier can't serve (admin routes, GETs, cold programs, bulk
# bodies) proxies to the CPython workers unchanged, and MISAKA_NATIVE_EDGE=0
# or ANY build/start failure falls back to the worker tier wholesale.

M_NE_UP = metrics.gauge(
    "misaka_native_edge_up",
    "1 while the C++ native edge tier is serving the public port "
    "(0/absent = CPython worker tier)",
)
M_NE_CONNS = metrics.gauge(
    "misaka_native_edge_connections_open",
    "Client connections currently open on the native edge",
)
M_NE_REQUESTS = metrics.counter(
    "misaka_native_edge_requests_total",
    "HTTP requests terminated by the native edge (served or proxied)",
)
M_NE_PLANE = metrics.counter(
    "misaka_native_edge_plane_frames_total",
    "Compute frames the native edge shipped directly over the plane "
    "(the no-GIL hot path)",
)
M_NE_PROXIED = metrics.counter(
    "misaka_native_edge_proxied_total",
    "Requests the native edge proxied to the CPython worker tier "
    "(admin routes, GETs, cold programs, bulk bodies)",
)
M_NE_LOCAL_REJECTS = metrics.counter(
    "misaka_native_edge_local_rejects_total",
    "Requests the native edge rejected from pushed edge state without "
    "a plane round-trip (401 unknown/missing key, 413 burst, shed "
    "cache, overload) — each also bills misaka_edge_rejected_total "
    "via frame metadata",
)

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)


class _FrontendNativeLib(NativeLib):
    """frontend.so builds from THREE units (frontend.cpp includes the
    msk_http/msk_frame codec headers), so staleness must hash all of
    them: the content hash is the sha256 of their concatenation in the
    fixed order (msk_http.hpp, msk_frame.hpp, frontend.cpp) — the
    Makefile's frontend rule computes the identical digest (`cat ... |
    sha256sum`), so `make native` artifacts and on-demand builds agree
    on identity."""

    _PARTS = ("msk_http.hpp", "msk_frame.hpp", "frontend.cpp")

    def _src_hash(self) -> str:
        h = hashlib.sha256()
        d = os.path.dirname(self._src)
        for part in self._PARTS:
            with open(os.path.join(d, part), "rb") as f:
                h.update(f.read())
        return h.hexdigest()[:16]


def _configure_frontend(lib: ctypes.CDLL) -> None:
    lib.msk_edge_start.restype = ctypes.c_int
    lib.msk_edge_start.argtypes = [ctypes.c_char_p]
    lib.msk_edge_port.restype = ctypes.c_int
    lib.msk_edge_port.argtypes = []
    lib.msk_edge_push_state.restype = ctypes.c_int
    lib.msk_edge_push_state.argtypes = [ctypes.c_char_p]
    lib.msk_edge_stats.restype = ctypes.c_int64
    lib.msk_edge_stats.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.msk_edge_spans.restype = ctypes.c_int64
    lib.msk_edge_spans.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.msk_edge_captures.restype = ctypes.c_int64
    lib.msk_edge_captures.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.msk_edge_stop.restype = None
    lib.msk_edge_stop.argtypes = []
    lib.msk_edge_last_error.restype = ctypes.c_char_p
    lib.msk_edge_last_error.argtypes = []


_FRONTEND_LIB = _FrontendNativeLib(
    os.path.join(_NATIVE_DIR, "frontend.cpp"),
    os.path.join(_NATIVE_DIR, "libmisaka_frontend.so"),
    _configure_frontend,
    so_env="MISAKA_FRONTEND_SO",
)

# the exporter reads spans through a module-level source so a dead
# supervisor never pins itself (weakref), and re-registration across
# server restarts in one process stays idempotent
_ACTIVE_NATIVE_EDGE = None  # weakref.ref[NativeFrontendSupervisor] | None


def _native_edge_spans() -> list:
    sup = _ACTIVE_NATIVE_EDGE() if _ACTIVE_NATIVE_EDGE is not None else None
    if sup is None:
        return []
    return sup.recent_spans()


class NativeFrontendSupervisor:
    """Build, start, and feed the in-process C++ edge.

    Lifecycle mirrors FrontendSupervisor's contract (state()/close(),
    `port` attribute) so app.py treats either tier uniformly.  The
    watcher thread is the push plane: every ~0.5s it re-snapshots the
    edge chain (KeyFile stats its mtime internally, so rotations
    propagate within a second), the registry's active program set, the
    trace/SLO arming flags, and the engine's current /healthz body, and
    pushes the bundle into C++ shared state iff it changed.  The same
    thread drains the native span ring into the flight-recorder plane
    and converts native counters into Prometheus series.

    Any failure in __init__ raises — app.py catches and falls back to
    the plain worker tier (the fallback ladder's load-bearing rung).
    """

    def __init__(
        self,
        *,
        port: int,
        proxy_port: int,
        plane_path: str,
        chain=None,
        registry=None,
        healthz_url: str | None = None,
        threads: int | None = None,
        max_conns: int | None = None,
        plane_conns: int = 2,
        environ=os.environ,
    ):
        # build-failure chaos point: the fallback ladder's own test
        # surface (MISAKA_FAULTS=edge_native_build) — fires exactly
        # where a missing toolchain would
        if faults.armed() and faults.fire("edge_native_build") is not None:
            raise RuntimeError("native edge build failed (injected fault)")
        lib = _FRONTEND_LIB.load()
        if lib is None:
            raise RuntimeError(
                "native edge unavailable: frontend.so failed to "
                "build/load (no g++?)"
            )
        self._lib = lib
        self._chain = chain if chain is not None else edge_mod.current()
        self._registry = registry
        self._healthz_url = healthz_url
        self._environ = environ
        if threads is None:
            threads = int(
                environ.get("MISAKA_NATIVE_EDGE_THREADS", "")
                or min(8, max(2, (os.cpu_count() or 2) // 2))
            )
        if max_conns is None:
            max_conns = int(
                environ.get("MISAKA_NATIVE_EDGE_MAX_CONNS", "") or 4096
            )
        config = {
            "port": int(port),
            "threads": int(threads),
            "max_conns": int(max_conns),
            "plane_conns": int(plane_conns),
            "plane_depth_max": int(
                environ.get("MISAKA_PLANE_DEPTH_MAX", "") or 256
            ),
            "proxy_port": int(proxy_port),
            "proxy_host": "127.0.0.1",
            "max_body": int(
                environ.get("MISAKA_MAX_BODY", "") or 64 * 1024 * 1024
            ),
            "plane_body_limit": MAX_FRAME_VALUES * 2,
            "plane_timeout_s": float(
                environ.get("MISAKA_PLANE_TIMEOUT_S", "") or 30.0
            ),
            # the C++ tier dials AF_UNIX only: pick the first unix plane
            # (in a mixed fleet the Python router owns the TCP peers).
            # An all-TCP plane list rides the normal fallback ladder —
            # the Python tier speaks TCP+mTLS.
            "plane_path": next(
                (p for p in plane_path.split(",")
                 if p and parse_plane_addr(p)[0] == "unix"),
                None,
            ),
        }
        if config["plane_path"] is None:
            raise RuntimeError(
                "native edge unavailable: no unix plane in "
                f"{plane_path!r} (the C++ tier does not speak the TCP "
                "plane transport)"
            )
        secret = edge_mod.plane_secret(environ)
        if secret is not None:
            config["handshake_hex"] = edge_mod.plane_handshake(secret).hex()
        rc = lib.msk_edge_start(json.dumps(config).encode())
        if rc != 0:
            raise RuntimeError(
                "native edge failed to start: "
                + (lib.msk_edge_last_error() or b"?").decode("utf-8", "replace")
            )
        self.port = int(lib.msk_edge_port())
        self._lock = threading.Lock()
        self._closed = False
        self._last_push: str | None = None
        self._healthz_body: bytes | None = None
        self._healthz_ctype: str | None = None
        self._span_buf: deque = deque(maxlen=4096)
        self._last_stats: dict = {}
        try:
            self._push(force=True)
        except Exception:
            # the C++ loop is already live: a failure ANYWHERE between
            # start and a fully-armed supervisor must release it, or the
            # in-process singleton wedges every later boot attempt
            lib.msk_edge_stop()
            raise

        import weakref

        global _ACTIVE_NATIVE_EDGE
        _ACTIVE_NATIVE_EDGE = weakref.ref(self)
        tracespan.register_tier_source(_native_edge_spans)
        ref = weakref.ref(self)
        M_NE_UP.set_function(
            lambda: 0 if (s := ref()) is None or s._closed else 1
        )
        M_NE_CONNS.set_function(
            lambda: (
                s._last_stats.get("conns_open", 0)
                if (s := ref()) is not None else 0
            )
        )
        self._watcher = threading.Thread(
            target=self._watch_loop, daemon=True,
            name="misaka-native-edge-supervisor",
        )
        self._watcher.start()
        log.info(
            "native edge serving :%d (%d threads, proxy -> 127.0.0.1:%d, "
            "plane %s)", self.port, threads, proxy_port, config["plane_path"],
        )

    # --- push plane ---------------------------------------------------------

    def _snapshot(self) -> dict:
        state = edge_mod.native_edge_state(self._chain)
        reg = self._registry
        if reg is not None:
            try:
                state["programs"] = sorted(reg._entries.keys())
            except Exception:
                state["programs"] = []
        state["trace_enabled"] = tracespan.enabled()
        state["trace_sample"] = float(getattr(tracespan, "_SAMPLE", 1.0))
        state["slo_armed"] = bool(slo.armed())
        # the capture plane rides the same push: the C++ edge records its
        # locally-terminated rejects (shed/401/413/overload) only while
        # the engine-side recorder is armed, pre-applying the sample rate
        state["capture_enabled"] = capture_mod.recording()
        state["capture_sample"] = capture_mod.sample_rate()
        if self._healthz_body is not None:
            state["healthz_body"] = self._healthz_body.decode(
                "utf-8", "replace"
            )
            state["healthz_ctype"] = self._healthz_ctype or "application/json"
        return state

    def _push(self, force: bool = False) -> None:
        js = json.dumps(self._snapshot(), sort_keys=True)
        if not force and js == self._last_push:
            return
        if self._lib.msk_edge_push_state(js.encode()) != 0:
            log.warning(
                "native edge rejected state push: %s",
                (self._lib.msk_edge_last_error() or b"?").decode(
                    "utf-8", "replace"),
            )
            return
        self._last_push = js

    def _fetch_healthz(self) -> None:
        if self._healthz_url is None:
            return
        import urllib.request

        try:
            with urllib.request.urlopen(self._healthz_url, timeout=2) as r:
                self._healthz_body = r.read()
                self._healthz_ctype = r.headers.get(
                    "Content-Type", "application/json"
                )
        except Exception:
            pass  # engine mid-boot or draining: keep the last snapshot

    # --- observability ------------------------------------------------------

    def _read_stats(self) -> dict:
        buf = ctypes.create_string_buffer(1024)
        n = self._lib.msk_edge_stats(buf, len(buf))
        if n <= 0:
            return {}
        try:
            return json.loads(buf.raw[:n].decode())
        except ValueError:
            return {}

    def _pump_metrics(self) -> None:
        stats = self._read_stats()
        if not stats:
            return
        prev = self._last_stats
        for field, counter in (
            ("requests", M_NE_REQUESTS),
            ("plane", M_NE_PLANE),
            ("proxied", M_NE_PROXIED),
        ):
            d = stats.get(field, 0) - prev.get(field, 0)
            if d > 0:
                counter.inc(d)
        rejects = sum(
            stats.get(f, 0) for f in
            ("local_401", "local_413", "shed_hits", "overload")
        ) - sum(
            prev.get(f, 0) for f in
            ("local_401", "local_413", "shed_hits", "overload")
        )
        if rejects > 0:
            M_NE_LOCAL_REJECTS.inc(rejects)
        self._last_stats = stats

    def _drain_spans(self) -> None:
        cap = 256 * 1024
        for _ in range(2):
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.msk_edge_spans(buf, cap)
            if n >= 0:
                break
            cap *= 4
        else:
            return
        try:
            recs = json.loads(buf.raw[:n].decode("utf-8", "replace"))
        except ValueError:
            return
        with self._lock:
            for r in recs:
                attrs = {"_lane": r.get("lane") or "edge"}
                trace = r.get("trace")
                if trace:
                    attrs["trace_ids"] = [trace]
                self._span_buf.append(tracespan.Span(
                    r.get("name", "frontend.edge"),
                    float(r.get("start", 0.0)),
                    float(r.get("dur", 0.0)),
                    attrs,
                ))

    def _drain_captures(self) -> None:
        """Drain the C++ edge's capture rows (locally-terminated
        rejects) into the engine-side capture ring.  The edge applies
        MISAKA_CAPTURE_SAMPLE itself, so rows ingest pre-sampled."""
        if not capture_mod.RECORDING:
            return
        cap = 256 * 1024
        for _ in range(2):
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.msk_edge_captures(buf, cap)
            if n >= 0:
                break
            cap *= 4
        else:
            return
        if n == 0:
            return
        try:
            payload = json.loads(buf.raw[:n].decode("utf-8", "replace"))
        except ValueError:
            return
        rows = payload.get("records") or []
        if rows:
            capture_mod.ingest("edge", rows, pre_sampled=True)

    def recent_spans(self, window_s: float = 15.0) -> list:
        """Native per-request spans for the Perfetto export (tier
        source): drain the C++ ring into a bounded buffer, return the
        recent window.  attrs carry `_lane` (per-edge-thread timelines)
        and `trace_ids` (the request trace each span served), so one
        X-Misaka-Trace ID still renders a single timeline from
        http.parse through the native edge down to the engine."""
        self._drain_spans()
        now = time.monotonic()
        with self._lock:
            return [s for s in self._span_buf if now - s.start <= window_s]

    def state(self) -> dict:
        """The /healthz `native_edge` block."""
        stats = self._read_stats() or dict(self._last_stats)
        stats["up"] = not self._closed
        return stats

    # --- lifecycle ----------------------------------------------------------

    def _watch_loop(self) -> None:
        tick = 0
        while not self._closed:
            time.sleep(0.5)
            if self._closed:
                return
            try:
                if tick % 2 == 0:
                    self._fetch_healthz()
                self._push()
                self._pump_metrics()
                self._drain_spans()
                self._drain_captures()
            except Exception:
                log.exception("native edge watcher tick failed")
            tick += 1

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._lib.msk_edge_stop()


if __name__ == "__main__":
    sys.exit(frontend_main())
